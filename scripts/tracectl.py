#!/usr/bin/env python3
"""tracectl — summarize flight-recorder dumps into per-stage latency
tables.

Input: the JSON served by a node's ``/debug/traces`` endpoint (or a
flight auto-dump file written on wedge/breaker-trip) — either shape is
accepted: ``{"spans": [...]}`` wrappers or a bare span list.

    python scripts/tracectl.py dump.json            # per-stage table
    curl -s localhost:26657/debug/traces | python scripts/tracectl.py -
    python scripts/tracectl.py dump.json --trace 42 # one trace, in order
    python scripts/tracectl.py dump.json --subsystem hub

The per-stage table answers the ROADMAP question ("where did this vote
spend its time?") in aggregate: count, p50, p90, p99, max, and total
time per (subsystem, name) stage. ``--trace`` prints one end-to-end
trace's spans in start order so a single message's life is readable
top to bottom.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_spans(path: str) -> list[dict]:
    if path == "-":
        data = json.load(sys.stdin)
    else:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    if isinstance(data, dict):
        data = data.get("spans", [])
    if not isinstance(data, list):
        raise ValueError("expected a span list or a {'spans': [...]} object")
    return data


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def summarize(spans: list[dict]) -> str:
    """Per-stage latency table (the shape the acceptance run reads)."""
    stages: dict[str, list[float]] = {}
    for s in spans:
        key = f"{s.get('subsystem', '?')}.{s.get('name', '?')}"
        stages.setdefault(key, []).append(float(s.get("duration_ms", 0.0)))
    if not stages:
        return "no spans"
    rows = []
    for key, vals in stages.items():
        vals.sort()
        rows.append(
            (
                key,
                len(vals),
                _pct(vals, 0.50),
                _pct(vals, 0.90),
                _pct(vals, 0.99),
                vals[-1],
                sum(vals),
            )
        )
    rows.sort(key=lambda r: -r[6])  # biggest total time first
    header = f"{'stage':<28} {'count':>7} {'p50ms':>9} {'p90ms':>9} {'p99ms':>9} {'maxms':>9} {'totalms':>10}"
    lines = [header, "-" * len(header)]
    for key, n, p50, p90, p99, mx, total in rows:
        lines.append(
            f"{key:<28} {n:>7} {p50:>9.3f} {p90:>9.3f} {p99:>9.3f} "
            f"{mx:>9.3f} {total:>10.2f}"
        )
    return "\n".join(lines)


def render_trace(spans: list[dict], trace_id: int) -> str:
    """One trace's spans in start order — a message's life, top down."""
    mine = [s for s in spans if s.get("trace_id") == trace_id]
    if not mine:
        return f"no spans for trace {trace_id}"
    mine.sort(key=lambda s: (s.get("start_s", 0.0), -s.get("duration_ms", 0.0)))
    t0 = mine[0].get("start_s", 0.0)
    lines = [f"trace {trace_id} ({len(mine)} spans):"]
    for s in mine:
        at = (s.get("start_s", 0.0) - t0) * 1e3
        attrs = s.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  +{at:9.3f}ms {s.get('subsystem','?')}.{s.get('name','?'):<18} "
            f"{s.get('duration_ms', 0.0):9.3f}ms  {extra}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="dump file path, or - for stdin")
    ap.add_argument("--subsystem", help="only this subsystem's spans")
    ap.add_argument("--trace", type=int, help="print one trace in start order")
    args = ap.parse_args(argv)
    try:
        spans = load_spans(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"tracectl: cannot read {args.dump}: {e}", file=sys.stderr)
        return 2
    if args.subsystem:
        spans = [s for s in spans if s.get("subsystem") == args.subsystem]
    if args.trace is not None:
        print(render_trace(spans, args.trace))
    else:
        print(summarize(spans))
    return 0


if __name__ == "__main__":
    sys.exit(main())
