#!/usr/bin/env python3
"""tracectl — summarize flight-recorder dumps into per-stage latency
tables.

Input: the JSON served by a node's ``/debug/traces`` endpoint (or a
flight auto-dump file written on wedge/breaker-trip) — either shape is
accepted: ``{"spans": [...]}`` wrappers or a bare span list.

    python scripts/tracectl.py dump.json            # per-stage table
    curl -s localhost:26657/debug/traces | python scripts/tracectl.py -
    python scripts/tracectl.py dump.json --trace 42 # one trace, in order
    python scripts/tracectl.py dump.json --subsystem hub
    python scripts/tracectl.py dump.json --per-device  # mesh shard table

The per-stage table answers the ROADMAP question ("where did this vote
spend its time?") in aggregate: count, p50, p90, p99, max, and total
time per (subsystem, name) stage. ``--trace`` prints one end-to-end
trace's spans in start order so a single message's life is readable
top to bottom.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_spans(path: str) -> list[dict]:
    if path == "-":
        data = json.load(sys.stdin)
    else:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    if isinstance(data, dict):
        data = data.get("spans", [])
    if not isinstance(data, list):
        raise ValueError("expected a span list or a {'spans': [...]} object")
    return data


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def summarize(spans: list[dict]) -> str:
    """Per-stage latency table (the shape the acceptance run reads)."""
    stages: dict[str, list[float]] = {}
    for s in spans:
        key = f"{s.get('subsystem', '?')}.{s.get('name', '?')}"
        stages.setdefault(key, []).append(float(s.get("duration_ms", 0.0)))
    if not stages:
        return "no spans"
    rows = []
    for key, vals in stages.items():
        vals.sort()
        rows.append(
            (
                key,
                len(vals),
                _pct(vals, 0.50),
                _pct(vals, 0.90),
                _pct(vals, 0.99),
                vals[-1],
                sum(vals),
            )
        )
    rows.sort(key=lambda r: -r[6])  # biggest total time first
    header = f"{'stage':<28} {'count':>7} {'p50ms':>9} {'p90ms':>9} {'p99ms':>9} {'maxms':>9} {'totalms':>10}"
    lines = [header, "-" * len(header)]
    for key, n, p50, p90, p99, mx, total in rows:
        lines.append(
            f"{key:<28} {n:>7} {p50:>9.3f} {p90:>9.3f} {p99:>9.3f} "
            f"{mx:>9.3f} {total:>10.2f}"
        )
    return "\n".join(lines)


def per_device(spans: list[dict]) -> str:
    """Per-device shard-occupancy table from the hub.dispatch spans'
    mesh attrs (devices=[ids], shards=[real-signature counts]): how
    evenly the mesh is fed, straight from a flight dump."""
    dispatches: dict = {}
    sigs: dict = {}
    total_sigs = 0
    for s in spans:
        if s.get("subsystem") != "hub" or s.get("name") != "dispatch":
            continue
        attrs = s.get("attrs") or {}
        devices, shards = attrs.get("devices"), attrs.get("shards")
        if not devices or shards is None:
            continue
        for dev, n in zip(devices, shards):
            dispatches[dev] = dispatches.get(dev, 0) + 1
            sigs[dev] = sigs.get(dev, 0) + int(n)
            total_sigs += int(n)
    if not dispatches:
        return "no sharded hub.dispatch spans (single-device or CPU route)"
    header = f"{'device':>8} {'dispatches':>11} {'sigs':>10} {'share':>7} {'sigs/dispatch':>14}"
    lines = [header, "-" * len(header)]
    for dev in sorted(dispatches):
        n, total = dispatches[dev], sigs[dev]
        share = total / total_sigs if total_sigs else 0.0
        lines.append(
            f"{dev!s:>8} {n:>11} {total:>10} {share:>6.1%} {total / n:>14.1f}"
        )
    return "\n".join(lines)


def render_trace(spans: list[dict], trace_id: int) -> str:
    """One trace's spans in start order — a message's life, top down."""
    mine = [s for s in spans if s.get("trace_id") == trace_id]
    if not mine:
        return f"no spans for trace {trace_id}"
    mine.sort(key=lambda s: (s.get("start_s", 0.0), -s.get("duration_ms", 0.0)))
    t0 = mine[0].get("start_s", 0.0)
    lines = [f"trace {trace_id} ({len(mine)} spans):"]
    for s in mine:
        at = (s.get("start_s", 0.0) - t0) * 1e3
        attrs = s.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  +{at:9.3f}ms {s.get('subsystem','?')}.{s.get('name','?'):<18} "
            f"{s.get('duration_ms', 0.0):9.3f}ms  {extra}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="dump file path, or - for stdin")
    ap.add_argument("--subsystem", help="only this subsystem's spans")
    ap.add_argument("--trace", type=int, help="print one trace in start order")
    ap.add_argument(
        "--per-device",
        action="store_true",
        help="per-device mesh shard occupancy from hub.dispatch spans",
    )
    args = ap.parse_args(argv)
    try:
        spans = load_spans(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"tracectl: cannot read {args.dump}: {e}", file=sys.stderr)
        return 2
    if args.subsystem:
        spans = [s for s in spans if s.get("subsystem") == args.subsystem]
    if args.trace is not None:
        print(render_trace(spans, args.trace))
    elif args.per_device:
        print(per_device(spans))
    else:
        print(summarize(spans))
    return 0


if __name__ == "__main__":
    sys.exit(main())
