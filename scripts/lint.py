#!/usr/bin/env python3
"""tmtlint driver — run the project's AST invariant analyzers.

Usage:
    python scripts/lint.py                    # whole tree (tier-1 gate)
    python scripts/lint.py --rule clock-discipline tendermint_tpu/consensus
    python scripts/lint.py --changed          # only git-modified files
    python scripts/lint.py --json             # machine output (+ wall time)
    python scripts/lint.py --list-rules

Exit status: 0 clean, 1 findings, 2 usage/internal error.

The rules, pragma syntax (`# tmtlint: allow[rule] -- reason`) and the
checked-in allowlist live in tendermint_tpu/tools/lint/; see the README
"Static analysis" section for the invariant behind each rule.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tendermint_tpu.tools.lint import (  # noqa: E402
    ALL_RULES,
    DEFAULT_ALLOWLIST,
    RULES_BY_ID,
    Allowlist,
    lint_paths,
)

DEFAULT_PATHS = ["tendermint_tpu", "scripts", "tests"]


def changed_files() -> list[str]:
    """Working-tree changes vs HEAD plus untracked files — the fast
    pre-commit surface."""
    out = subprocess.run(
        ["git", "diff", "--name-only", "HEAD"],
        cwd=REPO,
        capture_output=True,
        text=True,
        check=True,
    ).stdout.splitlines()
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=REPO,
        capture_output=True,
        text=True,
        check=True,
    ).stdout.splitlines()
    return [
        p
        for p in dict.fromkeys(out + untracked)
        if p.endswith(".py") and os.path.exists(os.path.join(REPO, p))
    ]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help=f"files/dirs (default: {DEFAULT_PATHS})")
    ap.add_argument(
        "--rule",
        action="append",
        default=[],
        metavar="ID",
        help="run only these rule ids (repeatable)",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--changed",
        action="store_true",
        help="lint only files modified vs HEAD (plus untracked), "
        "restricted to the positional paths (default: the tier-1 scan "
        "surface, so pre-commit and the gate agree)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--allowlist",
        default=DEFAULT_ALLOWLIST,
        help="path to the allowlist JSON (default: checked-in)",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            scope = ", ".join(r.scope) if r.scope else "everywhere"
            print(f"{r.id:22s} [{'/'.join(r.profiles)}] {r.doc}")
            print(f"{'':22s} scope: {scope}")
        return 0

    rules = list(ALL_RULES)
    if args.rule:
        unknown = [r for r in args.rule if r not in RULES_BY_ID]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            print(f"known: {', '.join(sorted(RULES_BY_ID))}", file=sys.stderr)
            return 2
        rules = [RULES_BY_ID[r] for r in args.rule]

    # a typo'd path must be a usage error, not a 0-file "clean" — the
    # silent-miss class this linter exists to prevent
    missing = [
        p
        for p in args.paths
        if not os.path.exists(p if os.path.isabs(p) else os.path.join(REPO, p))
    ]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    if args.changed:
        # intersect with the gate's scan surface (or the named paths):
        # pre-commit must never fail on files the tier-1 gate ignores,
        # or pass on files it checks
        scope = [
            os.path.relpath(p, REPO).replace(os.sep, "/")
            if os.path.isabs(p)
            else p.rstrip("/")
            for p in (args.paths or DEFAULT_PATHS)
        ]
        paths = [
            f
            for f in changed_files()
            if any(f == s or f.startswith(s + "/") for s in scope)
        ]
        if not paths:
            if args.json:
                print(json.dumps({"findings": [], "files_scanned": 0,
                                  "rules": [r.id for r in rules],
                                  "elapsed_s": 0.0, "clean": True}))
            else:
                print("tmtlint: no changed python files")
            return 0
    else:
        paths = args.paths or DEFAULT_PATHS

    allowlist = Allowlist.load(args.allowlist)
    t0 = time.monotonic()
    # bad-pragma findings belong to the full gate; a single-rule run
    # (the shims, --rule spot checks) reports only its own rule
    findings, n_files = lint_paths(
        paths, rules, allowlist, REPO, report_pragma_errors=not args.rule
    )
    elapsed = time.monotonic() - t0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "files_scanned": n_files,
                    "rules": [r.id for r in rules],
                    "elapsed_s": round(elapsed, 3),
                    "clean": not findings,
                },
                indent=2,
            )
        )
        return 1 if findings else 0

    if not findings:
        print(
            f"tmtlint: clean — {n_files} files, {len(rules)} rules, "
            f"{elapsed * 1e3:.0f} ms"
        )
        return 0
    print(
        f"tmtlint: {len(findings)} finding(s) across {n_files} files "
        f"({elapsed * 1e3:.0f} ms):",
        file=sys.stderr,
    )
    for f in findings:
        print(f"  {f.render()}", file=sys.stderr)
        if f.snippet:
            print(f"      {f.snippet}", file=sys.stderr)
    print(
        "\nfix the call site, or annotate an intentional one with\n"
        "  # tmtlint: allow[rule-id] -- reason\n"
        "(see README 'Static analysis' for each rule's invariant)",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
