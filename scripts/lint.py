#!/usr/bin/env python3
"""Legacy alias — the tmtlint driver moved to `scripts/tmtlint`
(tendermint_tpu/tools/lint/cli.py) when the suite grew the
interprocedural and wire-schema passes. Kept so existing wiring and
docs referencing `scripts/lint.py` keep working; both names run the
same `main()` — one code path, no drift.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.tools.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
