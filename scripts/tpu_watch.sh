#!/bin/bash
# Poll the axon TPU tunnel; when it answers, run the headline benchmark
# once and record the JSON + diagnostics in the repo (TPU_RUN.json /
# TPU_RUN.log). The analog of keeping a long-running perf canary pointed
# at scarce hardware: the tunnel flaps, the watcher catches the window.
#
# Usage: scripts/tpu_watch.sh [max_attempts] [poll_seconds]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-600}
POLL=${2:-45}
LOG=${TMTPU_WATCH_LOG:-TPU_RUN.log}
OUT=${TMTPU_WATCH_OUT:-TPU_RUN.json}
for i in $(seq 1 "$MAX"); do
  if timeout 90 python -u -c "
import threading, sys
import jax
res={}
def p():
    try: res['d']=jax.devices()
    except Exception as e: res['e']=e
t=threading.Thread(target=p,daemon=True); t.start(); t.join(75)
sys.exit(0 if 'd' in res else 1)
" 2>/dev/null; then
    echo "$(date +%H:%M:%S) tunnel up; running bench.py" >> "$LOG"
    timeout 3000 python -u bench.py > "$OUT" 2>> "$LOG"
    echo "$(date +%H:%M:%S) bench rc=$? -> $OUT" >> "$LOG"
    exit 0
  fi
  echo "$(date +%H:%M:%S) tunnel down ($i/$MAX)" >> "$LOG"
  sleep "$POLL"
done
echo "$(date +%H:%M:%S) gave up after $MAX attempts" >> "$LOG"
exit 1
