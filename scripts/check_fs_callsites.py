#!/usr/bin/env python3
"""Shim — the fs-discipline lint now lives in the tmtlint framework.

Equivalent to `python scripts/lint.py --rule fs-discipline`; kept so
existing tier-1 wiring and docs referencing this script keep working.
The AST analyzer (tendermint_tpu/tools/lint/rules/chokepoint_rules.py)
replaces the old regex: binary write modes are read off the actual
`open()` argument, `self.fs.open(...)` is structurally exempt, and the
allowlist moved to tendermint_tpu/tools/lint/allowlist.json.

Exit status: 0 clean, 1 violations.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lint import main  # noqa: E402  (scripts/lint.py)

if __name__ == "__main__":
    # scoped to the rule's scan surface (the old regex lint's SCAN_PREFIXES)
    sys.exit(
        main(
            [
                "--rule",
                "fs-discipline",
                "tendermint_tpu/consensus/wal.py",
                "tendermint_tpu/store",
                "tendermint_tpu/state",
            ]
        )
    )
