#!/usr/bin/env python3
"""Lint: storage-layer writes must go through the injectable I/O layer
(`libs/chaosfs.FS`) — no raw binary `open(..)` writes, `os.write`,
`os.fsync`, or `os.replace/rename` in the WAL/store/state write path.

The crash-consistency guarantees (torn-write/lost-fsync/ENOSPC recovery,
tests/test_crash_recovery.py) only hold for I/O the chaos-fs layer can
see: a new raw `open(path, "ab")` in the WAL or stores silently escapes
fault injection AND the durable-watermark crash model — the matrix keeps
passing while the real crash path regresses. This lint (wired into
tier-1 via tests/test_tools.py, like check_verify_callsites.py) makes
that a hard failure.

Scanned: tendermint_tpu/consensus/wal.py, tendermint_tpu/store/**,
tendermint_tpu/state/**. Allowlisted:
  * tendermint_tpu/libs/chaosfs.py — IS the I/O layer;
  * tendermint_tpu/store/db.py — sqlite3 owns its file descriptors; DB
    fault injection happens at the `ChaosDB` wrapper, not under sqlite.

Exit status: 0 clean, 1 violations (printed as file:line: text).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCAN_PREFIXES = (
    "tendermint_tpu/consensus/wal.py",
    "tendermint_tpu/store/",
    "tendermint_tpu/state/",
)

ALLOWLIST_PREFIXES = (
    "tendermint_tpu/store/db.py",  # sqlite3-owned descriptors; see ChaosDB
)

# binary write/append/update opens + the os-level mutation calls the FS
# layer wraps. Read-only opens ("rb") are allowed: bit-rot injection only
# matters where the caller can be handed an FS (the WAL takes one).
PATTERNS = (
    # bare builtin open() with a binary write/append/update mode — a
    # leading `.` (self.fs.open, chaosfs-layer calls) is exempt
    re.compile(r"""(?<![\w.])open\s*\([^)]*,\s*["'][^"']*[wax+][^"']*b[^"']*["']"""),
    re.compile(r"""(?<![\w.])open\s*\([^)]*,\s*["'][^"']*b[^"']*[wax+][^"']*["']"""),
    re.compile(r"\bos\s*\.\s*(write|fsync|open|rename|replace|remove|truncate)\s*\("),
)


def find_violations() -> list[tuple[str, int, str]]:
    out = []
    for prefix in SCAN_PREFIXES:
        root = os.path.join(REPO, prefix)
        paths = [root] if root.endswith(".py") else [
            os.path.join(dp, fn)
            for dp, _dn, fns in os.walk(root)
            for fn in sorted(fns)
            if fn.endswith(".py")
        ]
        for path in paths:
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if any(rel.startswith(p) for p in ALLOWLIST_PREFIXES):
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if any(p.search(line) for p in PATTERNS):
                        out.append((rel, lineno, line.strip()))
    return out


def main() -> int:
    violations = find_violations()
    if not violations:
        print("fs-callsite lint: clean")
        return 0
    print(
        "fs-callsite lint: %d raw storage I/O call site(s) outside the "
        "injectable chaos-fs layer:" % len(violations),
        file=sys.stderr,
    )
    for rel, lineno, text in violations:
        print(f"  {rel}:{lineno}: {text}", file=sys.stderr)
    print(
        "route these through the injectable libs/chaosfs.FS (self.fs.open/"
        "fsync/rename/...), or extend the allowlist with a reason.",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
