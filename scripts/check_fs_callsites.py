#!/usr/bin/env python3
"""Retired shim — the fs-discipline checks live in tmtlint.

This predates the PR 4 analyzer framework (it was a regex grep over
storage files) and is now an alias for::

    scripts/tmtlint --rule fs-discipline --rule transitive-fs tendermint_tpu

The AST rules replace everything the regex did and more: binary write
modes are read off the actual `open()` argument, `self.fs.open(...)` is
structurally exempt, the allowlist lives in
tendermint_tpu/tools/lint/allowlist.json — and `transitive-fs` also
catches a storage path reaching a raw write through a helper in
another file, which no single-file scan can see. That is why the scan
surface is the WHOLE package, not the old regex's storage-path list: a
call graph restricted to storage files has no edges into the libs/
helper the transitive rule exists to follow.

Exit status: 0 clean, 1 violations.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.tools.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(
        main(["--rule", "fs-discipline", "--rule", "transitive-fs", "tendermint_tpu"])
    )
