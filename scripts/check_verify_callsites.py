#!/usr/bin/env python3
"""Retired shim — the verify-chokepoint checks live in tmtlint.

This predates the PR 4 analyzer framework (it was a regex grep for
`verify_signature` call sites) and is now an alias for::

    scripts/tmtlint --rule verify-chokepoint --rule transitive-verify \
        tendermint_tpu

The AST rules replace everything the regex did and more: actual
`*.verify_signature(...)` call expressions are resolved (interface
`def`s never need special-casing), the allowlist lives in
tendermint_tpu/tools/lint/allowlist.json — and `transitive-verify`
also catches a coroutine reaching the hub's sync facade through a
helper chain in other files, which the per-file scan provably misses.

Exit status: 0 clean, 1 violations.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.tools.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(
        main(
            [
                "--rule",
                "verify-chokepoint",
                "--rule",
                "transitive-verify",
                "tendermint_tpu",
            ]
        )
    )
