#!/usr/bin/env python3
"""Lint: no direct `*.verify_signature(...)` call sites outside the
crypto/verify-hub allowlist.

Every signature check in the node must route through the VerifyHub
chokepoints (crypto/verify_hub.verify_one / verify_many or the
validation _CommitVerifier shim) so it participates in micro-batching
and gossip-duplicate dedup. A new direct call site silently bypasses
batching — this lint (wired into tier-1 via tests/test_tools.py) makes
that a hard failure instead of a perf regression nobody notices.

Allowlisted:
  * tendermint_tpu/crypto/** — the backends and the hub itself;
  * tendermint_tpu/p2p/secret.py — the handshake challenge: one
    latency-critical signature before the peer even exists, verified
    inline by design;
  * tendermint_tpu/tools/signer_harness.py — external-signer
    conformance harness; it deliberately verifies exactly what the
    remote signer returned, with no caching layer in between.

Exit status: 0 clean, 1 violations (printed as file:line: text).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "tendermint_tpu")

ALLOWLIST_PREFIXES = (
    "tendermint_tpu/crypto/",
    "tendermint_tpu/p2p/secret.py",
    "tendermint_tpu/tools/signer_harness.py",
)

CALL_RE = re.compile(r"\.\s*verify_signature\s*\(")
DEF_RE = re.compile(r"def\s+verify_signature\s*\(")


def find_violations() -> list[tuple[str, int, str]]:
    out = []
    for dirpath, _dirnames, filenames in os.walk(PACKAGE):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if any(rel.startswith(p) for p in ALLOWLIST_PREFIXES):
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if DEF_RE.search(line):
                        continue  # interface definitions are fine
                    if CALL_RE.search(line):
                        out.append((rel, lineno, line.strip()))
    return out


def main() -> int:
    violations = find_violations()
    if not violations:
        print("verify-callsite lint: clean")
        return 0
    print(
        "verify-callsite lint: %d direct verify_signature call site(s) "
        "outside the VerifyHub allowlist:" % len(violations),
        file=sys.stderr,
    )
    for rel, lineno, text in violations:
        print(f"  {rel}:{lineno}: {text}", file=sys.stderr)
    print(
        "route these through crypto/verify_hub.verify_one (or the "
        "validation batch shim), or extend the allowlist with a reason.",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
