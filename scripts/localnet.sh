#!/bin/bash
# Spin up an N-validator localnet from scratch, drive transactions at it,
# and assert the chain advances with converged app hashes — the
# one-command smoke the reference ships as `make localnet-start`
# (docker-compose) — here plain processes on one host.
#
# Usage: scripts/localnet.sh [N] [TARGET_HEIGHT] [BASE_PORT]
set -euo pipefail
cd "$(dirname "$0")/.."

N=${1:-4}
TARGET=${2:-5}
BASE_PORT=${3:-27656}
DIR=$(mktemp -d /tmp/tmtpu-localnet.XXXXXX)
PY=${PYTHON:-python}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

cleanup() {
  kill "${PIDS[@]}" 2>/dev/null || true
  wait 2>/dev/null || true
}
trap cleanup EXIT

echo "==> generating $N-validator testnet in $DIR"
$PY -m tendermint_tpu.cli testnet -v "$N" -o "$DIR" --base-port "$BASE_PORT" >/dev/null

PIDS=()
for i in $(seq 0 $((N - 1))); do
  $PY -m tendermint_tpu.cli --home "$DIR/node$i" start >"$DIR/node$i.log" 2>&1 &
  PIDS+=($!)
done
echo "==> started ${#PIDS[@]} nodes (logs in $DIR)"

rpc_port=$((BASE_PORT + 1))
status() {
  curl -s "http://127.0.0.1:$rpc_port/status" 2>/dev/null || true
}

echo "==> sending txs + waiting for height >= $TARGET"
for t in $(seq 1 120); do
  curl -s "http://127.0.0.1:$rpc_port/broadcast_tx_async?tx=%22k$t=v$t%22" >/dev/null 2>&1 || true
  H=$(status | $PY -c 'import json,sys
try: print(json.load(sys.stdin)["result"]["sync_info"]["latest_block_height"])
except Exception: print(0)')
  if [ "${H:-0}" -ge "$TARGET" ]; then
    echo "==> height $H reached"
    # cross-check app hashes at a common height across all nodes;
    # a node still gossip-lagged behind TARGET gets retried — only an
    # ACTUAL hash mismatch is divergence
    REF=""
    for i in $(seq 0 $((N - 1))); do
      p=$((BASE_PORT + 2 * i + 1))
      AH="?"
      for _try in $(seq 1 30); do
        AH=$(curl -s "http://127.0.0.1:$p/block?height=$TARGET" | $PY -c 'import json,sys
try: print(json.load(sys.stdin)["result"]["block"]["header"]["app_hash"])
except Exception: print("?")')
        [ "$AH" != "?" ] && break
        sleep 1
      done
      echo "    node$i app_hash@$TARGET = $AH"
      [ "$AH" = "?" ] && { echo "node$i never served block $TARGET"; exit 1; }
      [ -z "$REF" ] && REF="$AH"
      [ "$AH" = "$REF" ] || { echo "APP HASH DIVERGENCE"; exit 1; }
    done
    echo "==> localnet OK: $N nodes converged at height $TARGET"
    exit 0
  fi
  sleep 1
done
echo "localnet did not reach height $TARGET; last status:"
status
exit 1
