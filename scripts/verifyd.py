#!/usr/bin/env python3
"""Standalone driver for the verification sidecar (crypto/verifyd.py).

Equivalent to `tendermint-tpu verifyd ...` — kept as a script so ops
tooling (systemd units, the localnet harness, the bench driver) can
start the daemon without installing the CLI entrypoint:

    python scripts/verifyd.py --sock /run/tmtpu/verifyd.sock
    python scripts/verifyd.py --sock /run/tmtpu/verifyd.sock --stats

The daemon owns THE warm device mesh + persistent compile cache for the
host; every node process pointed at the socket (TMTPU_VERIFYD_SOCK or
`[verify_hub] verifyd_sock`) ships its cold verification micro-batches
there instead of paying its own backend attach.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["verifyd", *sys.argv[1:]]))
