"""Evidence pool (reference internal/evidence/pool.go:30).

Evidence lives in two DB buckets — pending (verified, awaiting block
inclusion) and committed (markers to prevent re-submission). Conflicting
votes reported by consensus are buffered until `update` runs for the
height that committed them, when the pool can stamp the evidence with
that block's time and validator power (reference
processConsensusBuffer pool.go:512). Expiry follows the consensus
params' max_age_num_blocks AND max_age_duration (both must pass,
reference pool.go:61 isExpired)."""

from __future__ import annotations

import logging
from collections import OrderedDict

from ..crypto.hashes import sha256
from ..store.db import DB
from ..types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
    decode_evidence,
)
from ..types.keys import SignedMsgType
from . import EvidencePoolI

_PENDING = b"evp/"
_COMMITTED = b"evc/"


def _key(prefix: bytes, height: int, hash_: bytes) -> bytes:
    return prefix + height.to_bytes(8, "big") + hash_


class EvidenceError(ValueError):
    pass


class EvidencePool(EvidencePoolI):
    def __init__(
        self,
        db: DB,
        state_store,
        block_store,
        *,
        logger: logging.Logger | None = None,
    ):
        self.db = db
        self.state_store = state_store
        self.block_store = block_store
        self.logger = logger or logging.getLogger("evidence")
        self._consensus_buffer: list[tuple] = []  # (vote_a, vote_b) pairs
        # cached tip, advanced by update()
        self.state = state_store.load()
        # pending_evidence is polled by the gossip reactor per peer at
        # 4 Hz; decoding the whole pending bucket on every poll turned
        # the first height WITH evidence into an event-loop meltdown at
        # committee scale (50 nodes × 8 peers × 4 Hz × N decodes/s on
        # one core — observed as a liveness wedge the moment 16
        # traitors' evidence became pending). The decoded list is
        # cached and invalidated by the version stamp every mutation
        # bumps.
        self._version = 0
        self._pending_cache: tuple[int, list] | None = None
        # one buffered conflict per (H, R, type, validator): gossip
        # re-delivers an equivocating pair once per re-offer cycle, and
        # without dedup a committee-scale equivocation flood grows the
        # buffer (and the per-commit processing pass) without bound
        self._conflict_keys: set[tuple] = set()
        # verified-LCA memo (bounded, hash-keyed): light-client-attack
        # verification reruns TWO commit checks over a committee-scale
        # conflicting block (trusting + own-set — pairing-heavy for BLS
        # committees), and every proposal carrying the evidence re-asks
        # through check_evidence until it's pending here. The inputs
        # behind a hash are immutable (committed historical state), so
        # a PASSED verdict is safe to replay; failures are never
        # memoized — a "conflicting height not committed yet" rejection
        # legitimately becomes a pass as the tip advances.
        self._lca_verified: "OrderedDict[bytes, bool]" = OrderedDict()

    # -- intake ----------------------------------------------------------

    def add_evidence(self, ev) -> None:
        """Verify and persist gossiped/RPC-submitted evidence (reference
        pool.go:137 AddEvidence)."""
        if self._is_pending(ev):
            return
        if self._is_committed(ev):
            return
        self.verify(ev)
        self._add_pending(ev)
        self.logger.info("added evidence height=%d hash=%s", ev.height, ev.hash().hex()[:12])

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        key = self._conflict_key(vote_a)
        if key in self._conflict_keys:
            return
        if len(self._conflict_keys) >= 1 << 14:
            self._conflict_keys.clear()  # bounded memory; dups re-dedup above
        self._conflict_keys.add(key)
        self._consensus_buffer.append((vote_a, vote_b))

    # -- verification ----------------------------------------------------

    def verify(self, ev) -> None:
        """Full verification against historical state (reference
        verify.go:24 verify)."""
        if self.state is None:
            raise EvidenceError("evidence pool has no state")
        state = self.state
        height = ev.height
        if height > state.last_block_height:
            raise EvidenceError("evidence from the future")
        # expiry window: BOTH dimensions must be exceeded to expire
        params = state.consensus_params.evidence
        age_blocks = state.last_block_height - height
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            raise EvidenceError(f"no block meta at evidence height {height}")
        age_ns = state.last_block_time_ns - meta.header.time_ns
        if (
            age_blocks > params.max_age_num_blocks
            and age_ns > params.max_age_duration_ns
        ):
            raise EvidenceError("evidence has expired")

        if isinstance(ev, DuplicateVoteEvidence):
            self._verify_duplicate_vote(ev, meta.header.time_ns)
        elif isinstance(ev, LightClientAttackEvidence):
            # memo key covers the FULL encoding, not ev.hash():
            # the dedup hash deliberately collapses variants that differ
            # in attribution/timestamp/power, and a same-hash variant
            # with a forged byzantine_validators list must re-run the
            # attribution check, never ride a previous verdict
            memo_key = sha256(ev.encode())
            if self._lca_verified.get(memo_key):
                self._lca_verified.move_to_end(memo_key)
                return
            self._verify_light_client_attack(ev, meta.header.time_ns)
            self._lca_verified[memo_key] = True
            while len(self._lca_verified) > 512:
                self._lca_verified.popitem(last=False)
        else:
            raise EvidenceError(f"unsupported evidence type {type(ev).__name__}")

    def _verify_duplicate_vote(self, ev: DuplicateVoteEvidence, block_time_ns: int) -> None:
        """Reference verify.go VerifyDuplicateVote."""
        ev.validate_basic()
        vals = self.state_store.load_validators(ev.height)
        if vals is None:
            raise EvidenceError(f"no validator set at height {ev.height}")
        idx, val = vals.get_by_address(ev.vote_a.validator_address)
        if val is None:
            raise EvidenceError("validator not in set at evidence height")
        if ev.vote_a.type != SignedMsgType.PRECOMMIT and ev.vote_a.type != SignedMsgType.PREVOTE:
            raise EvidenceError("bad vote type in evidence")
        # power and total must match the historical set (verify.go:104)
        if ev.validator_power != val.voting_power:
            raise EvidenceError("evidence validator power mismatch")
        if ev.total_voting_power != vals.total_voting_power():
            raise EvidenceError("evidence total power mismatch")
        if ev.timestamp_ns != block_time_ns:
            raise EvidenceError("evidence timestamp != block time")
        chain_id = self.state.chain_id
        # vote.verify routes through the VerifyHub: the consensus
        # reactor already verified both votes of a live equivocation, so
        # these are usually verdict-cache hits, not device work
        for vote in (ev.vote_a, ev.vote_b):
            if not vote.verify(chain_id, val.pub_key):
                raise EvidenceError("invalid signature on evidence vote")

    def _verify_light_client_attack(
        self, ev: LightClientAttackEvidence, common_block_time_ns: int
    ) -> None:
        """Reference verify.go:159 VerifyLightClientAttack:
        1. the conflicting block must be properly signed — by 1/3+ of the
           common-height validator set when the attack skips heights
           (VerifyCommitLightTrusting), or carry the exact common-height
           validator hash when adjacent;
        2. AND by +2/3 of its own claimed validator set (VerifyCommitLight
           — this funnels into the TPU batch path, verify.go:176);
        3. the header must actually conflict with the block we committed;
        4. attribution/power/time fields must match what this node derives."""
        from fractions import Fraction

        from ..types.validation import (
            InvalidCommitError,
            verify_commit_light,
            verify_commit_light_trusting,
        )

        ev.validate_basic()
        chain_id = self.state.chain_id
        common_vals = self.state_store.load_validators(ev.common_height)
        if common_vals is None:
            raise EvidenceError(
                f"no validator set at common height {ev.common_height}"
            )
        conflicting = ev.conflicting_block
        sh = conflicting.signed_header
        try:
            # backfill lane: evidence verification is accountability
            # traffic, never the consensus hot path — a flood of LCA
            # reports fills device batches behind live votes
            if ev.common_height != conflicting.height:
                # skipping attack: 1/3 of the common set must have signed
                verify_commit_light_trusting(
                    chain_id, common_vals, sh.commit, Fraction(1, 3),
                    lane="backfill",
                )
            else:
                if conflicting.header.validators_hash != common_vals.hash():
                    raise EvidenceError(
                        "adjacent attack: conflicting header carries a "
                        "different validator set than the common height"
                    )
            verify_commit_light(
                chain_id,
                conflicting.validators,
                sh.commit.block_id,
                conflicting.height,
                sh.commit,
                lane="backfill",
            )
        except InvalidCommitError as e:
            raise EvidenceError(f"conflicting block not properly signed: {e}") from e

        # must actually conflict with what we committed at that height
        trusted_meta = self.block_store.load_block_meta(conflicting.height)
        if trusted_meta is None:
            raise EvidenceError(
                f"no committed block at conflicting height {conflicting.height}"
            )
        if trusted_meta.header.hash() == conflicting.header.hash():
            raise EvidenceError("conflicting header matches the committed one")

        # attribution and the snapshot fields must match our own derivation
        trusted_commit = self.block_store.load_block_commit(conflicting.height)
        if trusted_commit is None:
            # canonical commit for H is stored with block H+1 — at the
            # store tip only the seen-commit exists
            trusted_commit = self.block_store.load_seen_commit(conflicting.height)
        if trusted_commit is None:
            raise EvidenceError(
                f"no commit for conflicting height {conflicting.height}"
            )
        from ..light.types import SignedHeader

        trusted_sh = SignedHeader(trusted_meta.header, trusted_commit)
        expect_byz = ev.get_byzantine_validators(common_vals, trusted_sh)
        if [v.address for v in ev.byzantine_validators] != [
            v.address for v in expect_byz
        ]:
            raise EvidenceError("byzantine validator attribution mismatch")
        if ev.total_voting_power != common_vals.total_voting_power():
            raise EvidenceError("evidence total power mismatch")
        if ev.timestamp_ns != common_block_time_ns:
            raise EvidenceError("evidence timestamp != common block time")

    # -- proposal / block flow ------------------------------------------

    def pending_evidence(self, max_bytes: int) -> tuple[list, int]:
        cache = self._pending_cache
        if cache is not None and cache[0] == self._version:
            return self._clip(cache[1], max_bytes)
        full: list[tuple[object, int]] = []
        for _, raw in self.db.iterate(_PENDING, _PENDING + b"\xff"):
            full.append((decode_evidence(raw), len(raw)))
        self._pending_cache = (self._version, full)
        return self._clip(full, max_bytes)

    @staticmethod
    def _clip(entries: list, max_bytes: int) -> tuple[list, int]:
        out, size = [], 0
        for ev, sz in entries:
            if size + sz > max_bytes:
                break
            out.append(ev)
            size += sz
        return out, size

    def check_evidence(self, evidence: tuple) -> None:
        """Verify all evidence in a proposed block (reference
        pool.go:166 CheckEvidence)."""
        seen = set()
        for ev in evidence:
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            if self._is_committed(ev):
                raise EvidenceError("evidence already committed")
            if not self._is_pending(ev):
                self.verify(ev)

    def update(self, state, evidence: tuple) -> None:
        """Block committed: mark its evidence committed, convert buffered
        consensus equivocations, prune expired (reference pool.go Update)."""
        self.state = state
        for ev in evidence:
            self._mark_committed(ev)
        self._process_consensus_buffer(state)
        self._prune(state)

    @staticmethod
    def _conflict_key(vote_a) -> tuple:
        return (
            vote_a.height,
            vote_a.round,
            int(vote_a.type),
            vote_a.validator_address,
        )

    def _process_consensus_buffer(self, state) -> None:
        buf, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buf:
            try:
                vals = self.state_store.load_validators(vote_a.height)
                meta = self.block_store.load_block_meta(vote_a.height)
                if vals is None or meta is None:
                    # too old or not yet committed; re-buffer if plausible
                    if vote_a.height > state.last_block_height:
                        self._consensus_buffer.append((vote_a, vote_b))
                    continue
                ev = DuplicateVoteEvidence.from_votes(
                    vote_a, vote_b, meta.header.time_ns, vals
                )
                if not self._is_pending(ev) and not self._is_committed(ev):
                    self.verify(ev)
                    self._add_pending(ev)
                    self.logger.info(
                        "equivocation evidence from consensus height=%d val=%s",
                        ev.height,
                        ev.vote_a.validator_address.hex()[:12],
                    )
            except Exception as e:
                # forget the dedup key: with it retained, the NEXT
                # gossip re-delivery of this pair would be silently
                # dropped at report time and a transient failure here
                # (store hiccup mid-update) would cost the evidence
                # forever
                self._conflict_keys.discard(self._conflict_key(vote_a))
                self.logger.error("failed to build consensus evidence: %r", e)

    def _prune(self, state) -> None:
        params = state.consensus_params.evidence
        for key, raw in list(self.db.iterate(_PENDING, _PENDING + b"\xff")):
            ev = decode_evidence(raw)
            age_blocks = state.last_block_height - ev.height
            meta = self.block_store.load_block_meta(ev.height)
            expired_time = True
            if meta is not None:
                expired_time = (
                    state.last_block_time_ns - meta.header.time_ns
                    > params.max_age_duration_ns
                )
            if age_blocks > params.max_age_num_blocks and expired_time:
                self._version += 1
                self.db.delete(key)
                self.logger.debug("pruned expired evidence at height %d", ev.height)

    # -- storage helpers -------------------------------------------------

    def _add_pending(self, ev) -> None:
        self._version += 1
        self.db.set(_key(_PENDING, ev.height, ev.hash()), ev.encode())

    def _mark_committed(self, ev) -> None:
        self._version += 1
        self.db.delete(_key(_PENDING, ev.height, ev.hash()))
        self.db.set(_key(_COMMITTED, ev.height, ev.hash()), b"\x01")

    def _is_pending(self, ev) -> bool:
        return self.db.has(_key(_PENDING, ev.height, ev.hash()))

    def _is_committed(self, ev) -> bool:
        return self.db.has(_key(_COMMITTED, ev.height, ev.hash()))
