"""Evidence pool interface (reference internal/evidence/pool.go:30).

The concrete pool lives in evidence/pool.py; `NopEvidencePool` keeps the
block executor testable without one."""

from __future__ import annotations

EVIDENCE_CHANNEL = 0x38


class EvidencePoolI:
    def pending_evidence(self, max_bytes: int) -> tuple[list, int]:
        """Evidence ready for inclusion in a proposal, with its total size."""
        raise NotImplementedError

    def check_evidence(self, evidence: tuple) -> None:
        """Verify block evidence; raises on invalid (reference verify.go:24)."""
        raise NotImplementedError

    def update(self, state, evidence: tuple) -> None:
        """Mark committed evidence and prune expired."""
        raise NotImplementedError

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        """Consensus saw an equivocation; buffer it until the pool can
        stamp it with the committed height/time (reference
        pool.go:188 ReportConflictingVotes)."""
        raise NotImplementedError


class NopEvidencePool(EvidencePoolI):
    def pending_evidence(self, max_bytes):
        return [], 0

    def check_evidence(self, evidence):
        pass

    def update(self, state, evidence):
        pass

    def report_conflicting_votes(self, vote_a, vote_b):
        pass
