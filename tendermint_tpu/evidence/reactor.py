"""Evidence gossip reactor (reference internal/evidence/reactor.go,
channel 0x38): continuously offer all pending evidence to every peer;
receivers verify and pool it."""

from __future__ import annotations

import asyncio
import logging

from ..libs.service import Service
from ..p2p.peermanager import PeerStatus
from ..p2p.router import Channel
from ..p2p.types import Envelope, PeerError
from ..types.evidence import decode_evidence
from . import EVIDENCE_CHANNEL
from .pool import EvidenceError, EvidencePool

BROADCAST_SLEEP = 0.25


class EvidenceReactor(Service):
    def __init__(
        self,
        pool: EvidencePool,
        channel: Channel,
        peer_updates: asyncio.Queue,
        *,
        logger: logging.Logger | None = None,
    ):
        super().__init__("ev-reactor", logger)
        self.pool = pool
        self.channel = channel
        self.peer_updates = peer_updates
        self._peer_tasks: dict[str, asyncio.Task] = {}
        self._sent: dict[str, set[bytes]] = {}

    async def on_start(self) -> None:
        self.spawn(self._process_peer_updates(), name="evr.peers")
        self.spawn(self._process_inbound(), name="evr.in")

    async def on_stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()

    async def _process_peer_updates(self) -> None:
        while True:
            upd = await self.peer_updates.get()
            if upd.status == PeerStatus.UP:
                if upd.node_id not in self._peer_tasks:
                    self._sent[upd.node_id] = set()
                    self._peer_tasks[upd.node_id] = self.spawn(
                        self._broadcast_to(upd.node_id),
                        name=f"evr.bcast.{upd.node_id[:8]}",
                    )
            else:
                t = self._peer_tasks.pop(upd.node_id, None)
                if t is not None:
                    t.cancel()
                self._sent.pop(upd.node_id, None)

    async def _process_inbound(self) -> None:
        async for env in self.channel:
            try:
                ev = decode_evidence(env.message) if isinstance(env.message, bytes) else env.message
                self.pool.add_evidence(ev)
            except EvidenceError as e:
                await self.channel.error(PeerError(env.from_, f"bad evidence: {e}"))
            except Exception as e:
                await self.channel.error(PeerError(env.from_, f"evidence: {e!r}"))

    async def _broadcast_to(self, peer_id: str) -> None:
        sent = self._sent[peer_id]
        while True:
            fresh = False
            for ev in self.pool.pending_evidence(1 << 30)[0]:
                h = ev.hash()
                if h in sent:
                    continue
                # awaited put: backpressure instead of silently losing
                # evidence gossip to this peer
                await self.channel.out_q.put(
                    Envelope(EVIDENCE_CHANNEL, ev, to=peer_id)
                )
                sent.add(h)
                fresh = True
            if not fresh:
                await asyncio.sleep(BROADCAST_SLEEP)
