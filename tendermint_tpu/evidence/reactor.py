"""Evidence gossip reactor (reference internal/evidence/reactor.go,
channel 0x38): continuously offer all pending evidence to every peer;
receivers verify and pool it.

One wrinkle the live RouterNet wiring surfaced (tests/test_byzantine.py):
a sender gossips evidence as soon as it verifies locally, but the
receiver may not have COMMITTED the evidence height yet — the pool then
raises "evidence from the future". That is honest-vs-honest timing, not
a protocol violation, and a PeerError here would disconnect a correct
peer (the router evicts on every channel error) while the sender's
`sent` mark means the evidence is never re-offered. Future evidence is
therefore parked in a small bounded buffer and re-verified as this
node's tip advances; only genuinely invalid evidence costs the peer."""

from __future__ import annotations

import asyncio
import logging

from ..libs.service import Service
from ..p2p.peermanager import PeerStatus
from ..p2p.router import Channel
from ..p2p.types import Envelope, PeerError
from ..types.evidence import decode_evidence
from . import EVIDENCE_CHANNEL
from .pool import EvidenceError, EvidencePool

BROADCAST_SLEEP = 0.25
#: parked future-evidence bound: DuplicateVoteEvidence is ~300 bytes,
#: and anything beyond a committee's worth of simultaneous traitors is
#: a flood, not a race
MAX_PARKED = 256
#: heights ahead of our tip we will park for. Honest peers gossip only
#: VERIFIED pending evidence, which sits at most their own tip — a
#: claim far past any live peer's height is junk that would otherwise
#: squat in the bounded park forever (it never stops being "future").
#: Deep laggards lose nothing: evidence beyond this window is already
#: committed ON CHAIN by the time they catch up that far.
PARK_WINDOW = 256


class EvidenceReactor(Service):
    def __init__(
        self,
        pool: EvidencePool,
        channel: Channel,
        peer_updates: asyncio.Queue,
        *,
        logger: logging.Logger | None = None,
    ):
        super().__init__("ev-reactor", logger)
        self.pool = pool
        self.channel = channel
        self.peer_updates = peer_updates
        self._peer_tasks: dict[str, asyncio.Task] = {}
        self._sent: dict[str, set[bytes]] = {}
        # hash -> evidence parked because our tip hasn't reached its
        # height yet; retried as the pool's state advances
        self._parked: dict[bytes, object] = {}

    async def on_start(self) -> None:
        self.spawn(self._process_peer_updates(), name="evr.peers")
        self.spawn(self._process_inbound(), name="evr.in")
        self.spawn(self._retry_parked(), name="evr.retry")

    async def on_stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()

    async def _process_peer_updates(self) -> None:
        while True:
            upd = await self.peer_updates.get()
            if upd.status == PeerStatus.UP:
                if upd.node_id not in self._peer_tasks:
                    self._sent[upd.node_id] = set()
                    self._peer_tasks[upd.node_id] = self.spawn(
                        self._broadcast_to(upd.node_id),
                        name=f"evr.bcast.{upd.node_id[:8]}",
                    )
            else:
                t = self._peer_tasks.pop(upd.node_id, None)
                if t is not None:
                    t.cancel()
                self._sent.pop(upd.node_id, None)

    @staticmethod
    def _verify_height(ev) -> int:
        """The height our tip must reach before the pool can verify this
        evidence. For DuplicateVoteEvidence that is the vote height; a
        LightClientAttackEvidence additionally needs the CONFLICTING
        height committed (the pool compares the forged header against
        our own block there) — its `height` property is the common
        height, which can trail the conflicting height by the whole
        skipping hop."""
        return max(ev.height, getattr(ev, "conflicting_height", ev.height))

    def _is_future(self, ev) -> bool:
        state = self.pool.state
        return (
            state is not None
            and self._verify_height(ev) > state.last_block_height
        )

    async def _process_inbound(self) -> None:
        async for env in self.channel:
            try:
                ev = decode_evidence(env.message) if isinstance(env.message, bytes) else env.message
                if self._is_future(ev):
                    tip = self.pool.state.last_block_height
                    if (
                        self._verify_height(ev) <= tip + PARK_WINDOW
                        and len(self._parked) < MAX_PARKED
                    ):
                        self._parked[ev.hash()] = ev
                    # beyond the window (or park full): drop silently —
                    # unverifiable now, and if genuine it reaches us
                    # committed in a block anyway
                    continue
                self.pool.add_evidence(ev)
            except EvidenceError as e:
                await self.channel.error(PeerError(env.from_, f"bad evidence: {e}"))
            except Exception as e:
                await self.channel.error(PeerError(env.from_, f"evidence: {e!r}"))

    async def _retry_parked(self) -> None:
        """Re-verify parked future evidence once our tip has advanced.
        Invalid evidence found here is silently dropped — the peer that
        sent it was plausible at the time; the pool's own verify keeps
        the chain safe either way."""
        while True:
            await asyncio.sleep(BROADCAST_SLEEP)
            if not self._parked:
                continue
            ready = [
                h for h, ev in self._parked.items() if not self._is_future(ev)
            ]
            for h in ready:
                ev = self._parked.pop(h)
                try:
                    self.pool.add_evidence(ev)
                except Exception as e:  # noqa: BLE001 — best-effort retry
                    self.logger.info("parked evidence rejected: %r", e)

    async def _broadcast_to(self, peer_id: str) -> None:
        sent = self._sent[peer_id]
        while True:
            fresh = False
            for ev in self.pool.pending_evidence(1 << 30)[0]:
                h = ev.hash()
                if h in sent:
                    continue
                # awaited put: backpressure instead of silently losing
                # evidence gossip to this peer
                await self.channel.out_q.put(
                    Envelope(EVIDENCE_CHANNEL, ev, to=peer_id)
                )
                sent.add(h)
                fresh = True
            if not fresh:
                await asyncio.sleep(BROADCAST_SLEEP)
