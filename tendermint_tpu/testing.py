"""Test/bench fixtures: deterministic validator sets, signed commits, and
chains — the analog of the reference's internal test factories. Used by the
unit tests and bench.py; not part of the public API surface."""

from __future__ import annotations

import hashlib

from .crypto import ed25519
from .crypto.hashes import sha256
from .types.block import BlockID, Commit, CommitSig, PartSetHeader
from .types.keys import SignedMsgType
from .types.validator_set import Validator, ValidatorSet
from .types.vote import Vote
from .types.canonical import vote_sign_bytes


def det_priv_keys(n: int, seed: bytes = b"tmtpu-test") -> list[ed25519.Ed25519PrivKey]:
    return [
        ed25519.Ed25519PrivKey(hashlib.sha256(seed + i.to_bytes(4, "big")).digest())
        for i in range(n)
    ]


def make_validator_set(
    n: int, power: int = 10, seed: bytes = b"tmtpu-test"
) -> tuple[ValidatorSet, dict[bytes, ed25519.Ed25519PrivKey]]:
    keys = det_priv_keys(n, seed)
    vals = ValidatorSet([Validator(k.pub_key(), power) for k in keys])
    by_addr = {k.pub_key().address(): k for k in keys}
    return vals, by_addr


def make_block_id(tag: bytes = b"blk") -> BlockID:
    return BlockID(sha256(tag), PartSetHeader(1, sha256(b"parts" + tag)))


def make_commit(
    chain_id: str,
    height: int,
    round_: int,
    block_id: BlockID,
    vals: ValidatorSet,
    keys_by_addr: dict,
    *,
    nil_indices: frozenset[int] = frozenset(),
    absent_indices: frozenset[int] = frozenset(),
    timestamp_ns: int = 1_700_000_000_000_000_000,
) -> Commit:
    """Build a fully-signed commit over `block_id` by the validator set."""
    from .types.block import NIL_BLOCK_ID

    sigs = []
    for i, val in enumerate(vals.validators):
        if i in absent_indices:
            sigs.append(CommitSig.absent())
            continue
        ts = timestamp_ns + i
        vote_bid = NIL_BLOCK_ID if i in nil_indices else block_id
        sb = vote_sign_bytes(
            chain_id, SignedMsgType.PRECOMMIT, height, round_, vote_bid, ts
        )
        sig = keys_by_addr[val.address].sign(sb)
        if i in nil_indices:
            sigs.append(CommitSig.for_nil(val.address, ts, sig))
        else:
            sigs.append(CommitSig.for_block(val.address, ts, sig))
    return Commit(height, round_, block_id, tuple(sigs))


def make_vote(
    chain_id: str,
    key: ed25519.Ed25519PrivKey,
    index: int,
    height: int,
    round_: int,
    type_: SignedMsgType,
    block_id: BlockID,
    timestamp_ns: int = 1_700_000_000_000_000_000,
) -> Vote:
    sb = vote_sign_bytes(chain_id, type_, height, round_, block_id, timestamp_ns)
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=timestamp_ns,
        validator_address=key.pub_key().address(),
        validator_index=index,
        signature=key.sign(sb),
    )
