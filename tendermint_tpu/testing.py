"""Test/bench fixtures: deterministic validator sets, signed commits, and
chains — the analog of the reference's internal test factories. Used by the
unit tests and bench.py; not part of the public API surface."""

from __future__ import annotations

import hashlib

from .crypto import ed25519
from .crypto.hashes import sha256
from .types.block import BlockID, Commit, CommitSig, PartSetHeader
from .types.keys import SignedMsgType
from .types.validator_set import Validator, ValidatorSet
from .types.vote import Vote
from .types.canonical import vote_sign_bytes


def det_priv_keys(n: int, seed: bytes = b"tmtpu-test") -> list[ed25519.Ed25519PrivKey]:
    return [
        ed25519.Ed25519PrivKey(hashlib.sha256(seed + i.to_bytes(4, "big")).digest())
        for i in range(n)
    ]


def make_validator_set(
    n: int,
    power: int = 10,
    seed: bytes = b"tmtpu-test",
    key_types: tuple[str, ...] = ("ed25519",),
) -> tuple[ValidatorSet, dict[bytes, object]]:
    """Deterministic validator set; `key_types` cycles over the validators
    (e.g. ("ed25519", "secp256k1") alternates key types — the BASELINE
    config-4 mixed-set shape)."""
    keys: list = []
    for i in range(n):
        kt = key_types[i % len(key_types)]
        secret = hashlib.sha256(seed + kt.encode() + i.to_bytes(4, "big")).digest()
        if kt == "ed25519":
            keys.append(ed25519.Ed25519PrivKey(secret))
        elif kt == "secp256k1":
            from .crypto.secp256k1 import Secp256k1PrivKey

            keys.append(Secp256k1PrivKey(secret))
        elif kt == "sr25519":
            from .crypto.sr25519 import Sr25519PrivKey

            keys.append(Sr25519PrivKey(secret))
        elif kt == "bls12381":
            from .crypto.bls import BLSPrivKey

            keys.append(BLSPrivKey(secret))
        else:
            raise ValueError(f"unknown key type {kt}")
    vals = ValidatorSet([Validator(k.pub_key(), power) for k in keys])
    by_addr = {k.pub_key().address(): k for k in keys}
    return vals, by_addr


def make_block_id(tag: bytes = b"blk") -> BlockID:
    return BlockID(sha256(tag), PartSetHeader(1, sha256(b"parts" + tag)))


def make_commit(
    chain_id: str,
    height: int,
    round_: int,
    block_id: BlockID,
    vals: ValidatorSet,
    keys_by_addr: dict,
    *,
    nil_indices: frozenset[int] = frozenset(),
    absent_indices: frozenset[int] = frozenset(),
    timestamp_ns: int = 1_700_000_000_000_000_000,
) -> Commit:
    """Build a fully-signed commit over `block_id` by the validator set."""
    from .types.block import NIL_BLOCK_ID

    sigs = []
    for i, val in enumerate(vals.validators):
        if i in absent_indices:
            sigs.append(CommitSig.absent())
            continue
        ts = timestamp_ns + i
        vote_bid = NIL_BLOCK_ID if i in nil_indices else block_id
        sb = vote_sign_bytes(
            chain_id, SignedMsgType.PRECOMMIT, height, round_, vote_bid, ts
        )
        sig = keys_by_addr[val.address].sign(sb)
        if i in nil_indices:
            sigs.append(CommitSig.for_nil(val.address, ts, sig))
        else:
            sigs.append(CommitSig.for_block(val.address, ts, sig))
    return Commit(height, round_, block_id, tuple(sigs))


def make_light_chain(
    n_heights: int,
    vals: ValidatorSet,
    keys_by_addr: dict,
    chain_id: str = "light-chain",
    *,
    start_time_ns: int = 1_700_000_000_000_000_000,
    block_interval_ns: int = 1_000_000_000,
):
    """A synthetic chain of properly-signed LightBlocks 1..n_heights
    over one static validator set: hash-linked headers with monotone
    times, each committed by the full set — the light-client serving /
    hop-proof workload shape (LightFleet tests and `bench.py
    light_fleet`) without spinning a live network."""
    from .crypto.hashes import sha256 as _sha
    from .light.types import LightBlock, SignedHeader
    from .types.block import Header

    out: list = []
    last_bid = BlockID()
    vh = vals.hash()
    for h in range(1, n_heights + 1):
        header = Header(
            chain_id=chain_id,
            height=h,
            time_ns=start_time_ns + h * block_interval_ns,
            last_block_id=last_bid,
            last_commit_hash=_sha(b"lc" + h.to_bytes(8, "big")),
            data_hash=_sha(b"data" + h.to_bytes(8, "big")),
            validators_hash=vh,
            next_validators_hash=vh,
            consensus_hash=_sha(b"consensus"),
            app_hash=_sha(b"app" + h.to_bytes(8, "big")),
            last_results_hash=_sha(b"results"),
            evidence_hash=b"",
            proposer_address=vals.validators[h % len(vals.validators)].address,
        )
        bid = BlockID(header.hash(), PartSetHeader(1, _sha(b"p" + h.to_bytes(8, "big"))))
        commit = make_commit(
            chain_id, h, 0, bid, vals, keys_by_addr,
            timestamp_ns=header.time_ns,
        )
        out.append(LightBlock(SignedHeader(header, commit), vals))
        last_bid = bid
    return out


def make_list_provider(blocks, chain_id: str = "light-chain"):
    """An in-memory light-block Provider over a prebuilt chain (height
    0 = tip), with a fetch counter — the serving-side fixture for the
    LightFleet tests and `bench.py light_fleet`."""
    from .light.provider import LightBlockNotFoundError, Provider

    class ListProvider(Provider):
        def __init__(self):
            self.blocks = {b.height: b for b in blocks}
            self.tip = max(self.blocks)
            self.fetches = 0

        def chain_id(self):
            return chain_id

        async def light_block(self, height):
            self.fetches += 1
            h = height or self.tip
            if h not in self.blocks:
                raise LightBlockNotFoundError(str(h))
            return self.blocks[h]

        async def report_evidence(self, ev):
            pass

    return ListProvider()


async def build_kvstore_chain(n_blocks: int, n_vals: int, chain_id: str = "ss-bench"):
    """Build an n_blocks kvstore chain through the real executor: returns
    (block_store, state_store, app_conns, genesis, keys_by_addr) with the
    app holding its periodic snapshots. Shared by bench.py config 5 and
    the statesync tests."""
    from .abci.kvstore import KVStoreApp
    from .consensus.replay import Handshaker
    from .proxy import AppConns
    from .state.execution import BlockExecutor
    from .state.state import state_from_genesis
    from .state.store import StateStore
    from .store.blockstore import BlockStore
    from .store.db import MemDB
    from .types.genesis import GenesisDoc, GenesisValidator

    keys = det_priv_keys(n_vals)
    gvals = [GenesisValidator(k.pub_key(), 10, f"v{i}") for i, k in enumerate(keys)]
    genesis = GenesisDoc(
        chain_id=chain_id,
        initial_height=1,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=gvals,
    )
    by_addr = {k.pub_key().address(): k for k in keys}
    app = KVStoreApp()
    conns = AppConns.local(app)
    bstore = BlockStore(MemDB())
    sstore = StateStore(MemDB())
    state = state_from_genesis(genesis)
    state = await Handshaker(sstore, state, bstore, genesis).handshake(conns)
    sstore.save(state)
    ex = BlockExecutor(sstore, conns.consensus, block_store=bstore)
    from .config import MempoolConfig
    from .mempool.pool import PriorityMempool

    mp = PriorityMempool(MempoolConfig(), conns.mempool)
    ex.mempool = mp
    commit = None
    for h in range(1, n_blocks + 1):
        if h % 3 == 1:
            await mp.check_tx(b"k%d=v%d" % (h, h))
        block, parts = ex.create_proposal_block(
            h, state, commit, state.validators.get_proposer().address
        )
        bid = block.block_id(parts.header)
        state, _ = await ex.apply_block(state, bid, block)
        commit = make_commit(
            chain_id, h, 0, bid, state.last_validators, by_addr,
            timestamp_ns=block.header.time_ns + 1,
        )
        bstore.save_block(block, parts, commit)
    return bstore, sstore, conns, genesis, by_addr


async def statesync_restore_scenario(
    n_blocks: int, n_vals: int, *, backfill_blocks: int | None = None
) -> int:
    """BASELINE config 5 shape: snapshot restore + verified backfill over
    the real statesync reactor protocol, two reactors bridged in-process.
    Returns the number of headers the restored node holds afterwards
    (reference internal/statesync/reactor.go Sync + Backfill)."""
    import asyncio

    from .abci.kvstore import KVStoreApp
    from .p2p.peermanager import PeerStatus, PeerUpdate
    from .p2p.router import Channel
    from .p2p.types import Envelope
    from .proxy import AppConns
    from .state.store import StateStore
    from .statesync import (
        CHUNK_CHANNEL,
        LIGHT_BLOCK_CHANNEL,
        PARAMS_CHANNEL,
        SNAPSHOT_CHANNEL,
    )
    from .statesync import messages as ssm
    from .statesync.reactor import StateSyncReactor, SyncConfig
    from .store.blockstore import BlockStore
    from .store.db import MemDB

    src_bstore, src_sstore, src_conns, genesis, _keys = await build_kvstore_chain(
        n_blocks, n_vals
    )

    def channels() -> dict[int, Channel]:
        return {
            cid: Channel(cid, name, 5, ssm.encode_message, ssm.decode_message)
            for cid, name in (
                (SNAPSHOT_CHANNEL, "snapshot"),
                (CHUNK_CHANNEL, "chunk"),
                (LIGHT_BLOCK_CHANNEL, "lightblock"),
                (PARAMS_CHANNEL, "params"),
            )
        }

    src_ch, dst_ch = channels(), channels()

    server_q: asyncio.Queue = asyncio.Queue()
    client_q: asyncio.Queue = asyncio.Queue()
    server = StateSyncReactor(
        genesis.chain_id, src_conns, src_sstore, src_bstore,
        src_ch[SNAPSHOT_CHANNEL], src_ch[CHUNK_CHANNEL],
        src_ch[LIGHT_BLOCK_CHANNEL], src_ch[PARAMS_CHANNEL], server_q,
    )
    dst_app = AppConns.local(KVStoreApp(MemDB()))
    dst_bstore = BlockStore(MemDB())
    dst_sstore = StateStore(MemDB())
    client = StateSyncReactor(
        genesis.chain_id, dst_app, dst_sstore, dst_bstore,
        dst_ch[SNAPSHOT_CHANNEL], dst_ch[CHUNK_CHANNEL],
        dst_ch[LIGHT_BLOCK_CHANNEL], dst_ch[PARAMS_CHANNEL], client_q,
    )

    async def pump(src: Channel, dst: Channel, from_name: str) -> None:
        while True:
            env = await src.out_q.get()
            await dst.in_q.put(Envelope(env.channel_id, env.message, from_=from_name))

    pumps = [
        asyncio.get_running_loop().create_task(pump(a, b, name))
        for cid in src_ch
        for a, b, name in (
            (dst_ch[cid], src_ch[cid], "client"),
            (src_ch[cid], dst_ch[cid], "server"),
        )
    ]
    await server.start()
    await client.start()
    await client_q.put(PeerUpdate("server", PeerStatus.UP))
    try:
        meta1 = src_bstore.load_block_meta(1)
        cfg = SyncConfig(
            trust_height=1,
            trust_hash=meta1.header.hash(),
            trust_period_ns=10 * 365 * 24 * 3600 * 10**9,
            backfill_blocks=backfill_blocks,
        )
        state = await asyncio.wait_for(client.sync(cfg), timeout=300)
        assert state.last_block_height >= n_blocks - 12, state.last_block_height
        held = 0
        h = state.last_block_height
        while h >= 1 and dst_bstore.load_block_meta(h) is not None:
            held += 1
            h -= 1
        return held
    finally:
        for t in pumps:
            t.cancel()
        await client.stop()
        await server.stop()
        await dst_app.stop()
        await src_conns.stop()


async def statesync_fleet_scenario(
    n_blocks: int,
    n_vals: int,
    n_joiners: int = 4,
    *,
    backfill_blocks: int | None = None,
    bootd_config=None,
    sync_timeout_s: float = 300.0,
) -> dict:
    """BootFleet in-process shape: ONE donor reactor (its BootD serving
    every joiner from the shared chunk cache) vs `n_joiners` concurrent
    cold joiners, bridged by routing pumps — the `bench.py statesync`
    join-wave workload and the tier-1 BootFleet fixtures, without a live
    router mesh. Returns per-joiner sync times, the donor's BootD stats
    (cache amortization, sheds, store reads), and per-joiner join
    outcomes (a shed/failed joiner is an outcome, not a raise)."""
    import asyncio

    from .abci.kvstore import KVStoreApp
    from .p2p.peermanager import PeerStatus, PeerUpdate
    from .p2p.router import Channel
    from .p2p.types import Envelope
    from .proxy import AppConns
    from .state.store import StateStore
    from .statesync import (
        CHUNK_CHANNEL,
        LIGHT_BLOCK_CHANNEL,
        PARAMS_CHANNEL,
        SNAPSHOT_CHANNEL,
    )
    from .statesync import messages as ssm
    from .statesync.reactor import StateSyncReactor, SyncConfig
    from .store.blockstore import BlockStore
    from .store.db import MemDB

    src_bstore, src_sstore, src_conns, genesis, _keys = await build_kvstore_chain(
        n_blocks, n_vals
    )

    def channels() -> dict[int, Channel]:
        return {
            cid: Channel(cid, name, 5, ssm.encode_message, ssm.decode_message)
            for cid, name in (
                (SNAPSHOT_CHANNEL, "snapshot"),
                (CHUNK_CHANNEL, "chunk"),
                (LIGHT_BLOCK_CHANNEL, "lightblock"),
                (PARAMS_CHANNEL, "params"),
            )
        }

    src_ch = channels()
    server = StateSyncReactor(
        genesis.chain_id, src_conns, src_sstore, src_bstore,
        src_ch[SNAPSHOT_CHANNEL], src_ch[CHUNK_CHANNEL],
        src_ch[LIGHT_BLOCK_CHANNEL], src_ch[PARAMS_CHANNEL],
        asyncio.Queue(),
        bootd_config=bootd_config,
    )
    joiner_ch: dict[str, dict[int, Channel]] = {
        f"joiner-{i}": channels() for i in range(n_joiners)
    }
    clients: dict[str, StateSyncReactor] = {}
    apps: list[AppConns] = []
    stores: dict[str, BlockStore] = {}
    for name, chs in joiner_ch.items():
        app = AppConns.local(KVStoreApp(MemDB()))
        apps.append(app)
        bstore = BlockStore(MemDB())
        stores[name] = bstore
        q: asyncio.Queue = asyncio.Queue()
        clients[name] = StateSyncReactor(
            genesis.chain_id, app, StateStore(MemDB()), bstore,
            chs[SNAPSHOT_CHANNEL], chs[CHUNK_CHANNEL],
            chs[LIGHT_BLOCK_CHANNEL], chs[PARAMS_CHANNEL], q,
        )
        await q.put(PeerUpdate("server", PeerStatus.UP))

    async def pump_to_server(cid: int, name: str) -> None:
        src = joiner_ch[name][cid]
        while True:
            env = await src.out_q.get()
            await src_ch[cid].in_q.put(
                Envelope(env.channel_id, env.message, from_=name)
            )

    async def route_from_server(cid: int) -> None:
        # the server addresses every reply (`to=env.from_`); route it to
        # that joiner's channel — a broadcast (never sent today) fans out
        while True:
            env = await src_ch[cid].out_q.get()
            targets = [env.to] if env.to else list(joiner_ch)
            for t in targets:
                if t in joiner_ch:
                    await joiner_ch[t][cid].in_q.put(
                        Envelope(env.channel_id, env.message, from_="server")
                    )

    pumps = [
        asyncio.get_running_loop().create_task(pump_to_server(cid, name))
        for cid in src_ch
        for name in joiner_ch
    ] + [
        asyncio.get_running_loop().create_task(route_from_server(cid))
        for cid in src_ch
    ]
    await server.start()
    for c in clients.values():
        await c.start()
    loop = asyncio.get_running_loop()
    meta1 = src_bstore.load_block_meta(1)
    cfg = SyncConfig(
        trust_height=1,
        trust_hash=meta1.header.hash(),
        trust_period_ns=10 * 365 * 24 * 3600 * 10**9,
        backfill_blocks=backfill_blocks,
    )
    out: dict = {
        "n_joiners": n_joiners,
        "joined": 0,
        "join_errors": [],
        "time_to_synced_s": [],
        "headers_held": [],
        "elapsed_s": 0.0,
        "server_stats": {},
    }

    async def join_one(name: str) -> None:
        t0 = loop.time()
        try:
            state = await asyncio.wait_for(
                clients[name].sync(cfg), sync_timeout_s
            )
        except Exception as e:  # noqa: BLE001 — structured outcome
            out["join_errors"].append(f"{name}: {e!r}")
            return
        out["joined"] += 1
        out["time_to_synced_s"].append(round(loop.time() - t0, 4))
        held, h = 0, state.last_block_height
        while h >= 1 and stores[name].load_block_meta(h) is not None:
            held += 1
            h -= 1
        out["headers_held"].append(held)

    try:
        t0 = loop.time()
        await asyncio.gather(*(join_one(n) for n in clients))
        out["elapsed_s"] = round(loop.time() - t0, 4)
        out["server_stats"] = dict(server.bootd.stats)
        # backfill verification happens on the JOINERS' side (their
        # BootD counters), not the donor's
        out["joiner_backfill"] = {
            key: sum(c.bootd.stats[key] for c in clients.values())
            for key in (
                "backfill_heights", "backfill_sigs",
                "backfill_agg_heights", "backfill_batches",
            )
        }
        return out
    finally:
        for t in pumps:
            t.cancel()
        for c in clients.values():
            await c.stop()
        await server.stop()
        for app in apps:
            await app.stop()
        await src_conns.stop()


def make_vote(
    chain_id: str,
    key: ed25519.Ed25519PrivKey,
    index: int,
    height: int,
    round_: int,
    type_: SignedMsgType,
    block_id: BlockID,
    timestamp_ns: int = 1_700_000_000_000_000_000,
) -> Vote:
    sb = vote_sign_bytes(chain_id, type_, height, round_, block_id, timestamp_ns)
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=timestamp_ns,
        validator_address=key.pub_key().address(),
        validator_index=index,
        signature=key.sign(sb),
    )
