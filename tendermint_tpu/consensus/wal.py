"""Write-ahead log (reference internal/consensus/wal.go, autofile group).

Every consensus input (peer msg, internal msg, timeout) is WAL-written
before it is processed, so a crashed node can deterministically replay to
its pre-crash state. Records are CRC32+length framed; `EndHeight` marker
records delimit completed heights (reference wal.go:288 WALEncoder,
EndHeightMessage).

Files: `wal` is the head; at `head_size_limit` it rotates to `wal.000`,
`wal.001`, … (the autofile.Group analog); replay reads rotated files in
order, then the head."""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator

from ..libs import protoenc as pe

_FRAME = struct.Struct("<II")  # crc32, length
MAX_RECORD_SIZE = 1 << 20

KIND_MESSAGE = 1
KIND_END_HEIGHT = 2


@dataclass(frozen=True)
class WALRecord:
    kind: int
    time_ns: int
    data: bytes  # opaque consensus message (KIND_MESSAGE)
    height: int = 0  # KIND_END_HEIGHT

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.kind)
        out += pe.varint_field(2, self.time_ns)
        if self.kind == KIND_END_HEIGHT:
            out += pe.varint_field(3, self.height)
        else:
            out += pe.bytes_field(4, self.data)
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "WALRecord":
        r = pe.Reader(raw)
        kind, time_ns, height, data = KIND_MESSAGE, 0, 0, b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kind = r.read_uvarint()
            elif f == 2:
                time_ns = r.read_uvarint()
            elif f == 3:
                height = r.read_uvarint()
            elif f == 4:
                data = r.read_bytes()
            else:
                r.skip(wt)
        return cls(kind, time_ns, data, height)


class WALCorruptionError(RuntimeError):
    pass


class WAL:
    def __init__(
        self,
        directory: str,
        *,
        head_size_limit: int = 10 * 1024 * 1024,
        total_size_limit: int = 1024 * 1024 * 1024,
    ):
        self.dir = directory
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        os.makedirs(directory, exist_ok=True)
        self._head_path = os.path.join(directory, "wal")
        self._f = open(self._head_path, "ab")

    # -- writing ---------------------------------------------------------

    def _write_record(self, rec: WALRecord, sync: bool) -> None:
        payload = rec.encode()
        if len(payload) > MAX_RECORD_SIZE:
            raise ValueError("WAL record too big")
        frame = _FRAME.pack(zlib.crc32(payload), len(payload))
        self._f.write(frame + payload)
        if sync:
            self._f.flush()
            os.fsync(self._f.fileno())
        if self._f.tell() >= self.head_size_limit:
            self._rotate()

    def write(self, data: bytes, time_ns: int = 0) -> None:
        """Buffered write (group-flushed; reference wal.go Write)."""
        self._write_record(WALRecord(KIND_MESSAGE, time_ns, data), sync=False)

    def write_sync(self, data: bytes, time_ns: int = 0) -> None:
        """Fsync'd write — used for messages about to be acted on
        (reference wal.go WriteSync)."""
        self._write_record(WALRecord(KIND_MESSAGE, time_ns, data), sync=True)

    def write_end_height(self, height: int) -> None:
        self._write_record(WALRecord(KIND_END_HEIGHT, 0, b"", height), sync=True)

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.flush()
        except (OSError, ValueError):
            pass
        self._f.close()

    # -- rotation --------------------------------------------------------

    def _rotated_files(self) -> list[str]:
        names = sorted(
            (n for n in os.listdir(self.dir) if n.startswith("wal.") and n[4:].isdigit()),
            key=lambda n: int(n[4:]),
        )
        return [os.path.join(self.dir, n) for n in names]

    def _rotate(self) -> None:
        self._f.flush()
        self._f.close()
        existing = self._rotated_files()
        idx = (
            int(os.path.basename(existing[-1])[4:]) + 1 if existing else 0
        )
        os.rename(self._head_path, os.path.join(self.dir, f"wal.{idx:03d}"))
        self._f = open(self._head_path, "ab")
        # enforce the group size cap by dropping the oldest rotated file
        files = self._rotated_files()
        total = sum(os.path.getsize(p) for p in files) + self._f.tell()
        while files and total > self.total_size_limit:
            total -= os.path.getsize(files[0])
            os.remove(files.pop(0))

    # -- reading ---------------------------------------------------------

    def _all_files(self) -> list[str]:
        files = self._rotated_files()
        if os.path.exists(self._head_path):
            files.append(self._head_path)
        return files

    def iter_records(self, *, strict: bool = False) -> Iterator[WALRecord]:
        """Replay all records oldest-first. A torn tail frame (crash during
        write) terminates iteration; corruption mid-log raises in strict
        mode (reference WALDecoder semantics)."""
        self._f.flush()
        for path in self._all_files():
            with open(path, "rb") as f:
                is_head = path == self._head_path
                while True:
                    frame = f.read(_FRAME.size)
                    if not frame:
                        break
                    if len(frame) < _FRAME.size:
                        if strict and not is_head:
                            raise WALCorruptionError(f"torn frame in {path}")
                        return
                    crc, length = _FRAME.unpack(frame)
                    if length > MAX_RECORD_SIZE:
                        if strict:
                            raise WALCorruptionError(f"oversized record in {path}")
                        return
                    payload = f.read(length)
                    if len(payload) < length:
                        if strict and not is_head:
                            raise WALCorruptionError(f"torn payload in {path}")
                        return
                    if zlib.crc32(payload) != crc:
                        if strict:
                            raise WALCorruptionError(f"CRC mismatch in {path}")
                        return
                    yield WALRecord.decode(payload)

    def search_for_end_height(self, height: int) -> list[WALRecord] | None:
        """Messages recorded after `#ENDHEIGHT: height` (reference
        wal.go:231 SearchForEndHeight) — i.e. everything belonging to
        height+1. Returns None if the marker is absent. Height 0 matches
        the start of the log (fresh chain)."""
        if height == 0:
            found = True
            out: list[WALRecord] = []
        else:
            found = False
            out = []
        for rec in self.iter_records():
            if rec.kind == KIND_END_HEIGHT:
                if rec.height == height:
                    found = True
                    out = []
                elif found and rec.height > height:
                    # next height completed too; keep collecting — replay
                    # handles duplicates idempotently
                    pass
                continue
            if found:
                out.append(rec)
        return out if found else None
