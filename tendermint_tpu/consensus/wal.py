"""Write-ahead log (reference internal/consensus/wal.go, autofile group).

Every consensus input (peer msg, internal msg, timeout) is WAL-written
before it is processed, so a crashed node can deterministically replay to
its pre-crash state. Records are CRC32+length framed; `EndHeight` marker
records delimit completed heights (reference wal.go:288 WALEncoder,
EndHeightMessage).

Files: `wal` is the head; at `head_size_limit` it rotates to `wal.000`,
`wal.001`, … (the autofile.Group analog); replay reads rotated files in
order, then the head.

Crash consistency: all file I/O goes through an injectable `libs.chaosfs.FS`
(lint-enforced by the tmtlint fs-discipline + transitive-fs rules,
`scripts/tmtlint`) so storage faults — torn
writes, lost fsyncs, ENOSPC mid-record, bit-rot — are testable. On open,
`repair()` scans every file and truncates to the last whole record,
moving any damaged tail aside into `<file>.corrupt.<n>` instead of
raising: a node killed mid-write restarts without manual intervention,
and the exact truncation point is logged (consensus/replay.py
`report_wal_repair`). A mid-record ENOSPC rolls the partial frame back so
the log never grows an undetected garbage gap."""

from __future__ import annotations

import logging
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator

from ..libs import protoenc as pe
from ..libs.chaosfs import FS, REAL_FS
from ..libs.metrics import record_storage

_FRAME = struct.Struct("<II")  # crc32, length
MAX_RECORD_SIZE = 1 << 20

KIND_MESSAGE = 1
KIND_END_HEIGHT = 2


@dataclass(frozen=True)
class WALRecord:
    kind: int
    time_ns: int
    data: bytes  # opaque consensus message (KIND_MESSAGE)
    height: int = 0  # KIND_END_HEIGHT

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.kind)
        out += pe.varint_field(2, self.time_ns)
        if self.kind == KIND_END_HEIGHT:
            out += pe.varint_field(3, self.height)
        else:
            out += pe.bytes_field(4, self.data)
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "WALRecord":
        r = pe.Reader(raw)
        kind, time_ns, height, data = KIND_MESSAGE, 0, 0, b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kind = r.read_uvarint()
            elif f == 2:
                time_ns = r.read_uvarint()
            elif f == 3:
                height = r.read_uvarint()
            elif f == 4:
                data = r.read_bytes()
            else:
                r.skip(wt)
        return cls(kind, time_ns, data, height)


class WALCorruptionError(RuntimeError):
    pass


@dataclass(frozen=True)
class WALRepair:
    """One repaired file: everything past `valid_end` was moved aside."""

    path: str
    valid_end: int  # byte offset of the last whole record
    file_size: int  # size before repair
    n_records: int  # whole records surviving in this file
    tail_path: str  # where the damaged tail went
    reason: str  # what broke the frame walk


class WAL:
    def __init__(
        self,
        directory: str,
        *,
        head_size_limit: int = 10 * 1024 * 1024,
        total_size_limit: int = 1024 * 1024 * 1024,
        fs: FS | None = None,
        logger: logging.Logger | None = None,
    ):
        self.dir = directory
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self.fs = fs or REAL_FS
        self.logger = logger or logging.getLogger("wal")
        self.fs.makedirs(directory)
        self._head_path = os.path.join(directory, "wal")
        # heal any crash damage BEFORE appending: writing after a torn
        # tail would bury the corruption mid-file and silently drop every
        # later record at replay
        self.last_repair: list[WALRepair] = self.repair()
        self._f = self.fs.open(self._head_path, "ab")

    # -- writing ---------------------------------------------------------

    def _write_record(self, rec: WALRecord, sync: bool) -> None:
        payload = rec.encode()
        if len(payload) > MAX_RECORD_SIZE:
            raise ValueError("WAL record too big")
        frame = _FRAME.pack(zlib.crc32(payload), len(payload))
        start = self._f.tell()
        try:
            self._f.write(frame + payload)
        except OSError:
            # ENOSPC (or any I/O error) mid-record: roll the partial frame
            # back so the head never grows an unframed garbage gap. Best
            # effort — if even the truncate fails, repair() heals it at
            # the next open.
            try:
                self._f.flush()
            except OSError:
                pass
            try:
                self._f.truncate(start)
                self._f.seek(start)
            except OSError:
                pass
            raise
        if sync:
            self.fs.fsync(self._f)
        if self._f.tell() >= self.head_size_limit:
            self._rotate()

    def write(self, data: bytes, time_ns: int = 0) -> None:
        """Buffered write (group-flushed; reference wal.go Write)."""
        self._write_record(WALRecord(KIND_MESSAGE, time_ns, data), sync=False)

    def write_sync(self, data: bytes, time_ns: int = 0) -> None:
        """Fsync'd write — used for messages about to be acted on
        (reference wal.go WriteSync)."""
        self._write_record(WALRecord(KIND_MESSAGE, time_ns, data), sync=True)

    def write_end_height(self, height: int) -> None:
        self._write_record(WALRecord(KIND_END_HEIGHT, 0, b"", height), sync=True)

    def flush(self) -> None:
        self.fs.fsync(self._f)

    def close(self) -> None:
        try:
            self.flush()
        except (OSError, ValueError):
            pass
        self._f.close()

    # -- rotation --------------------------------------------------------

    def _rotated_files(self) -> list[str]:
        names = sorted(
            (n for n in self.fs.listdir(self.dir) if n.startswith("wal.") and n[4:].isdigit()),
            key=lambda n: int(n[4:]),
        )
        return [os.path.join(self.dir, n) for n in names]

    def _rotate(self) -> None:
        self._f.flush()
        self._f.close()
        existing = self._rotated_files()
        idx = (
            int(os.path.basename(existing[-1])[4:]) + 1 if existing else 0
        )
        self.fs.rename(self._head_path, os.path.join(self.dir, f"wal.{idx:03d}"))
        self._f = self.fs.open(self._head_path, "ab")
        # enforce the group size cap by dropping the oldest rotated file
        files = self._rotated_files()
        total = sum(self.fs.getsize(p) for p in files) + self._f.tell()
        while files and total > self.total_size_limit:
            total -= self.fs.getsize(files[0])
            self.fs.remove(files.pop(0))

    # -- reading ---------------------------------------------------------

    def _all_files(self) -> list[str]:
        files = self._rotated_files()
        if self.fs.exists(self._head_path):
            files.append(self._head_path)
        return files

    def _note_corrupt(self, path: str, offset: int, reason: str) -> None:
        record_storage("wal_corrupt_records")
        self.logger.warning(
            "WAL corruption: %s in %s at offset %d (replay truncated here)",
            reason, path, offset,
        )

    def iter_records(self, *, strict: bool = False) -> Iterator[WALRecord]:
        """Replay all records oldest-first. A torn tail frame (crash during
        write) terminates iteration; corruption mid-log raises in strict
        mode (reference WALDecoder semantics). Non-strict truncation is
        never silent: it bumps the `wal_corrupt_records` metric and logs
        the file/offset."""
        if getattr(self, "_f", None) is not None and not self._f.closed:
            self._f.flush()
        for path in self._all_files():
            with self.fs.open(path, "rb") as f:
                is_head = path == self._head_path
                while True:
                    at = f.tell()
                    frame = f.read(_FRAME.size)
                    if not frame:
                        break
                    if len(frame) < _FRAME.size:
                        if strict and not is_head:
                            raise WALCorruptionError(f"torn frame in {path}")
                        self._note_corrupt(path, at, "torn frame")
                        return
                    crc, length = _FRAME.unpack(frame)
                    if length > MAX_RECORD_SIZE:
                        if strict:
                            raise WALCorruptionError(f"oversized record in {path}")
                        self._note_corrupt(path, at, "oversized record")
                        return
                    payload = f.read(length)
                    if len(payload) < length:
                        if strict and not is_head:
                            raise WALCorruptionError(f"torn payload in {path}")
                        self._note_corrupt(path, at, "torn payload")
                        return
                    if zlib.crc32(payload) != crc:
                        if strict:
                            raise WALCorruptionError(f"CRC mismatch in {path}")
                        self._note_corrupt(path, at, "CRC mismatch")
                        return
                    yield WALRecord.decode(payload)

    # -- crash repair ----------------------------------------------------

    def _scan_valid(self, path: str) -> tuple[int, int, str]:
        """Walk frames; return (offset past the last whole record, count
        of whole records, reason the walk stopped)."""
        valid_end = 0
        n = 0
        with self.fs.open(path, "rb") as f:
            while True:
                frame = f.read(_FRAME.size)
                if not frame:
                    return valid_end, n, "eof"
                if len(frame) < _FRAME.size:
                    return valid_end, n, "torn frame"
                crc, length = _FRAME.unpack(frame)
                if length > MAX_RECORD_SIZE:
                    return valid_end, n, "oversized record"
                payload = f.read(length)
                if len(payload) < length:
                    return valid_end, n, "torn payload"
                if zlib.crc32(payload) != crc:
                    return valid_end, n, "CRC mismatch"
                valid_end = f.tell()
                n += 1

    def repair(self) -> list[WALRepair]:
        """Truncate every WAL file to its last whole record, moving the
        damaged tail aside as `<file>.corrupt.<n>` (never deleted — it is
        forensic evidence, and `wal.corrupt.*` names are invisible to the
        rotation scan). ALL files are scanned, not just the newest:
        lost-but-acked fsyncs mean even rotated files can carry torn
        tails (the durable watermark travels with the rename), and an
        unrepaired mid-log tear would silently drop every later record
        at replay. The cost is one extra CRC pass over the WAL — the
        same order as the `iter_records` replay that follows on every
        restart anyway. Returns one `WALRepair` per healed file; the
        caller (consensus/replay.report_wal_repair) logs the truncation
        points."""
        repairs: list[WALRepair] = []
        for path in self._all_files():
            size = self.fs.getsize(path)
            valid_end, n, reason = self._scan_valid(path)
            if valid_end >= size:
                continue
            # confirm before destroying: a transient read error (bit-rot
            # injection, flaky medium) must not truncate records that are
            # intact on disk — re-scan and keep the furthest clean walk
            valid_end2, n2, reason2 = self._scan_valid(path)
            if valid_end2 >= size:
                continue  # first scan's corruption was a transient read
            if valid_end2 > valid_end:
                valid_end, n, reason = valid_end2, n2, reason2
            # salvage the damaged tail before truncating — best-effort:
            # it is forensic evidence, and a full disk (ENOSPC) must not
            # turn a post-crash restart into a startup failure
            k = 0
            while self.fs.exists(f"{path}.corrupt.{k}"):
                k += 1
            tail_path = f"{path}.corrupt.{k}"
            try:
                with self.fs.open(path, "rb") as src:
                    src.seek(valid_end)
                    tail = src.read(size - valid_end)
                with self.fs.open(tail_path, "wb") as dst:
                    dst.write(tail)
                    self.fs.fsync(dst)
            except OSError as e:
                self.logger.warning(
                    "WAL repair: could not salvage damaged tail of %s "
                    "to %s (%r); truncating anyway", path, tail_path, e,
                )
                try:
                    if self.fs.exists(tail_path):
                        self.fs.remove(tail_path)  # no partial salvage litter
                except OSError:
                    pass
                tail_path = ""
            self.fs.truncate(path, valid_end)
            record_storage("wal_repairs")
            record_storage("wal_truncated_bytes", size - valid_end)
            rep = WALRepair(path, valid_end, size, n, tail_path, reason)
            repairs.append(rep)
            self.logger.warning(
                "WAL repair: %s at %s:%d — truncated %d damaged byte(s) to "
                "the last whole record (#%d), tail saved to %s",
                reason, path, valid_end, size - valid_end, n, tail_path,
            )
        return repairs

    def search_for_end_height(self, height: int) -> list[WALRecord] | None:
        """Messages recorded after `#ENDHEIGHT: height` (reference
        wal.go:231 SearchForEndHeight) — i.e. everything belonging to
        height+1. Returns None if the marker is absent. Height 0 matches
        the start of the log (fresh chain)."""
        if height == 0:
            found = True
            out: list[WALRecord] = []
        else:
            found = False
            out = []
        for rec in self.iter_records():
            if rec.kind == KIND_END_HEIGHT:
                if rec.height == height:
                    found = True
                    out = []
                elif found and rec.height > height:
                    # next height completed too; keep collecting — replay
                    # handles duplicates idempotently
                    pass
                continue
            if found:
                out.append(rec)
        return out if found else None
