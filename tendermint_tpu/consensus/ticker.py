"""Timeout scheduling (reference internal/consensus/ticker.go:17).

One pending timeout at a time: scheduling a new timeout for a later
(height, round, step) replaces the pending one; stale schedules (for an
earlier HRS than the pending) are ignored. Fired timeouts are delivered
as `TimeoutInfo` on `tock` — the consensus state machine consumes them
exactly like the reference's tockChan."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from ..libs.clock import SYSTEM, Clock
from .types import RoundStep


@dataclass(frozen=True)
class TimeoutInfo:
    duration_ns: int
    height: int
    round: int
    step: RoundStep

    def hrs(self):
        return (self.height, self.round, self.step)


class TimeoutTicker:
    def __init__(self, tock: "asyncio.Queue | None" = None, clock: Clock | None = None):
        # fired timeouts are delivered here; the consensus SM passes its
        # merged input queue. The clock scales timeout durations: a
        # drifting validator (libs/clock.SkewedClock with rate != 1)
        # fires its consensus timeouts early/late, which is exactly the
        # fault the chaos clock-skew class wants to exercise.
        self.tock: asyncio.Queue = tock if tock is not None else asyncio.Queue()
        self.clock = clock or SYSTEM
        self._pending: TimeoutInfo | None = None
        self._timer: asyncio.TimerHandle | None = None

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace the pending timeout if ti is for a later-or-equal HRS
        (reference ticker.go timeoutRoutine: newer HRS wins; older is
        ignored)."""
        if self._pending is not None and ti.hrs() < self._pending.hrs():
            return
        self._cancel()
        self._pending = ti
        loop = asyncio.get_running_loop()
        self._timer = loop.call_later(self.clock.timeout_s(ti.duration_ns), self._fire, ti)

    def _fire(self, ti: TimeoutInfo) -> None:
        if self._pending is ti:
            self._pending = None
            self._timer = None
        self.tock.put_nowait(ti)

    def _cancel(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._pending = None

    def stop(self) -> None:
        self._cancel()
