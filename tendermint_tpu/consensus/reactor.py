"""Consensus reactor (reference internal/consensus/reactor.go).

Four wire channels (reactor.go:84-87):
  0x20 state — NewRoundStep / NewValidBlock / HasVote / VoteSetMaj23
  0x21 data  — Proposal / ProposalPOL / BlockPart
  0x22 vote  — Vote
  0x23 vote-set-bits — VoteSetBits

Per-peer gossip tasks mirror the reference's three goroutines
(gossipDataRoutine :519, gossipVotesRoutine :731, queryMaj23Routine
:813): each loops over the local RoundState vs the tracked PeerState and
sends exactly what the peer is missing."""

from __future__ import annotations

import asyncio
import logging

from ..libs import trace
from ..libs.clock import SYSTEM
from ..libs.service import Service
from ..p2p.peermanager import PeerStatus
from ..p2p.router import Channel
from ..p2p.types import Envelope, PeerError
from ..types.block import Commit
from ..types.keys import SignedMsgType
from ..types.vote import Vote

from . import messages as m
from .peer_state import PeerState
from .state import ConsensusState
from .types import RoundStep

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

GOSSIP_SLEEP = 0.05  # reference peerGossipSleepDuration=100ms; we poll faster
QUERY_MAJ23_SLEEP = 2.0
# votes per VoteBatch frame: per-envelope overhead (framing + queue
# hops + task wakeups) dominates committee-scale gossip, so missing
# votes ship in batches instead of one frame each
VOTE_GOSSIP_BATCH = 32
# have-vote hints are coalesced for this long before one batched
# broadcast (advisory traffic: a slightly stale hint only risks a
# duplicate send, which the receiver's VoteSet dedups)
HAS_VOTE_FLUSH_S = 0.05
# default catch-up token-bucket burst (items = votes or block parts):
# one full commit's worth of votes at committee scale, so a single
# freshly-healed laggard still catches a whole height per tick while a
# SUSTAINED lag storm (many laggards, or byzantine peers lying about
# their height to bait catch-up service) degrades to the refill rate
CATCHUP_BURST = 4 * 32


class _CatchupBucket:
    """Per-peer token bucket for catch-up service (ROADMAP: straggler
    catch-up at 150 validators costs the donor 1-3 min of loop share —
    and consensus/byzantine.py's lying_frames strategy manufactures
    laggards on purpose). One token = one sent item (a commit vote or a
    stored block part). Pure function of (rate, burst, now): callers
    pass the injected clock's monotonic reading, so the bucket is
    deterministic under test clocks and never reads wall time."""

    def __init__(self, rate: float, burst: int, now: float):
        self.rate = rate
        self.burst = max(1, burst)
        self.tokens = float(self.burst)
        self.last = now

    def grant(self, want: int, now: float) -> int:
        self.tokens = min(
            float(self.burst), self.tokens + max(0.0, now - self.last) * self.rate
        )
        self.last = now
        got = min(want, int(self.tokens))
        self.tokens -= got
        return got


class ConsensusReactor(Service):
    def __init__(
        self,
        cs: ConsensusState,
        state_ch: Channel,
        data_ch: Channel,
        vote_ch: Channel,
        bits_ch: Channel,
        peer_updates: asyncio.Queue,
        *,
        logger: logging.Logger | None = None,
        gossip_sleep: float = GOSSIP_SLEEP,
        stall_refresh_s: float | None = None,
        catchup_rate: float | None = None,
        catchup_burst: int | None = None,
    ):
        super().__init__("cs-reactor", logger)
        self.cs = cs
        # per-peer catch-up pacing: rate = items/s a single lagging peer
        # may draw from this node's stores (None = unlimited, the
        # pre-pacing behavior small nets keep). Bounds the donor's loop
        # share during lag storms; the laggard's recovery speed then
        # comes from MANY donors, each serving its bounded slice.
        self.catchup_rate = catchup_rate
        self.catchup_burst = (
            catchup_burst if catchup_burst is not None else CATCHUP_BURST
        )
        self._catchup_buckets: dict[str, _CatchupBucket] = {}
        # per-peer gossip poll interval: large router-chaos nets (50-150
        # validators x degree-k topologies) raise it so thousands of
        # gossip tasks don't saturate the loop with 20 Hz wakeups
        self.gossip_sleep = gossip_sleep
        if stall_refresh_s is not None:
            self.STALL_REFRESH_S = stall_refresh_s
        self.state_ch = state_ch
        self.data_ch = data_ch
        self.vote_ch = vote_ch
        self.bits_ch = bits_ch
        self.peer_updates = peer_updates
        self.peers: dict[str, PeerState] = {}
        self._peer_tasks: dict[str, list[asyncio.Task]] = {}
        self._hasvote_buf: list[m.HasVoteMessage] = []

    # -- lifecycle -------------------------------------------------------

    async def on_start(self) -> None:
        self.cs.step_hook = self._on_new_step
        self.cs.broadcast_hook = self._on_broadcast
        self.cs.invalid_sig_hook = self._on_invalid_sig
        self.spawn(self._process_peer_updates(), name="csr.peers")
        self.spawn(self._process_state_ch(), name="csr.state")
        self.spawn(self._process_data_ch(), name="csr.data")
        self.spawn(self._process_vote_ch(), name="csr.vote")
        self.spawn(self._process_bits_ch(), name="csr.bits")
        self.spawn(self._flush_has_votes(), name="csr.hasvote")

    async def on_stop(self) -> None:
        self.cs.step_hook = None
        self.cs.broadcast_hook = None
        self.cs.invalid_sig_hook = None
        for tasks in self._peer_tasks.values():
            for t in tasks:
                t.cancel()

    # -- hooks from the state machine -----------------------------------

    def _new_round_step_msg(self) -> m.NewRoundStepMessage:
        rs = self.cs.rs
        return m.NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=int(rs.step),
            seconds_since_start_time=max(
                0, int((self.cs.clock.now_ns() - rs.start_time_ns) / 1e9)
            ),
            last_commit_round=rs.last_commit.round if rs.last_commit else -1,
        )

    def _on_new_step(self, rs) -> None:
        self._send_nowait(
            self.state_ch, Envelope(STATE_CHANNEL, self._new_round_step_msg(), broadcast=True)
        )

    def _on_broadcast(self, msg) -> None:
        """Out-of-band broadcasts from the SM: HasVote/NewValidBlock go to
        the state channel; proposal/parts/votes are handled by gossip
        (but broadcasting them too cuts a round-trip on small nets).
        HasVote is pure advisory traffic and the SM emits one per added
        vote — O(validators) per height — so it is coalesced and flushed
        as a single HasVoteBatch frame (`_flush_has_votes`)."""
        if isinstance(msg, m.HasVoteMessage):
            if len(self._hasvote_buf) < 8192:  # bounded: hints are lossy
                self._hasvote_buf.append(msg)
            return
        if isinstance(msg, m.NewValidBlockMessage):
            self._send_nowait(self.state_ch, Envelope(STATE_CHANNEL, msg, broadcast=True))

    async def _flush_has_votes(self) -> None:
        while True:
            await asyncio.sleep(HAS_VOTE_FLUSH_S)
            if not self._hasvote_buf:
                continue
            buf, self._hasvote_buf = self._hasvote_buf, []
            for i in range(0, len(buf), m.MAX_BATCH_VOTES):
                chunk = buf[i : i + m.MAX_BATCH_VOTES]
                msg = (
                    chunk[0]
                    if len(chunk) == 1
                    else m.HasVoteBatchMessage(tuple(chunk))
                )
                self._send_nowait(
                    self.state_ch, Envelope(STATE_CHANNEL, msg, broadcast=True)
                )

    def _send_nowait(self, ch: Channel, env: Envelope) -> None:
        try:
            ch.out_q.put_nowait(env)
        except asyncio.QueueFull:
            self.logger.warning("dropping outbound on %s: full", ch.name)

    def _on_invalid_sig(self, peer_id: str, vote) -> None:
        """The ingest pipeline disproved a peer-supplied vote signature.
        Before pipelining this was swallowed inside the apply-time
        VoteSetError; now the peer gets reported to the peer manager
        (score/ban) like any other protocol violation."""
        self.spawn(
            self.vote_ch.error(
                PeerError(
                    peer_id,
                    f"invalid vote signature (h={vote.height} r={vote.round} "
                    f"val={vote.validator_index})",
                )
            ),
            name="csr.badsig",
        )

    # -- peer lifecycle --------------------------------------------------

    async def _process_peer_updates(self) -> None:
        while True:
            upd = await self.peer_updates.get()
            if upd.status == PeerStatus.UP:
                if upd.node_id in self.peers:
                    continue
                ps = PeerState(upd.node_id)
                self.peers[upd.node_id] = ps
                self._peer_tasks[upd.node_id] = [
                    self.spawn(self._gossip_data(ps), name=f"csr.gd.{upd.node_id[:8]}"),
                    self.spawn(self._gossip_votes(ps), name=f"csr.gv.{upd.node_id[:8]}"),
                    self.spawn(self._query_maj23(ps), name=f"csr.qm.{upd.node_id[:8]}"),
                ]
                # tell the new peer where we are
                self._send_nowait(
                    self.state_ch,
                    Envelope(STATE_CHANNEL, self._new_round_step_msg(), to=upd.node_id),
                )
            else:
                self.peers.pop(upd.node_id, None)
                self._catchup_buckets.pop(upd.node_id, None)
                for t in self._peer_tasks.pop(upd.node_id, []):
                    t.cancel()

    def _catchup_grant(self, peer_id: str, want: int) -> int:
        """How many catch-up items (commit votes / stored parts) this
        peer may be served right now. Unlimited when pacing is off."""
        if self.catchup_rate is None or want <= 0:
            return want
        now = self.cs.clock.monotonic()
        bucket = self._catchup_buckets.get(peer_id)
        if bucket is None:
            bucket = _CatchupBucket(self.catchup_rate, self.catchup_burst, now)
            self._catchup_buckets[peer_id] = bucket
        return bucket.grant(want, now)

    # -- inbound processing ---------------------------------------------

    async def _process_state_ch(self) -> None:
        async for env in self.state_ch:
            ps = self.peers.get(env.from_)
            if ps is None:
                continue
            msg = env.message
            try:
                if isinstance(msg, m.NewRoundStepMessage):
                    ps.apply_new_round_step(msg)
                elif isinstance(msg, m.NewValidBlockMessage):
                    ps.apply_new_valid_block(msg)
                elif isinstance(msg, m.HasVoteMessage):
                    ps.apply_has_vote(msg)
                elif isinstance(msg, m.HasVoteBatchMessage):
                    for entry in msg.entries:
                        ps.apply_has_vote(entry)
                elif isinstance(msg, m.VoteSetMaj23Message):
                    await self._handle_vote_set_maj23(env.from_, msg)
            except Exception as e:
                await self.state_ch.error(PeerError(env.from_, f"state msg: {e!r}"))

    async def _handle_vote_set_maj23(self, peer_id: str, msg) -> None:
        """Record the claim, reply with our bits for that (round, type,
        block) (reference handleStateMessage VoteSetMaj23)."""
        rs = self.cs.rs
        if rs.height != msg.height or rs.votes is None:
            return
        rs.votes.set_peer_maj23(msg.round, msg.type, peer_id, msg.block_id)
        vs = (
            rs.votes.prevotes(msg.round)
            if msg.type == SignedMsgType.PREVOTE
            else rs.votes.precommits(msg.round)
        )
        if vs is None:
            return
        bits = vs.bit_array_by_block_id(msg.block_id)
        if bits is None:
            from ..libs.bits import BitArray

            bits = BitArray(vs.size())
        self._send_nowait(
            self.bits_ch,
            Envelope(
                VOTE_SET_BITS_CHANNEL,
                m.VoteSetBitsMessage(msg.height, msg.round, msg.type, msg.block_id, bits),
                to=peer_id,
            ),
        )

    def _start_trace(self, env):
        """Open the end-to-end trace at the gossip edge. The router
        stamped `env.recv_at` as the bytes came off the wire; the
        p2p.receive span (recorded by the caller after the hand-off)
        therefore covers decode + channel-queue wait + ingest
        backpressure."""
        return trace.start(self.cs.clock)

    def _finish_receive(self, ctx, env, channel: str) -> None:
        if ctx is None:
            return
        # recv_at was stamped by the router on the SYSTEM monotonic
        # domain; this node's clock may be rate-scaled (chaos drift), so
        # measure the duration purely in the SYSTEM domain and anchor it
        # ending at the trace clock's "now" — mixing the two domains in
        # one subtraction would corrupt the duration by (rate-1)*uptime
        now = self.cs.clock.monotonic()
        dur = max(0.0, SYSTEM.monotonic() - env.recv_at) if env.recv_at else 0.0
        trace.record(
            ctx, "p2p", "receive", now - dur, now,
            channel=channel, peer=env.from_[:8],
        )

    async def _process_data_ch(self) -> None:
        async for env in self.data_ch:
            ps = self.peers.get(env.from_)
            msg = env.message
            try:
                if isinstance(msg, m.ProposalMessage):
                    if ps is not None:
                        ps.set_has_proposal(msg.proposal)
                    ctx = self._start_trace(env)
                    await self.cs.add_proposal(msg.proposal, env.from_, trace_ctx=ctx)
                    self._finish_receive(ctx, env, "data")
                elif isinstance(msg, m.ProposalPOLMessage):
                    if ps is not None:
                        ps.apply_proposal_pol(msg)
                elif isinstance(msg, m.BlockPartMessage):
                    if ps is not None:
                        ps.set_has_proposal_block_part(msg.height, msg.round, msg.part.index)
                    ctx = self._start_trace(env)
                    await self.cs.add_block_part(
                        msg.height, msg.round, msg.part, env.from_, trace_ctx=ctx
                    )
                    self._finish_receive(ctx, env, "data")
            except Exception as e:
                await self.data_ch.error(PeerError(env.from_, f"data msg: {e!r}"))

    async def _process_vote_ch(self) -> None:
        async for env in self.vote_ch:
            msg = env.message
            if isinstance(msg, m.VoteMessage):
                votes = (msg.vote,)
            elif isinstance(msg, m.VoteBatchMessage):
                votes = msg.votes
            else:
                continue
            # a decoded-but-garbage vote (corrupt frame that survived the
            # codec) must cost the PEER, never the channel task: an
            # uncaught error here would kill csr.vote and wedge the node
            # for every honest peer too
            try:
                ps = self.peers.get(env.from_)
                first = True
                for v in votes:
                    if v.validator_index > m.MAX_WIRE_INDEX:
                        # same wire bound the HasVote decoder enforces:
                        # peer bookkeeping must not grow bit arrays from
                        # an unvalidated index before add_vote rejects it
                        raise ValueError(
                            f"vote validator_index {v.validator_index} "
                            f"exceeds {m.MAX_WIRE_INDEX}"
                        )
                    if ps is not None:
                        ps.set_has_vote(v.height, v.round, v.type, v.validator_index)
                    ctx = self._start_trace(env)
                    await self.cs.add_vote(v, env.from_, trace_ctx=ctx)
                    if first:
                        # the decode+queue-wait window is per ENVELOPE:
                        # recording it on every vote of a batch would
                        # attribute the same wall time up to 32x
                        self._finish_receive(ctx, env, "vote")
                        first = False
            except Exception as e:
                await self.vote_ch.error(PeerError(env.from_, f"vote msg: {e!r}"))

    async def _process_bits_ch(self) -> None:
        async for env in self.bits_ch:
            msg = env.message
            if not isinstance(msg, m.VoteSetBitsMessage):
                continue
            ps = self.peers.get(env.from_)
            if ps is None:
                continue
            try:
                # authoritative reconciliation (reference
                # handleVoteSetBitsMessage): the reply REPLACES our view
                # of the peer's votes for the queried round — clearing
                # has-vote false positives (corrupt-frame HasVotes, sends
                # the wire ate) that one-way OR bookkeeping keeps forever
                our_votes = None
                rs = self.cs.rs
                if rs.height == msg.height and rs.votes is not None:
                    vs = (
                        rs.votes.prevotes(msg.round)
                        if msg.type == SignedMsgType.PREVOTE
                        else rs.votes.precommits(msg.round)
                    )
                    if vs is not None:
                        our_votes = vs.bit_array_by_block_id(msg.block_id)
                ps.apply_vote_set_bits(msg, our_votes)
            except Exception as e:
                await self.bits_ch.error(PeerError(env.from_, f"bits msg: {e!r}"))

    # -- gossip routines -------------------------------------------------

    async def _gossip_data(self, ps: PeerState) -> None:
        """Reference gossipDataRoutine reactor.go:519."""
        while True:
            rs = self.cs.rs
            prs = ps.prs
            sent = False
            if rs.height == prs.height and rs.proposal_block_parts is not None:
                sent = self._send_missing_part(ps)
            if not sent and rs.height == prs.height and rs.proposal is not None and not prs.proposal:
                ps.set_has_proposal(rs.proposal)
                self._send_nowait(
                    self.data_ch,
                    Envelope(DATA_CHANNEL, m.ProposalMessage(rs.proposal), to=ps.peer_id),
                )
                if rs.proposal.pol_round >= 0:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        self._send_nowait(
                            self.data_ch,
                            Envelope(
                                DATA_CHANNEL,
                                m.ProposalPOLMessage(
                                    rs.height,
                                    rs.proposal.pol_round,
                                    pol.votes_bit_array.copy(),
                                ),
                                to=ps.peer_id,
                            ),
                        )
                sent = True
            if not sent and 0 < prs.height < rs.height:
                sent = self._send_catchup_part(ps)
            if not sent:
                await asyncio.sleep(self.gossip_sleep)
            else:
                await asyncio.sleep(0)

    def _send_missing_part(self, ps: PeerState) -> bool:
        rs = self.cs.rs
        prs = ps.prs
        if prs.proposal_block_parts is None:
            return False
        ours = rs.proposal_block_parts.parts_bit_array
        theirs = prs.proposal_block_parts
        missing = ours.sub(theirs)
        idx = missing.pick_random()
        if idx is None:
            return False
        part = rs.proposal_block_parts.get_part(idx)
        if part is None:
            return False
        ps.set_has_proposal_block_part(prs.height, prs.round, idx)
        self._send_nowait(
            self.data_ch,
            Envelope(DATA_CHANNEL, m.BlockPartMessage(prs.height, prs.round, part), to=ps.peer_id),
        )
        return True

    def _send_catchup_part(self, ps: PeerState) -> bool:
        """Peer is on an earlier height: serve stored block parts
        (reference gossipDataForCatchup reactor.go:577)."""
        prs = ps.prs
        meta = self.cs.block_store.load_block_meta(prs.height)
        if meta is None:
            return False
        psh = meta.block_id.part_set_header
        if prs.proposal_block_parts is None or prs.proposal_block_parts_header != (
            psh.total,
            psh.hash,
        ):
            from ..libs.bits import BitArray

            prs.proposal_block_parts_header = (psh.total, psh.hash)
            prs.proposal_block_parts = BitArray(psh.total)
        # batched: send every part the peer is missing in one sweep (a
        # catching-up peer must outpace live block production) — capped
        # by the per-peer catch-up bucket so a lag storm cannot turn
        # this sweep into the donor's whole loop share
        missing = prs.proposal_block_parts.not_().true_indices()
        grant = self._catchup_grant(ps.peer_id, len(missing))
        sent = False
        for idx in missing:
            if grant <= 0:
                break
            part = self.cs.block_store.load_block_part(prs.height, idx)
            if part is None:
                continue
            grant -= 1
            prs.proposal_block_parts.set(idx, True)
            self._send_nowait(
                self.data_ch,
                Envelope(
                    DATA_CHANNEL,
                    m.BlockPartMessage(prs.height, prs.round, part),
                    to=ps.peer_id,
                ),
            )
            sent = True
        return sent

    # a peer link with BOTH round states static and nothing to send for
    # this long is presumed poisoned (a send-marked frame the wire ate:
    # chaos drop/corruption, or a queue-full drop) — reset the gossip
    # marks and re-offer. Only a stalled link pays the duplicate cost,
    # and consecutive refreshes without progress back off exponentially:
    # at 50-150 validators a refresh re-offers ~2 votes/validator per
    # link, and a 1s refresh cadence across hundreds of links turns the
    # cure into a resend storm that starves the very delivery it is
    # trying to restart (measured: >2k duplicate vote sends/s, loop
    # saturated, zero progress).
    STALL_REFRESH_S = 1.0
    STALL_REFRESH_MAX_BACKOFF = 4  # cap: threshold * 2**4

    async def _gossip_votes(self, ps: PeerState) -> None:
        """Reference gossipVotesRoutine reactor.go:731, plus the
        stall-refresh: the routines mark items delivered at SEND time,
        so a lossy byte path can leave has-marks for frames that never
        arrived; when the link is wedged-idle we forget the marks and
        let receiver-side dedup absorb the re-sends."""
        last_sig = None
        idle = 0
        last_lag_sig = None
        lag_idle = 0
        refreshes = 0  # consecutive refreshes with no progress since
        while True:
            rs = self.cs.rs
            prs = ps.prs
            sent = False
            if rs.height == prs.height:
                sent = self._gossip_votes_same_height(ps)
            elif prs.height != 0 and rs.height == prs.height + 1 and rs.last_commit is not None:
                sent = self._pick_send_vote(ps, rs.last_commit)
            elif (
                prs.height != 0
                and rs.height >= prs.height + 2
                and self.cs.block_store.base() <= prs.height <= self.cs.block_store.height()
            ):
                commit = self.cs.block_store.load_block_commit(prs.height)
                if commit is not None:
                    sent = self._send_catchup_commit_vote(ps, commit)
            # stall detection, two distinct wedge shapes:
            #  * committee wedge — EVERYTHING static (our round state and
            #    the peer's) and nothing to send: some send-marked frame
            #    never arrived (chaos drop/corruption/queue-full);
            #  * starved laggard — the peer sits BEHIND us and doesn't
            #    move while we have "already sent" catch-up marks: those
            #    marks were set while the link was partitioned/lossy
            #    (gossip marks at SEND time, delivery was never
            #    confirmed), and since WE keep committing, only a
            #    peer-scoped trigger can notice.
            sig = (rs.height, rs.round, int(rs.step), prs.height, prs.round, prs.step)
            # the starved-laggard signature is the peer's HEIGHT alone:
            # a laggard whose catch-up frames were eaten keeps churning
            # ROUNDS on its own timeouts (it can never quorum a stale
            # height by itself), and a (height, round, step) signature
            # reads that churn as progress — the refresh then never
            # fires and the mark-poisoned link starves the peer for as
            # long as the rounds keep turning (surfaced by the byz
            # full-taxonomy matrix: a healed one-way-partition victim
            # wedged at its old height while round-cycling)
            lag_sig = prs.height
            if sent:
                # sending resets the idle clocks but NOT the backoff: a
                # refresh's own re-offers count as sends, so resetting
                # `refreshes` here would re-arm the base cadence after
                # every refresh and a permanently deaf link would eat
                # full-commit resends at base rate forever. Only
                # OBSERVED round-state progress (sig change below)
                # re-arms fast refresh.
                idle = lag_idle = 0
                last_sig, last_lag_sig = sig, lag_sig
                await asyncio.sleep(0)
                continue
            if sig != last_sig:
                refreshes = 0  # progress somewhere: re-arm fast refresh
            idle = idle + 1 if sig == last_sig else 0
            lag_behind = 0 < prs.height < rs.height
            lag_idle = lag_idle + 1 if (lag_sig == last_lag_sig and lag_behind) else 0
            last_sig, last_lag_sig = sig, lag_sig
            threshold = self.STALL_REFRESH_S * (
                2 ** min(refreshes, self.STALL_REFRESH_MAX_BACKOFF)
            )
            stalled = idle * self.gossip_sleep >= threshold
            starved = lag_idle * self.gossip_sleep >= threshold
            if stalled or starved:
                refreshes += 1
                ps.reset_gossip_marks()
                # and re-exchange round state: NewRoundStep is only
                # broadcast on step CHANGES, so one queue-full/chaos
                # drop leaves the peer's view of us stale forever — and
                # an idle-wedged committee produces no step changes to
                # fix it. The peer's own stall-refresh answers with its
                # HRS, un-staling our prs in the same cycle.
                self._send_nowait(
                    self.state_ch,
                    Envelope(
                        STATE_CHANNEL, self._new_round_step_msg(), to=ps.peer_id
                    ),
                )
                idle = lag_idle = 0
            await asyncio.sleep(self.gossip_sleep)

    def _gossip_votes_same_height(self, ps: PeerState) -> bool:
        rs = self.cs.rs
        prs = ps.prs
        # last commit first (peer may still be finishing the previous height)
        if prs.step == int(RoundStep.NEW_HEIGHT) and rs.last_commit is not None:
            if self._pick_send_vote(ps, rs.last_commit):
                return True
        # POL prevotes
        if prs.proposal_pol_round != -1 and prs.proposal_pol_round <= rs.round:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and self._pick_send_vote(ps, pol):
                return True
        if prs.round != -1 and prs.round <= rs.round:
            if self._pick_send_vote(ps, rs.votes.prevotes(prs.round)):
                return True
            if self._pick_send_vote(ps, rs.votes.precommits(prs.round)):
                return True
        return False

    def _pick_send_vote(self, ps: PeerState, votes) -> bool:
        """Ship up to VOTE_GOSSIP_BATCH missing votes in one frame
        (reference PickSendVote sends one; batching is the in-process
        scale adaptation — per-envelope overhead is the gossip cost)."""
        picked = ps.pick_votes_to_send(votes, VOTE_GOSSIP_BATCH)
        if not picked:
            return False
        for vote in picked:
            ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
        msg = (
            m.VoteMessage(picked[0])
            if len(picked) == 1
            else m.VoteBatchMessage(tuple(picked))
        )
        self._send_nowait(
            self.vote_ch, Envelope(VOTE_CHANNEL, msg, to=ps.peer_id)
        )
        return True

    def _send_catchup_commit_vote(self, ps: PeerState, commit: Commit) -> bool:
        """Send ALL missing precommits of a stored commit at once — a peer
        catching up must close the gap faster than blocks are produced,
        so catch-up gossip is batched rather than one-vote-per-tick."""
        prs = ps.prs
        ps.ensure_catchup_commit(prs.height, commit.round, len(commit.signatures))
        have = prs.catchup_commit
        # per-peer pacing: only votes actually granted get their "sent"
        # mark — an over-budget remainder stays unmarked and ships on a
        # later tick once the bucket refills
        budget = self._catchup_grant(
            ps.peer_id,
            sum(
                1
                for idx, cs_ in enumerate(commit.signatures)
                if not cs_.is_absent() and not have.get(idx)
            ),
        )
        pending: list[Vote] = []
        for idx, cs_ in enumerate(commit.signatures):
            if len(pending) >= budget:
                break
            if cs_.is_absent() or have.get(idx):
                continue
            pending.append(
                Vote(
                    type=SignedMsgType.PRECOMMIT,
                    height=commit.height,
                    round=commit.round,
                    block_id=cs_.block_id(commit.block_id),
                    timestamp_ns=cs_.timestamp_ns,
                    validator_address=cs_.validator_address,
                    validator_index=idx,
                    signature=cs_.signature,
                )
            )
            have.set(idx, True)
        for i in range(0, len(pending), VOTE_GOSSIP_BATCH):
            chunk = pending[i : i + VOTE_GOSSIP_BATCH]
            msg = (
                m.VoteMessage(chunk[0])
                if len(chunk) == 1
                else m.VoteBatchMessage(tuple(chunk))
            )
            self._send_nowait(
                self.vote_ch, Envelope(VOTE_CHANNEL, msg, to=ps.peer_id)
            )
        return bool(pending)

    async def _query_maj23(self, ps: PeerState) -> None:
        """Reference queryMaj23Routine reactor.go:813: periodically tell
        peers which majorities we see so they can send us missing votes."""
        while True:
            await asyncio.sleep(QUERY_MAJ23_SLEEP)
            rs = self.cs.rs
            prs = ps.prs
            if rs.height != prs.height:
                # catch-up half (reference reactor.go:846): a laggard can
                # only ADMIT the catch-up precommits `_gossip_votes` sends
                # it if the stored commit's round is open in its
                # HeightVoteSet — rounds beyond its round+1 need a peer
                # maj23 claim (set_peer_maj23). Without this, a peer that
                # fell behind while the committee decided in a late round
                # drops every rescue vote and wedges forever.
                if (
                    prs.height != 0
                    and self.cs.block_store.base()
                    <= prs.height
                    <= self.cs.block_store.height()
                ):
                    commit = self.cs.block_store.load_block_commit(
                        prs.height
                    ) or self.cs.block_store.load_seen_commit(prs.height)
                    if commit is not None:
                        self._send_nowait(
                            self.state_ch,
                            Envelope(
                                STATE_CHANNEL,
                                m.VoteSetMaj23Message(
                                    prs.height,
                                    commit.round,
                                    SignedMsgType.PRECOMMIT,
                                    commit.block_id,
                                ),
                                to=ps.peer_id,
                            ),
                        )
                continue
            if rs.votes is None:
                continue
            # reference reactor.go:820-846 — claim the majorities we see
            # in OUR round, the peer's round, and the peer's POL round;
            # the VoteSetBits replies these trigger reconcile our view of
            # the peer (apply_vote_set_bits), so a poisoned has-vote mark
            # heals within one query cycle
            queries = {(rs.round, SignedMsgType.PREVOTE),
                       (rs.round, SignedMsgType.PRECOMMIT)}
            if prs.round >= 0:
                queries.add((prs.round, SignedMsgType.PREVOTE))
                queries.add((prs.round, SignedMsgType.PRECOMMIT))
            if prs.proposal_pol_round >= 0:
                queries.add((prs.proposal_pol_round, SignedMsgType.PREVOTE))
            for round_, type_ in sorted(queries):
                vs = (
                    rs.votes.prevotes(round_)
                    if type_ == SignedMsgType.PREVOTE
                    else rs.votes.precommits(round_)
                )
                if vs is None:
                    continue
                maj = vs.two_thirds_majority()
                if maj is not None:
                    self._send_nowait(
                        self.state_ch,
                        Envelope(
                            STATE_CHANNEL,
                            m.VoteSetMaj23Message(rs.height, round_, type_, maj),
                            to=ps.peer_id,
                        ),
                    )
