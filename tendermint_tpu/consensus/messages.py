"""Consensus protocol messages (reference proto/tendermint/consensus/types.proto
and internal/consensus/msgs.go).

One union envelope `Message` with a type tag; used both on the wire
(reactor channels) and in the WAL (wrapped in MsgInfo with the peer id,
or TimeoutInfo for timer ticks — reference wal.go WALMessage)."""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..libs import protoenc as pe
from ..libs.bits import BitArray
from ..types.block import BlockID, NIL_BLOCK_ID
from ..types.keys import SignedMsgType
from ..types.part_set import Part
from ..types.vote import Proposal, Vote
from .ticker import TimeoutInfo
from .types import RoundStep

# message type tags (stable wire ids)
T_NEW_ROUND_STEP = 1
T_NEW_VALID_BLOCK = 2
T_PROPOSAL = 3
T_PROPOSAL_POL = 4
T_BLOCK_PART = 5
T_VOTE = 6
T_HAS_VOTE = 7
T_VOTE_SET_MAJ23 = 8
T_VOTE_SET_BITS = 9
T_VOTE_BATCH = 10
T_HAS_VOTE_BATCH = 11

# WAL record tags
W_MSG_INFO = 1
W_TIMEOUT = 2


@dataclass(frozen=True)
class NewRoundStepMessage:
    """Peer's current HRS (reference msgs: NewRoundStep, gossiped on the
    state channel every step change)."""

    height: int
    round: int
    step: int
    seconds_since_start_time: int
    last_commit_round: int


@dataclass(frozen=True)
class NewValidBlockMessage:
    height: int
    round: int
    block_part_set_header: tuple[int, bytes]
    block_parts: BitArray
    is_commit: bool


@dataclass(frozen=True)
class ProposalMessage:
    proposal: Proposal


@dataclass(frozen=True)
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: BitArray


@dataclass(frozen=True)
class BlockPartMessage:
    height: int
    round: int
    part: Part


@dataclass(frozen=True)
class VoteMessage:
    vote: Vote


@dataclass(frozen=True)
class VoteBatchMessage:
    """Several votes in one frame. Committee-scale gossip is dominated
    by per-message overhead (framing + four queue hops + a task wakeup
    per envelope); at 150 validators a height moves ~45k single-vote
    envelopes per node-neighborhood, and batching them 32:1 is the
    difference between a soak that converges and one that starves. The
    receiver splits the batch back into individual `add_vote` calls, so
    the SM/WAL path is unchanged."""

    votes: tuple


@dataclass(frozen=True)
class HasVoteMessage:
    height: int
    round: int
    type: SignedMsgType
    index: int


@dataclass(frozen=True)
class HasVoteBatchMessage:
    """Coalesced have-vote hints. The SM announces every added vote;
    at committee scale that is O(validators) broadcasts per height per
    node — pure advisory traffic — so the reactor buffers them briefly
    and ships one frame (see ConsensusReactor._flush_has_votes)."""

    entries: tuple  # of HasVoteMessage


@dataclass(frozen=True)
class VoteSetMaj23Message:
    height: int
    round: int
    type: SignedMsgType
    block_id: BlockID


@dataclass(frozen=True)
class VoteSetBitsMessage:
    height: int
    round: int
    type: SignedMsgType
    block_id: BlockID
    votes: BitArray


Message = (
    NewRoundStepMessage
    | NewValidBlockMessage
    | ProposalMessage
    | ProposalPOLMessage
    | BlockPartMessage
    | VoteMessage
    | VoteBatchMessage
    | HasVoteMessage
    | HasVoteBatchMessage
    | VoteSetMaj23Message
    | VoteSetBitsMessage
)

# batch frames are size-bounded at decode like every other wire field:
# a corrupt count must cost the sender its connection, not an allocation
MAX_BATCH_VOTES = 1024


# Wire-side sanity bounds. These messages arrive from untrusted peers
# and — under the chaos matrix — from CORRUPTED frames that still parse:
# a flipped byte in a varint can turn a 150-validator bit array into a
# 2^40-bit allocation request. Anything beyond these caps is malformed
# by construction (validator sets and part sets are orders of magnitude
# smaller), raises ValueError, and costs the sender its connection.
MAX_WIRE_BITS = 1 << 20  # vote-set / part-set bit arrays
MAX_WIRE_INDEX = 1 << 20  # has-vote validator indices


def _encode_has_vote_body(msg: "HasVoteMessage") -> bytes:
    return (
        pe.varint_field(1, msg.height)
        + pe.varint_field(2, msg.round)
        + pe.varint_field(3, int(msg.type))
        + pe.varint_field(4, msg.index + 1)
    )


def _decode_has_vote_body(body: bytes) -> "HasVoteMessage":
    br = pe.Reader(body)
    kw = dict(height=0, round=0, type=SignedMsgType.UNKNOWN, index=-1)
    while not br.eof():
        bf, bwt = br.read_tag()
        if bf == 1:
            kw["height"] = br.read_uvarint()
        elif bf == 2:
            kw["round"] = br.read_uvarint()
        elif bf == 3:
            kw["type"] = SignedMsgType(br.read_uvarint())
        elif bf == 4:
            kw["index"] = br.read_uvarint() - 1
        else:
            br.skip(bwt)
    if kw["index"] > MAX_WIRE_INDEX:
        raise ValueError(
            f"has-vote index {kw['index']} exceeds {MAX_WIRE_INDEX}"
        )
    return HasVoteMessage(**kw)


def _encode_bits(ba: BitArray) -> bytes:
    return pe.varint_field(1, len(ba)) + pe.bytes_field(2, ba.to_bytes())


def _decode_bits(data: bytes) -> BitArray:
    r = pe.Reader(data)
    n, raw = 0, b""
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            n = r.read_uvarint()
        elif f == 2:
            raw = r.read_bytes()
        else:
            r.skip(wt)
    if n > MAX_WIRE_BITS:
        raise ValueError(f"wire bit array of {n} bits exceeds {MAX_WIRE_BITS}")
    return BitArray.from_bytes(n, raw)


def encode_message_py(msg: Message) -> bytes:
    if isinstance(msg, NewRoundStepMessage):
        body = (
            pe.varint_field(1, msg.height)
            + pe.varint_field(2, msg.round + 1)
            + pe.varint_field(3, msg.step)
            + pe.varint_field(4, msg.seconds_since_start_time)
            + pe.varint_field(5, msg.last_commit_round + 1)
        )
        return pe.message_field(T_NEW_ROUND_STEP, body)
    if isinstance(msg, NewValidBlockMessage):
        total, h = msg.block_part_set_header
        body = (
            pe.varint_field(1, msg.height)
            + pe.varint_field(2, msg.round)
            + pe.message_field(3, pe.varint_field(1, total) + pe.bytes_field(2, h))
            + pe.message_field(4, _encode_bits(msg.block_parts))
            + pe.varint_field(5, 1 if msg.is_commit else 0)
        )
        return pe.message_field(T_NEW_VALID_BLOCK, body)
    if isinstance(msg, ProposalMessage):
        return pe.message_field(T_PROPOSAL, msg.proposal.encode())
    if isinstance(msg, ProposalPOLMessage):
        body = (
            pe.varint_field(1, msg.height)
            + pe.varint_field(2, msg.proposal_pol_round)
            + pe.message_field(3, _encode_bits(msg.proposal_pol))
        )
        return pe.message_field(T_PROPOSAL_POL, body)
    if isinstance(msg, BlockPartMessage):
        body = (
            pe.varint_field(1, msg.height)
            + pe.varint_field(2, msg.round)
            + pe.message_field(3, msg.part.encode())
        )
        return pe.message_field(T_BLOCK_PART, body)
    if isinstance(msg, VoteMessage):
        return pe.message_field(T_VOTE, msg.vote.encode())
    if isinstance(msg, VoteBatchMessage):
        body = b"".join(pe.bytes_field(1, v.encode()) for v in msg.votes)
        return pe.message_field(T_VOTE_BATCH, body)
    if isinstance(msg, HasVoteMessage):
        return pe.message_field(T_HAS_VOTE, _encode_has_vote_body(msg))
    if isinstance(msg, HasVoteBatchMessage):
        body = b"".join(
            pe.message_field(1, _encode_has_vote_body(e)) for e in msg.entries
        )
        return pe.message_field(T_HAS_VOTE_BATCH, body)
    if isinstance(msg, VoteSetMaj23Message):
        body = (
            pe.varint_field(1, msg.height)
            + pe.varint_field(2, msg.round)
            + pe.varint_field(3, int(msg.type))
            + pe.message_field(4, msg.block_id.encode())
        )
        return pe.message_field(T_VOTE_SET_MAJ23, body)
    if isinstance(msg, VoteSetBitsMessage):
        body = (
            pe.varint_field(1, msg.height)
            + pe.varint_field(2, msg.round)
            + pe.varint_field(3, int(msg.type))
            + pe.message_field(4, msg.block_id.encode())
            + pe.message_field(5, _encode_bits(msg.votes))
        )
        return pe.message_field(T_VOTE_SET_BITS, body)
    raise TypeError(f"unknown consensus message {type(msg)}")


def decode_message_py(data: bytes) -> Message:
    r = pe.Reader(data)
    f, wt = r.read_tag()
    body = r.read_bytes()
    if f == T_NEW_ROUND_STEP:
        br = pe.Reader(body)
        kw = dict(height=0, round=-1, step=0, seconds_since_start_time=0, last_commit_round=-1)
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                kw["height"] = br.read_uvarint()
            elif bf == 2:
                kw["round"] = br.read_uvarint() - 1
            elif bf == 3:
                kw["step"] = br.read_uvarint()
            elif bf == 4:
                kw["seconds_since_start_time"] = br.read_uvarint()
            elif bf == 5:
                kw["last_commit_round"] = br.read_uvarint() - 1
            else:
                br.skip(bwt)
        return NewRoundStepMessage(**kw)
    if f == T_NEW_VALID_BLOCK:
        br = pe.Reader(body)
        height = round_ = 0
        total, h = 0, b""
        bits = BitArray(0)
        is_commit = False
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                height = br.read_uvarint()
            elif bf == 2:
                round_ = br.read_uvarint()
            elif bf == 3:
                hr = pe.Reader(br.read_bytes())
                while not hr.eof():
                    hf, hwt = hr.read_tag()
                    if hf == 1:
                        total = hr.read_uvarint()
                    elif hf == 2:
                        h = hr.read_bytes()
                    else:
                        hr.skip(hwt)
            elif bf == 4:
                bits = _decode_bits(br.read_bytes())
            elif bf == 5:
                is_commit = br.read_uvarint() == 1
            else:
                br.skip(bwt)
        return NewValidBlockMessage(height, round_, (total, h), bits, is_commit)
    if f == T_PROPOSAL:
        return ProposalMessage(Proposal.decode(body))
    if f == T_PROPOSAL_POL:
        br = pe.Reader(body)
        height = pol_round = 0
        bits = BitArray(0)
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                height = br.read_uvarint()
            elif bf == 2:
                pol_round = br.read_uvarint()
            elif bf == 3:
                bits = _decode_bits(br.read_bytes())
            else:
                br.skip(bwt)
        return ProposalPOLMessage(height, pol_round, bits)
    if f == T_BLOCK_PART:
        br = pe.Reader(body)
        height = round_ = 0
        part = None
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                height = br.read_uvarint()
            elif bf == 2:
                round_ = br.read_uvarint()
            elif bf == 3:
                part = Part.decode(br.read_bytes())
            else:
                br.skip(bwt)
        return BlockPartMessage(height, round_, part)
    if f == T_VOTE:
        return VoteMessage(Vote.decode(body))
    if f == T_VOTE_BATCH:
        br = pe.Reader(body)
        votes = []
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                votes.append(Vote.decode(br.read_bytes()))
                if len(votes) > MAX_BATCH_VOTES:
                    raise ValueError(
                        f"vote batch exceeds {MAX_BATCH_VOTES} votes"
                    )
            else:
                br.skip(bwt)
        return VoteBatchMessage(tuple(votes))
    if f == T_HAS_VOTE:
        return _decode_has_vote_body(body)
    if f == T_HAS_VOTE_BATCH:
        br = pe.Reader(body)
        entries = []
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                entries.append(_decode_has_vote_body(br.read_bytes()))
                if len(entries) > MAX_BATCH_VOTES:
                    raise ValueError(
                        f"has-vote batch exceeds {MAX_BATCH_VOTES} entries"
                    )
            else:
                br.skip(bwt)
        return HasVoteBatchMessage(tuple(entries))
    if f in (T_VOTE_SET_MAJ23, T_VOTE_SET_BITS):
        br = pe.Reader(body)
        height = round_ = 0
        type_ = SignedMsgType.UNKNOWN
        bid = NIL_BLOCK_ID
        bits = BitArray(0)
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                height = br.read_uvarint()
            elif bf == 2:
                round_ = br.read_uvarint()
            elif bf == 3:
                type_ = SignedMsgType(br.read_uvarint())
            elif bf == 4:
                bid = BlockID.decode(br.read_bytes())
            elif bf == 5:
                bits = _decode_bits(br.read_bytes())
            else:
                br.skip(bwt)
        if f == T_VOTE_SET_MAJ23:
            return VoteSetMaj23Message(height, round_, type_, bid)
        return VoteSetBitsMessage(height, round_, type_, bid, bits)
    raise ValueError(f"unknown consensus message tag {f}")


# -- wiregen dispatch -----------------------------------------------------
# `encode_message` / `decode_message` are rebindable module globals: the
# interpreted codec above by default, the generated fast path
# (consensus/wire_gen.py, built by scripts/wiregen) once it imports.
# TMTPU_WIREGEN=0 is the kill switch; `use_wiregen` flips at runtime.

encode_message = encode_message_py
decode_message = decode_message_py

_WIREGEN_WANTED = os.environ.get("TMTPU_WIREGEN", "1") != "0"


def _adopt_generated(enc, dec) -> None:
    """Import tail of wire_gen hands over its entry points; honored only
    while the kill switch is open."""
    global encode_message, decode_message
    if _WIREGEN_WANTED:
        encode_message = enc
        decode_message = dec


def use_wiregen(enabled: bool) -> bool:
    """Flip the active codec. Returns True iff the generated codec is
    live after the call (False when disabled or wire_gen cannot load)."""
    global _WIREGEN_WANTED, encode_message, decode_message
    _WIREGEN_WANTED = bool(enabled)
    if not enabled:
        encode_message = encode_message_py
        decode_message = decode_message_py
        return False
    try:
        from . import wire_gen

        enc = wire_gen.encode_message
        dec = wire_gen.decode_message
    except Exception:
        # missing/broken generated module, or a circular import while
        # this module is still loading — wire_gen's import tail calls
        # _adopt_generated once it finishes, so leave _WIREGEN_WANTED
        # set and fall back to the interpreted codec for now.
        return False
    encode_message = enc
    decode_message = dec
    return True


def wiregen_active() -> bool:
    """True when gossip frames flow through the generated codec."""
    return encode_message is not encode_message_py


use_wiregen(_WIREGEN_WANTED)


# -- WAL message wrapping -------------------------------------------------


def encode_wal_message(msg, peer_id: str = "") -> bytes:
    """MsgInfo{msg, peer} or TimeoutInfo → WAL payload (reference
    wal.go WALMessage union)."""
    if isinstance(msg, TimeoutInfo):
        body = (
            pe.varint_field(1, msg.duration_ns)
            + pe.varint_field(2, msg.height)
            + pe.varint_field(3, msg.round)
            + pe.varint_field(4, int(msg.step))
        )
        return pe.message_field(W_TIMEOUT, body)
    body = pe.bytes_field(1, encode_message(msg)) + pe.string_field(2, peer_id)
    return pe.message_field(W_MSG_INFO, body)


def decode_wal_message(data: bytes):
    """Returns (msg, peer_id) for MsgInfo or (TimeoutInfo, None)."""
    r = pe.Reader(data)
    f, wt = r.read_tag()
    body = r.read_bytes()
    if f == W_TIMEOUT:
        br = pe.Reader(body)
        dur = height = round_ = 0
        step = RoundStep.NEW_HEIGHT
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                dur = br.read_uvarint()
            elif bf == 2:
                height = br.read_uvarint()
            elif bf == 3:
                round_ = br.read_uvarint()
            elif bf == 4:
                step = RoundStep(br.read_uvarint())
            else:
                br.skip(bwt)
        return TimeoutInfo(dur, height, round_, step), None
    br = pe.Reader(body)
    raw, peer = b"", ""
    while not br.eof():
        bf, bwt = br.read_tag()
        if bf == 1:
            raw = br.read_bytes()
        elif bf == 2:
            peer = br.read_string()
        else:
            br.skip(bwt)
    return decode_message(raw), peer
