"""ABCI handshake: sync the app with the block store on startup
(reference internal/consensus/replay.go:242 Handshaker).

Compares the app's last height (ABCI Info) with the store and state
heights, sends InitChain on a fresh chain, replays stored blocks through
the app as needed, and asserts app-hash agreement. Together with WAL
replay this is the crash-recovery path: the reference's crash-point test
matrix (replay_test.go) is the spec."""

from __future__ import annotations

import logging

from ..abci import types as abci
from ..proxy import AppConns
from ..state.execution import BlockExecutor, validator_updates_to_validators
from ..state.state import State
from ..state.store import StateStore
from ..store.blockstore import BlockStore
from ..types.genesis import GenesisDoc
from ..types.validator_set import ValidatorSet


def report_wal_repair(wal, logger: logging.Logger | None = None) -> None:
    """Surface the WAL's open-time crash repair in the recovery log: the
    exact truncation point (file:byte), how many whole records survived,
    and where the damaged tail went. Called on the node startup path next
    to the ABCI handshake so a post-crash boot reads as one coherent
    recovery story; a clean open logs nothing."""
    repairs = getattr(wal, "last_repair", None)
    if not repairs:
        return
    logger = logger or logging.getLogger("replay")
    for rep in repairs:
        logger.warning(
            "crash recovery: WAL truncated at %s:%d (%s; %d whole record(s) "
            "kept, %d damaged byte(s) moved to %s) — replaying to the "
            "pre-crash state",
            rep.path, rep.valid_end, rep.reason, rep.n_records,
            rep.file_size - rep.valid_end, rep.tail_path,
        )


class HandshakeError(RuntimeError):
    pass


class AppHashMismatchError(HandshakeError):
    pass


class Handshaker:
    def __init__(
        self,
        state_store: StateStore,
        state: State,
        block_store: BlockStore,
        genesis_doc: GenesisDoc,
        logger: logging.Logger | None = None,
    ):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis_doc = genesis_doc
        self.logger = logger or logging.getLogger("handshaker")
        self.n_blocks_replayed = 0

    async def handshake(self, app_conns: AppConns) -> State:
        res = await app_conns.query.info(abci.RequestInfo())
        app_height = res.last_block_height
        app_hash = res.last_block_app_hash
        if app_height < 0:
            raise HandshakeError(f"app reported negative height {app_height}")
        self.logger.info(
            "ABCI handshake: app height=%d hash=%s", app_height, app_hash.hex()
        )
        state = await self.replay_blocks(
            self.initial_state, app_hash, app_height, app_conns
        )
        return state

    async def replay_blocks(
        self,
        state: State,
        app_hash: bytes,
        app_height: int,
        app_conns: AppConns,
    ) -> State:
        store_height = self.block_store.height()
        store_base = self.block_store.base()
        state_height = state.last_block_height

        # 1. fresh chain → InitChain (reference replay.go:285 region)
        if app_height == 0 and state_height == 0:
            # carry genesis proofs of possession into the InitChain
            # updates: an app that echoes the request's validator set
            # back must round-trip the PoPs, or the bls12381 rogue-key
            # gate in validator_updates_to_validators would reject its
            # own genesis set
            pops = {
                gv.pub_key.bytes(): gv.pop for gv in self.genesis_doc.validators
            }
            validators = [
                abci.ValidatorUpdate(
                    v.pub_key.TYPE,
                    v.pub_key.bytes(),
                    v.voting_power,
                    pops.get(v.pub_key.bytes(), b""),
                )
                for v in state.validators.validators
            ]
            res = await app_conns.consensus.init_chain(
                abci.RequestInitChain(
                    time_ns=self.genesis_doc.genesis_time_ns,
                    chain_id=self.genesis_doc.chain_id,
                    consensus_params=state.consensus_params,
                    validators=tuple(validators),
                    app_state_bytes=self.genesis_doc.app_state,
                    initial_height=self.genesis_doc.initial_height,
                )
            )
            updates = {}
            if res.app_hash:
                updates["app_hash"] = res.app_hash
            if res.consensus_params is not None:
                updates["consensus_params"] = res.consensus_params
            if res.validators:
                vals = ValidatorSet(
                    validator_updates_to_validators(
                        res.validators,
                        updates.get("consensus_params", state.consensus_params),
                    )
                )
                updates["validators"] = vals
                updates["next_validators"] = vals.copy_increment_proposer_priority(1)
            if updates:
                state = state.copy(**updates)
            self.state_store.save(state)
            app_hash = state.app_hash

        if store_height == 0:
            self._assert_app_hash(state, app_hash)
            return state

        # 2. fresh state + populated store + fresh app → full replay:
        #    rebuild state by applying every stored block from the base
        #    (reference replay.go:415-443 replays the whole span when the
        #    app is behind the store; this is also what `replay` builds:
        #    a genesis state, a fresh app, and the node's block store).
        #    apply_block's header checks (app_hash chaining, last_block_id)
        #    validate each step against the stored chain.
        if state_height == 0 and app_height == 0 and store_height > 0:
            if store_base > state.initial_height:
                raise HandshakeError(
                    f"cannot replay from genesis: store pruned to base {store_base}"
                )
            executor = BlockExecutor(self.state_store, app_conns.consensus)
            for h in range(store_base, store_height + 1):
                block = self.block_store.load_block(h)
                meta = self.block_store.load_block_meta(h)
                if block is None or meta is None:
                    raise HandshakeError(f"missing block {h} in store")
                self.logger.info("replaying block %d from genesis", h)
                state, _ = await executor.apply_block(state, meta.block_id, block)
                self.n_blocks_replayed += 1
            return state

        # 3. sanity (reference replay.go checkAppHashEqualsOneFromState region)
        if app_height > store_height:
            raise HandshakeError(
                f"app height {app_height} ahead of store height {store_height}"
            )
        if state_height not in (store_height, store_height - 1):
            raise HandshakeError(
                f"state height {state_height} inconsistent with store height {store_height}"
            )
        if app_height < store_base - 1:
            raise HandshakeError(
                f"app height {app_height} below pruned store base {store_base}"
            )

        executor = BlockExecutor(self.state_store, app_conns.consensus)

        # 4. replay app-missing blocks up to store_height-1 via exec+commit
        #    (reference replayBlocks replay.go:528 region)
        replay_to = store_height - 1 if state_height == store_height - 1 else store_height
        for h in range(app_height + 1, replay_to + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise HandshakeError(f"missing block {h} in store")
            self.logger.info("replaying block %d against app", h)
            app_hash = await executor.exec_commit_block(state, block)
            self.n_blocks_replayed += 1

        # 5. if state lags the store by one, apply the tip block fully
        #    (crash happened between SaveBlock and ApplyBlock)
        if state_height == store_height - 1:
            block = self.block_store.load_block(store_height)
            meta = self.block_store.load_block_meta(store_height)
            if block is None or meta is None:
                raise HandshakeError(f"missing tip block {store_height}")
            self.logger.info("applying tip block %d", store_height)
            state, _ = await executor.apply_block(state, meta.block_id, block)
            self.n_blocks_replayed += 1
            app_hash = state.app_hash

        self._assert_app_hash(state, app_hash)
        return state

    def _assert_app_hash(self, state: State, app_hash: bytes) -> None:
        if state.app_hash != app_hash:
            raise AppHashMismatchError(
                f"app hash {app_hash.hex()} != state app hash {state.app_hash.hex()}"
            )
