"""Consensus round state (reference internal/consensus/types/round_state.go)
and HeightVoteSet (reference internal/consensus/types/height_vote_set.go).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..libs.bits import BitArray
from ..types.block import Block, BlockID, Commit
from ..types.keys import SignedMsgType
from ..types.part_set import PartSet
from ..types.validator_set import ValidatorSet
from ..types.vote import Proposal, Vote
from ..types.vote_set import ConflictingVoteError, VoteSet


class RoundStep(enum.IntEnum):
    """Step within a round (reference round_state.go:20-28). Ordering is
    meaningful: later steps compare greater."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


@dataclass
class RoundState:
    """Mutable state of the consensus SM for one height (reference
    round_state.go:60). `round` resets the proposal/vote fields; `height`
    resets everything."""

    height: int = 0
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0

    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None

    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None

    # the POL round/block for the `valid` value (reference round_state.go:79-87):
    # the most recent block known to have a +2/3 prevote polka
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None

    votes: "HeightVoteSet | None" = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def round_state_event(self):
        from ..types.events import EventDataRoundState

        return EventDataRoundState(self.height, self.round, self.step.name)


@dataclass(frozen=True)
class RoundVoteSet:
    prevotes: VoteSet
    precommits: VoteSet


class HeightVoteSet:
    """All VoteSets for one height, keyed by round; tracks peers'
    claimed +2/3 majorities to cap round skipping (reference
    height_vote_set.go). Rounds 0..round+1 are kept "open"; votes for
    other rounds are only admitted if some peer claimed a majority there
    (set_peer_maj23)."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._round_vote_sets: dict[int, RoundVoteSet] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)
        self._add_round(1)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        self._round_vote_sets[round_] = RoundVoteSet(
            prevotes=VoteSet(
                self.chain_id, self.height, round_, SignedMsgType.PREVOTE, self.val_set
            ),
            precommits=VoteSet(
                self.chain_id,
                self.height,
                round_,
                SignedMsgType.PRECOMMIT,
                self.val_set,
            ),
        )

    def set_round(self, round_: int) -> None:
        """Open vote sets up to round+1 (reference height_vote_set.go
        SetRound)."""
        if round_ < self.round:
            raise ValueError("set_round going backwards")
        for r in range(self.round, round_ + 2):
            self._add_round(r)
        self.round = round_

    def add_vote(
        self, vote: Vote, peer_id: str = "", *, verified: bool = False
    ) -> bool:
        """Returns True if added. Unwanted catch-up rounds (beyond
        round+1 with no peer maj23 claim) return False rather than
        raising (reference height_vote_set.go:126). `verified` marks a
        vote whose signature the ingest pipeline already proved."""
        if vote.height != self.height:
            return False
        vs = self._get_vote_set(vote.round, vote.type)
        if vs is None:
            rounds = self._peer_catchup_rounds.get(peer_id, [])
            if vote.round in rounds:
                self._add_round(vote.round)
                vs = self._get_vote_set(vote.round, vote.type)
            else:
                return False  # unwanted round; possible DoS, drop
        return vs.add_vote(vote, verified=verified)

    def wanted(self, vote: Vote, peer_id: str = "") -> bool:
        """Would add_vote even look at this vote — open round, or a
        catch-up round this peer claimed a +2/3 majority for? The
        pipelined ingest checks this BEFORE spending a signature
        verification, mirroring the unwanted-round DoS drop below: a
        flood of far-future-round votes must not burn live-lane hub
        capacity the sequential path never spent."""
        if self._get_vote_set(vote.round, vote.type) is not None:
            return True
        return vote.round in self._peer_catchup_rounds.get(peer_id, [])

    def _get_vote_set(self, round_: int, type_: SignedMsgType) -> VoteSet | None:
        rvs = self._round_vote_sets.get(round_)
        if rvs is None:
            return None
        return rvs.prevotes if type_ == SignedMsgType.PREVOTE else rvs.precommits

    def prevotes(self, round_: int) -> VoteSet | None:
        return self._get_vote_set(round_, SignedMsgType.PREVOTE)

    def precommits(self, round_: int) -> VoteSet | None:
        return self._get_vote_set(round_, SignedMsgType.PRECOMMIT)

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Highest round with a +2/3 prevote polka (reference
        height_vote_set.go POLInfo)."""
        for r in range(self.round, -1, -1):
            vs = self.prevotes(r)
            if vs is not None:
                maj = vs.two_thirds_majority()
                if maj is not None:
                    return r, maj
        return -1, None

    def set_peer_maj23(
        self,
        round_: int,
        type_: SignedMsgType,
        peer_id: str,
        block_id=None,
    ) -> None:
        """A peer claims a +2/3 majority for (round, type): open that
        round so its votes can be gossiped to us (max 2 catch-up rounds
        per peer, reference height_vote_set.go:165). When the claim
        names a block, the round's vote set records it so conflicting
        votes for THAT block stay admissible (reference SetPeerMaj23 —
        the equivocation-vs-catch-up case: an equivocator's twin in a
        laggard's slot must not block the committed majority forever)."""
        rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
        if round_ not in rounds and len(rounds) < 2:
            rounds.append(round_)
            self._add_round(round_)
        if block_id is not None:
            # record the claim on whichever vote set is reachable — an
            # already-open round takes it even when this peer's
            # catch-up budget is spent; claims are bounded PER PEER in
            # the vote set, so a liar can't crowd out honest donors
            vs = self._get_vote_set(round_, type_)
            if vs is not None:
                vs.set_peer_maj23_block(block_id, peer_id)
