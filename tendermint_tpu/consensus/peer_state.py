"""Per-peer consensus view (reference internal/consensus/peer_state.go).

Tracks what one peer has — its height/round/step, which proposal parts
and votes it holds — so the gossip routines send only what is missing."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs.bits import BitArray
from ..types.keys import SignedMsgType
from ..types.vote import Vote


@dataclass
class PeerRoundState:
    """The peer's claimed round state (reference
    internal/consensus/types/peer_round_state.go)."""

    height: int = 0
    round: int = -1
    step: int = 0
    proposal: bool = False
    proposal_block_parts_header: tuple[int, bytes] | None = None
    proposal_block_parts: BitArray | None = None
    proposal_pol_round: int = -1
    proposal_pol: BitArray | None = None
    prevotes: dict[int, BitArray] = field(default_factory=dict)
    precommits: dict[int, BitArray] = field(default_factory=dict)
    last_commit_round: int = -1
    last_commit: BitArray | None = None
    catchup_commit_round: int = -1
    catchup_commit: BitArray | None = None


class PeerState:
    def __init__(self, peer_id: str):
        self.peer_id = peer_id
        self.prs = PeerRoundState()

    # -- updates from the state channel ---------------------------------

    def apply_new_round_step(self, msg) -> None:
        """Reference peer_state.go ApplyNewRoundStepMessage."""
        prs = self.prs
        initial = (prs.height, prs.round)
        if msg.height != prs.height or msg.round != prs.round:
            prs.proposal = False
            prs.proposal_block_parts_header = None
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
        if msg.height != prs.height:
            # shift vote bookkeeping: the peer's precommits of the old
            # height become its last-commit
            if prs.height + 1 == msg.height and prs.round in prs.precommits:
                prs.last_commit_round = prs.round
                prs.last_commit = prs.precommits.get(prs.round)
            else:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = None
            prs.prevotes = {}
            prs.precommits = {}
            prs.catchup_commit_round = -1
            prs.catchup_commit = None
        prs.height = msg.height
        prs.round = msg.round
        prs.step = msg.step

    def apply_new_valid_block(self, msg) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round != msg.round and not msg.is_commit:
            return
        prs.proposal_block_parts_header = msg.block_part_set_header
        prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg) -> None:
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def set_has_proposal(self, proposal) -> None:
        prs = self.prs
        if prs.height != proposal.height or prs.round != proposal.round:
            return
        if prs.proposal:
            return
        prs.proposal = True
        if prs.proposal_block_parts is None:
            psh = proposal.block_id.part_set_header
            prs.proposal_block_parts_header = (psh.total, psh.hash)
            prs.proposal_block_parts = BitArray(psh.total)
        prs.proposal_pol_round = proposal.pol_round
        prs.proposal_pol = None

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        prs = self.prs
        if prs.height != height or prs.round != round_:
            return
        if prs.proposal_block_parts is None:
            return
        prs.proposal_block_parts.set(index, True)

    # -- vote bookkeeping ------------------------------------------------

    def _votes_bits(self, height: int, round_: int, type_: SignedMsgType, size: int) -> BitArray | None:
        prs = self.prs
        if height == prs.height:
            table = prs.prevotes if type_ == SignedMsgType.PREVOTE else prs.precommits
            if round_ not in table:
                table[round_] = BitArray(size)
            ba = table[round_]
            if ba.size == 0 and size:
                table[round_] = ba = BitArray(size)
            return ba
        if height + 1 == prs.height and type_ == SignedMsgType.PRECOMMIT:
            if round_ == prs.last_commit_round:
                if prs.last_commit is None:
                    prs.last_commit = BitArray(size)
                return prs.last_commit
        if height < prs.height and type_ == SignedMsgType.PRECOMMIT:
            if round_ == prs.catchup_commit_round:
                if prs.catchup_commit is None:
                    prs.catchup_commit = BitArray(size)
                return prs.catchup_commit
        return None

    def set_has_vote(self, height: int, round_: int, type_: SignedMsgType, index: int) -> None:
        ba = self._votes_bits(height, round_, type_, index + 1)
        if ba is not None:
            if ba.size <= index:
                grown = BitArray(index + 1)
                for i in ba.true_indices():
                    grown.set(i, True)
                self._replace_bits(height, round_, type_, ba, grown)
                ba = grown
            ba.set(index, True)

    def _replace_bits(self, height, round_, type_, old, new) -> None:
        prs = self.prs
        if height == prs.height:
            table = prs.prevotes if type_ == SignedMsgType.PREVOTE else prs.precommits
            table[round_] = new
        elif old is prs.last_commit:
            prs.last_commit = new
        elif old is prs.catchup_commit:
            prs.catchup_commit = new

    def reset_gossip_marks(self) -> None:
        """Forget what we believe the peer already holds (proposal flag,
        part bits, vote bits, catch-up bits) while KEEPING its claimed
        height/round/step. The gossip routines mark an item as delivered
        at SEND time, so a frame the wire ate — dropped, or corrupted
        into something else — leaves a false positive that is never
        resent. The reactor calls this when a link looks wedged (both
        round states static, nothing left to send): the next gossip
        passes re-offer everything, and the receiver's dedup (VoteSet /
        PartSet add) makes re-sends idempotent."""
        prs = self.prs
        prs.proposal = False
        prs.proposal_block_parts_header = None
        prs.proposal_block_parts = None
        prs.proposal_pol_round = -1
        prs.proposal_pol = None
        prs.prevotes = {}
        prs.precommits = {}
        prs.last_commit = None
        prs.catchup_commit_round = -1
        prs.catchup_commit = None

    def apply_vote_set_bits(self, msg, our_votes: BitArray | None) -> None:
        """Reference peer_state.go ApplyVoteSetBitsMessage: a VoteSetBits
        reply is an AUTHORITATIVE statement of what the peer holds for
        the queried (height, round, type, block), so it REPLACES our
        bookkeeping instead of or-ing into it. This is the only
        mechanism that can clear a false `has_vote` mark — e.g. a
        corrupted frame that still decoded as a plausible HasVote, or a
        vote we sent that the wire silently ate — and without it one
        poisoned bit starves the peer of that vote forever (a liveness
        wedge the router-chaos matrix reproduces). `our_votes` (our own
        bit array for the queried block) keeps bits for OTHER blocks
        that the reply cannot speak for."""
        votes = self._votes_bits(
            msg.height, msg.round, msg.type, msg.votes.size
        )
        if votes is None:
            return
        if our_votes is None:
            new = msg.votes.copy()
        else:
            other_block_bits = votes.sub(our_votes)
            new = other_block_bits.or_(msg.votes)
        self._replace_bits(msg.height, msg.round, msg.type, votes, new)

    def ensure_catchup_commit(self, height: int, round_: int, size: int) -> None:
        """Peer is far behind; track which precommits of `height`'s seen
        commit we have sent it (reference EnsureCatchupCommitRound)."""
        prs = self.prs
        if prs.catchup_commit_round != round_:
            prs.catchup_commit_round = round_
            prs.catchup_commit = BitArray(size)

    def pick_vote_to_send(self, votes) -> Vote | None:
        """A vote from `votes` (a VoteSet) the peer does not have
        (reference PickSendVote/PickVoteToSend)."""
        picked = self.pick_votes_to_send(votes, 1)
        return picked[0] if picked else None

    def pick_votes_to_send(self, votes, limit: int) -> list[Vote]:
        """Up to `limit` votes the peer is missing — the batched gossip
        pick. Committee-scale nets move votes in VoteBatch frames (one
        envelope per ~32 votes instead of one each); which missing votes
        go first doesn't affect correctness, so this takes them in
        index order rather than paying a random draw per vote."""
        if votes is None or votes.size() == 0:
            return []
        ba = self._votes_bits(votes.height, votes.round, votes.type, votes.size())
        if ba is None:
            return []
        missing = votes.votes_bit_array.sub(ba)
        out: list[Vote] = []
        for idx in missing.true_indices():
            v = votes.get_vote(idx)
            if v is not None:
                out.append(v)
                if len(out) >= limit:
                    break
        return out
