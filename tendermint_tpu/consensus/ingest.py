"""Pipelined consensus ingest — stage 1 of the two-stage receive path.

The receive routine historically verified each vote's signature through
the hub's SYNC facade, so a lone node pinned per-vote batch occupancy
at 1: the whole gossip firehose serialized behind one signature at a
time (ROADMAP's "biggest lever"). This module splits ingest into:

  stage 1 (this file, concurrent): incoming votes/proposals get a cheap
      structural check, are deduplicated against the live vote-set, and
      then submitted via the ASYNC ``hub.verify`` API — up to
      ``max_inflight`` verifications overlap per node, which is exactly
      what the micro-batching scheduler needs to fill device-sized
      batches from a single process (the request-pipelining shape the
      FPGA verification engines in arXiv:2112.02229 get their
      throughput from);

  stage 2 (the state machine, strictly ordered): verdicts flow through
      a sequence-numbered REORDER BUFFER and are released to
      ``ConsensusState.msg_queue`` in arrival order, so the SM's
      in-order single-task processing contract — and with it same-seed
      bit-reproducibility under chaos — is untouched. A message whose
      signature stage 1 proved carries ``sig_ok=True`` and is not
      re-checked at apply time (the pre-verified-vote path through
      ``VoteSet.add_vote``); a proven-bad signature carries
      ``sig_ok=False`` and is dropped at apply (after the WAL write,
      like any other rejected input); anything stage 1 could not
      attribute (wrong height, no hub, hub error) stays ``None`` and
      falls back to the apply-time synchronous check, i.e. exactly the
      pre-pipeline behavior.

Backpressure: a semaphore bounds the TOTAL number of messages between
``submit`` and in-order release (intake + verifying + parked in the
reorder buffer) at ``max_inflight``; ``submit`` awaits a permit BEFORE
a sequence number is assigned, so a gossip storm backs up into the
reactor's channel instead of ballooning the reorder buffer, a caller
cancelled mid-backpressure leaves no hole in the sequence space, and
the intake queue is always strictly sequence-ordered (permit → seq →
put_nowait with no await in between). Workers are plain
``Service.spawn`` tasks owned by the ConsensusState — stop() cancels
them mid-verify without leaking tasks or absorbing cancellation, and
anything already verified but not yet released is simply dropped with
the queue (the WAL only records APPLIED inputs, so a crash/stop here
is indistinguishable from the message never arriving).

Config: ``ConsensusConfig.ingest_pipeline`` / ``ingest_max_inflight``,
env mirrors ``TMTPU_INGEST_PIPELINE`` / ``TMTPU_INGEST_INFLIGHT``.
"""

from __future__ import annotations

import asyncio
import logging
import weakref
from dataclasses import replace

from ..libs import trace
from ..libs.metrics import Histogram
from ..types.keys import SignedMsgType
from . import messages as m

#: same sub-millisecond buckets as the hub's queue-latency histogram —
#: NodeMetrics folds pipeline histograms index-for-index
from ..crypto.verify_hub import LATENCY_BUCKETS

#: process-wide registry of running pipelines (multi-node in-process
#: tests run several); NodeMetrics sums them at render time, mirroring
#: crypto.verify_hub.running_hub
_pipelines: "weakref.WeakSet[IngestPipeline]" = weakref.WeakSet()


def aggregate():
    """(summed stats, verify-latency hist, reorder-wait hist) across
    every live pipeline, or (None, None, None) when none is running."""
    pipes = [p for p in _pipelines if p.started]
    if not pipes:
        return None, None, None
    keys = pipes[0].stats.keys()
    s = {k: sum(p.stats[k] for p in pipes) for k in keys}
    s["inflight"] = float(sum(p.inflight for p in pipes))

    def fold(hists):
        counts = [0] * (len(LATENCY_BUCKETS) + 1)
        total_sum, total_count = 0.0, 0
        for h in hists:
            for i, c in enumerate(h._counts):
                counts[i] += c
            total_sum += h._sum
            total_count += h._count
        return counts, total_sum, total_count

    return (
        s,
        fold([p.verify_latency for p in pipes]),
        fold([p.reorder_wait for p in pipes]),
    )


class IngestPipeline:
    """Stage-1 verifier pool + reorder buffer in front of one
    ConsensusState (see module docstring)."""

    def __init__(
        self,
        cs,
        *,
        max_inflight: int = 64,
        logger: logging.Logger | None = None,
    ):
        self.cs = cs
        self.max_inflight = max(1, int(max_inflight))
        self.logger = logger or logging.getLogger("consensus.ingest")
        self.started = False
        # one permit per message from submit() until in-order release:
        # bounds intake + verifying + reorder buffer at max_inflight
        # combined, and awaiting it BEFORE the seq is assigned is the
        # backpressure edge (see module docstring)
        self._sem = asyncio.Semaphore(self.max_inflight)
        # unbounded Queue object, but occupancy is capped by _sem; it
        # holds strictly ascending seqs because submit() never awaits
        # between seq assignment and put_nowait
        self._intake: asyncio.Queue = asyncio.Queue()
        # seq -> (verdict_done_at, MsgInfo | None, TraceCtx | None);
        # MsgInfo None = dropped in stage 1
        self._buf: dict[int, tuple[float, object | None, object | None]] = {}
        self._next_submit = 0
        self._next_release = 0
        self._completed = asyncio.Event()
        self.verify_latency = Histogram(
            "consensus_ingest_verify_latency_seconds",
            "stage-1 intake-to-verdict wait per message",
            buckets=LATENCY_BUCKETS,
        )
        self.reorder_wait = Histogram(
            "consensus_ingest_reorder_wait_seconds",
            "verdict-to-in-order-release wait per message",
            buckets=LATENCY_BUCKETS,
        )
        self.stats = {
            "submitted": 0.0,      # messages entering stage 1
            "released": 0.0,       # messages released in-order to the SM
            "dedup_drops": 0.0,    # gossip duplicates dropped pre-verify
            "structural_drops": 0.0,  # failed validate_basic in stage 1
            "pre_verified": 0.0,   # signature proven in stage 1
            "sig_invalid": 0.0,    # signature disproven in stage 1
            "unverified": 0.0,     # deferred to the apply-time check
        }

    @property
    def inflight(self) -> int:
        """Messages submitted and not yet released (intake + verifying +
        parked in the reorder buffer)."""
        return self._next_submit - self._next_release

    def start(self) -> None:
        """Spawn the worker pool + release task on the owning service —
        Service.stop() cancels and reaps them (no task leaks)."""
        for i in range(self.max_inflight):
            self.cs.spawn(self._worker(), name=f"cs.ingest.w{i}")
        self.cs.spawn(self._release_loop(), name="cs.ingest.release")
        self.started = True
        _pipelines.add(self)

    def stop(self) -> None:
        """Deregister from the metrics registry (the owning service's
        stop() cancels the worker/release tasks); a stopped node's
        counters must not keep folding into /metrics."""
        self.started = False
        _pipelines.discard(self)

    async def submit(self, mi) -> None:
        """Stage-1 intake: wait for an in-flight permit (backpressure —
        `max_inflight` messages between here and in-order release), then
        assign the arrival sequence number and hand the message to the
        verifier pool. The permit is acquired BEFORE the seq, with no
        await in between seq assignment and the put, so a cancelled
        submitter leaves no hole in the sequence space and the intake
        queue is strictly seq-ordered."""
        await self._sem.acquire()
        seq = self._next_submit
        self._next_submit += 1
        self.stats["submitted"] += 1
        t0 = self.cs.clock.monotonic()
        # flight-recorder trace: adopt the reactor-opened context (which
        # already carries the p2p.receive span) or open one here for
        # harness-injected messages; the "submit" mark anchors the
        # end-to-end ingest span the SM closes at apply time
        ctx = mi.trace if mi.trace is not None else trace.start(self.cs.clock)
        if ctx is not None:
            ctx.marks["submit"] = t0
        self._intake.put_nowait((seq, t0, mi, ctx))

    # -- stage 1: concurrent verify --------------------------------------

    async def _worker(self) -> None:
        while True:
            # the reorder buffer needs no explicit bound here: every
            # message from submit() to release holds one _sem permit,
            # so intake + verifying + _buf together can never exceed
            # max_inflight — and a worker that always drains intake
            # can never deadlock against a release loop stalled on a
            # seq still sitting in the queue
            seq, t0, mi, ctx = await self._intake.get()
            t_start = self.cs.clock.monotonic()
            trace.record(ctx, "consensus", "ingest.wait", t0, t_start)
            out = mi
            try:
                out = await self._classify(mi, ctx)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — degrade, never wedge
                # verdict stays "unknown": the apply-time synchronous
                # check decides, exactly the pre-pipeline path
                self.logger.warning(
                    "stage-1 verify failed (%r); deferring to apply", e
                )
                out = mi
            now = self.cs.clock.monotonic()
            self.verify_latency.observe(max(0.0, now - t0))
            trace.record(
                ctx, "consensus", "ingest.verify", t_start, now,
                sig_ok=getattr(out, "sig_ok", None) if out is not None else None,
                dropped=out is None,
            )
            self._buf[seq] = (now, out, ctx)
            self._completed.set()

    async def _classify(self, mi, ctx=None):
        """Returns the (possibly sig_ok-annotated) MsgInfo to release,
        or None to drop the message in stage 1."""
        msg = mi.msg
        if isinstance(msg, m.VoteMessage):
            return await self._classify_vote(mi, msg.vote, ctx)
        if isinstance(msg, m.ProposalMessage):
            return await self._classify_proposal(mi, msg.proposal, ctx)
        # block parts & friends carry no signature of their own; they
        # still ride the reorder buffer so arrival order is preserved
        return mi

    async def _classify_vote(self, mi, vote, ctx=None):
        try:
            vote.validate_basic()
        except ValueError as e:
            # the sequential path rejects these at apply; dropping a
            # structurally-invalid vote earlier changes no state
            self.stats["structural_drops"] += 1
            self.logger.debug("dropping malformed vote: %r", e)
            return None
        if self._duplicate_vote(vote):
            self.stats["dedup_drops"] += 1
            return None
        pub = self._resolve_vote_pubkey(vote, mi.peer_id)
        if pub is None:
            # wrong height / unwanted round (the SM will drop it) or
            # unknown validator (apply raises) — nothing worth
            # verifying here
            self.stats["unverified"] += 1
            return mi
        chain_id = self.cs.state.chain_id
        ok = await self._hub_verify(
            pub, vote.sign_bytes(chain_id), vote.signature, ctx
        )
        if ok is None:
            self.stats["unverified"] += 1
            return mi
        self.stats["pre_verified" if ok else "sig_invalid"] += 1
        return replace(mi, sig_ok=ok)

    async def _classify_proposal(self, mi, proposal, ctx=None):
        rs = self.cs.rs
        # only pre-verify when the proposal targets the CURRENT (height,
        # round): the proposer is then pinned, and if the round moves on
        # before apply the SM drops the proposal before trusting sig_ok
        if (
            rs.proposal is not None
            or rs.validators is None
            or self.cs.state is None
            or proposal.height != rs.height
            or proposal.round != rs.round
        ):
            return mi
        try:
            proposal.validate_basic()
        except ValueError:
            return mi  # apply raises/logs identically to the sync path
        pub = rs.validators.get_proposer().pub_key
        ok = await self._hub_verify(
            pub, proposal.sign_bytes(self.cs.state.chain_id), proposal.signature, ctx
        )
        if ok is None:
            self.stats["unverified"] += 1
            return mi
        self.stats["pre_verified" if ok else "sig_invalid"] += 1
        return replace(mi, sig_ok=ok)

    def _duplicate_vote(self, vote) -> bool:
        """Exact duplicate of a vote already tallied (same validator,
        same block) — the add_vote outcome would be a no-op False, so
        the signature is not worth verifying. A DIFFERENT block from
        the same validator is NOT a duplicate: it must verify and reach
        the SM in order so equivocation evidence is still produced."""
        rs = self.cs.rs
        if rs.votes is not None and vote.height == rs.height:
            vs = (
                rs.votes.prevotes(vote.round)
                if vote.type == SignedMsgType.PREVOTE
                else rs.votes.precommits(vote.round)
            )
            if vs is None:
                return False
            existing = vs.get_vote(vote.validator_index)
            return existing is not None and existing.block_id == vote.block_id
        if (
            vote.height + 1 == rs.height
            and vote.type == SignedMsgType.PRECOMMIT
            and rs.last_commit is not None
        ):
            existing = rs.last_commit.get_vote(vote.validator_index)
            return existing is not None and existing.block_id == vote.block_id
        return False

    def _resolve_vote_pubkey(self, vote, peer_id: str = ""):
        """The pubkey the apply-time vote-set would check this vote
        against, or None when stage 1 cannot attribute it. Validator
        sets are fixed per height, so a verdict computed here stays
        valid even if the SM advances before the in-order apply."""
        rs = self.cs.rs
        if rs.votes is not None and vote.height == rs.height:
            if not rs.votes.wanted(vote, peer_id):
                # unwanted round: apply drops it without a signature
                # check — don't spend one here either (DoS guard)
                return None
            vals = rs.votes.val_set
        elif vote.height + 1 == rs.height and rs.last_validators is not None:
            vals = rs.last_validators
        else:
            return None
        val = vals.get_by_index(vote.validator_index)
        if val is None or val.address != vote.validator_address:
            return None
        return val.pub_key

    async def _hub_verify(self, pub, sign_bytes, sig, ctx=None):
        """Async hub verdict, or None when no hub is running / the hub
        errored (the apply-time check then decides — a wedged hub costs
        latency, never consensus progress)."""
        from ..crypto.verify_hub import LANE_LIVE, running_hub

        hub = running_hub()
        if hub is None:
            return None
        try:
            return await hub.verify(
                pub, sign_bytes, sig, lane=LANE_LIVE, trace_ctx=ctx
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — shutdown/stall races
            self.logger.warning("hub verify failed (%r); deferring", e)
            return None

    # -- stage 2 hand-off: in-order release ------------------------------

    async def _release_loop(self) -> None:
        """Drain the reorder buffer strictly in sequence order into the
        SM's input queue. Single consumer: release order == arrival
        order, bit-for-bit what the sequential facade produced."""
        while True:
            await self._completed.wait()
            self._completed.clear()
            while self._next_release in self._buf:
                done_at, out, ctx = self._buf.pop(self._next_release)
                self._next_release += 1
                if out is None:
                    self._sem.release()
                    continue  # dropped in stage 1 (dup / malformed)
                t_rel = self.cs.clock.monotonic()
                self.reorder_wait.observe(max(0.0, t_rel - done_at))
                if ctx is not None:
                    trace.record(ctx, "consensus", "ingest.reorder", done_at, t_rel)
                    ctx.marks["release"] = t_rel
                    if out.trace is None:
                        out = replace(out, trace=ctx)
                self.stats["released"] += 1
                # put BEFORE releasing the permit: a stalled SM (full
                # msg_queue) keeps the in-flight bound strict
                await self.cs.msg_queue.put(out)
                self._sem.release()
