"""RouterNet — router-backed chaos consensus harness.

`LocalNetwork` (harness.py) wires ConsensusStates together through their
typed broadcast hooks: fast, but it cannot model byte-stream faults
(corruption, bandwidth shaping) and its catch-up relay stands in for the
consensus reactor's real gossip. RouterNet closes that gap — the
credibility gate in ROADMAP's live-chaos item: N full consensus nodes,
each with its own `p2p.Router` over a `ChaosTransport`-wrapped in-memory
transport and a real `ConsensusReactor`, so

  * every fault class in `libs/chaos.py` applies to the live byte path
    (a corrupt frame really hits the codec; a shaped link really queues
    encoded bytes), and
  * catch-up goes through `_send_catchup_commit_vote` /
    `_send_catchup_part` / the catch-up `VoteSetMaj23` exchange — the
    reactor's own gossip, with NO harness relay anywhere.

Topology: full mesh up to `degree`+1 nodes, else a ring plus seeded
random chords (deterministic in `topo_seed`), so 50-150 validator nets
run thousands — not tens of thousands — of peer links and vote gossip
crosses a few relay hops, like a real committee deployment.

Determinism: with a frozen `ManualClock` base (parked at/behind genesis)
the vote-time floor makes every vote/block timestamp a pure function of
(height, genesis_time); with 3 equal-power validators a commit needs ALL
precommits, pinning the commit signer set — two same-seed runs then
produce bit-identical block bytes even while the network is lying (see
tests/test_routernet.py).

The process-wide VerifyHub is acquired for the net's lifetime (like
node.py does): all in-process nodes share its verdict cache, so each
gossip-duplicated signature costs the committee one verification, which
is what makes 150-validator soaks feasible on a CPU image.
"""

from __future__ import annotations

import asyncio
import random

from ..evidence import EVIDENCE_CHANNEL
from ..evidence.reactor import EvidenceReactor
from ..p2p.memory import MemoryNetwork
from ..p2p.testing import RouterShell
from ..statesync import (
    CHUNK_CHANNEL,
    LIGHT_BLOCK_CHANNEL,
    PARAMS_CHANNEL,
    SNAPSHOT_CHANNEL,
)
from ..statesync import messages as ss_msgs
from ..statesync.reactor import StateSyncReactor, SyncConfig
from ..types.evidence import decode_evidence
from . import messages as m
from .harness import MS, Node, fast_config, make_genesis
from .reactor import (
    DATA_CHANNEL,
    STATE_CHANNEL,
    VOTE_CHANNEL,
    VOTE_SET_BITS_CHANNEL,
    ConsensusReactor,
)


def committee_config(n: int):
    """Consensus timeouts sized for an N-validator in-process committee:
    commit-time gossip storms at 50-150 validators take tens of seconds
    of event-loop time, and a propose/prevote timeout inside that window
    turns into round churn (nil prevotes -> new round -> MORE traffic).
    Generous timers cost nothing on the happy path — steps advance on
    quorum, not timers — so big nets run storm-sized timeouts."""
    from ..config import ConsensusConfig

    scale = max(1, n // 10)
    return ConsensusConfig(
        timeout_propose_ns=(2000 + 2000 * scale) * MS,
        timeout_propose_delta_ns=1000 * MS,
        timeout_prevote_ns=(1500 + 1500 * scale) * MS,
        timeout_prevote_delta_ns=1000 * MS,
        timeout_precommit_ns=(1500 + 1500 * scale) * MS,
        timeout_precommit_delta_ns=1000 * MS,
        timeout_commit_ns=200 * MS,
        skip_timeout_commit=False,
    )


def topology_edges(
    n: int, degree: int, seed: int = 0
) -> list[tuple[int, int]]:
    """Connected, deterministic topology: full mesh while n <= degree+1,
    else a ring (connectivity floor) plus seeded random chords until the
    average degree reaches `degree`. Edges are (i, j) with i < j; the
    lower index dials."""
    if n < 2:
        return []
    if n <= degree + 1:
        return [(i, j) for i in range(n) for j in range(i + 1, n)]
    edges = {(i, (i + 1) % n) for i in range(n)}
    edges = {(min(a, b), max(a, b)) for a, b in edges}
    rng = random.Random(f"routernet-topo:{seed}:{n}:{degree}")
    target = n * degree // 2
    # bounded draw loop: dense-enough graphs could make rejection
    # sampling spin, so cap attempts defensively
    attempts = 0
    while len(edges) < target and attempts < 50 * target:
        attempts += 1
        a = rng.randrange(n)
        b = rng.randrange(n)
        if a == b:
            continue
        edges.add((min(a, b), max(a, b)))
    return sorted(edges)


class RouterNode:
    """One validator (or full node): RouterShell (router + chaos-wrapped
    transport) + harness.Node (app, stores, WAL, consensus SM) + a real
    ConsensusReactor on the four consensus wire channels."""

    def __init__(
        self,
        net: "RouterNet",
        index: int,
        priv_key,
        *,
        fs=None,
        app=None,
        block_store=None,
        state_store=None,
        wal_dir=None,
    ):
        self.net = net
        self.index = index
        self.fs = fs
        self.shell = RouterShell(
            net.memory,
            index,
            net.genesis.chain_id,
            chaos=net.chaos,
            key_seed="routernet",
            moniker=f"rn{index}",
            max_connected=max(64, net.degree * 4),
            peer_queue_size=net.queue_size * 2,
            # routernet_xl: a per-node TCP/UDS transport for cross-process
            # links (chaos-wrapped by the shell like the memory transport)
            extra_transports=net._extra_transports_for(index),
        )
        self.node_id = self.shell.node_id
        clock = net._clock_for(self.node_id)
        self.inner = Node(
            net.genesis,
            priv_key,
            config=net.config,
            app=app,
            fs=fs,
            clock=clock,
            block_store=block_store,
            state_store=state_store,
            wal_dir=wal_dir,
        )
        r = self.shell.router
        qs = net.queue_size
        self.state_ch = r.open_channel(
            STATE_CHANNEL, name="cs-state", priority=6,
            encode=m.encode_message, decode=m.decode_message, queue_size=qs,
        )
        self.data_ch = r.open_channel(
            DATA_CHANNEL, name="cs-data", priority=10,
            encode=m.encode_message, decode=m.decode_message, queue_size=qs,
        )
        self.vote_ch = r.open_channel(
            VOTE_CHANNEL, name="cs-vote", priority=7,
            encode=m.encode_message, decode=m.decode_message, queue_size=qs,
        )
        self.bits_ch = r.open_channel(
            VOTE_SET_BITS_CHANNEL, name="cs-bits", priority=1,
            encode=m.encode_message, decode=m.decode_message, queue_size=qs,
        )
        # evidence (0x38): priority 6 — same tier as cs-state (node.py's
        # choice): accountability traffic must not starve behind block
        # parts, but never outranks them either. Queue sized like the
        # consensus channels: evidence is rare, but a committee-scale
        # commit storm shares the router's send loop, and a dropped
        # evidence frame costs a whole BROADCAST_SLEEP re-offer cycle.
        self.ev_ch = r.open_channel(
            EVIDENCE_CHANNEL, name="evidence", priority=6,
            encode=lambda ev: ev.encode(), decode=decode_evidence,
            queue_size=qs,
        )
        self.reactor: ConsensusReactor | None = None
        self.ev_reactor: EvidenceReactor | None = None
        # statesync serving (BootFleet): channels + reactor exist only
        # when the net opts in — a plain consensus soak carries zero
        # extra tasks
        self.ss_reactor: StateSyncReactor | None = None
        if net.statesync:
            for cid, name in (
                (SNAPSHOT_CHANNEL, "ss-snapshot"),
                (CHUNK_CHANNEL, "ss-chunk"),
                (LIGHT_BLOCK_CHANNEL, "ss-lb"),
                (PARAMS_CHANNEL, "ss-params"),
            ):
                setattr(
                    self,
                    name.replace("-", "_") + "_ch",
                    r.open_channel(
                        cid, name=name, priority=3,
                        encode=ss_msgs.encode_message,
                        decode=ss_msgs.decode_message,
                        queue_size=qs,
                    ),
                )

    # convenience mirrors of the inner harness node
    @property
    def cs(self):
        return self.inner.cs

    @property
    def block_store(self):
        return self.inner.block_store

    async def prepare(self) -> None:
        """Build the full stack and bring the ROUTER + REACTORS up, but
        do not start the consensus SM yet — node.py's ordering, so the
        first proposal isn't broadcast into a hook-less void. The net's
        `prepare_hook` (the Byzantine injection seam — see
        consensus/byzantine.py) runs LAST, after the reactor exists and
        before any vote is signed."""
        await self.inner.start(start_consensus=False)
        self.reactor = ConsensusReactor(
            self.inner.cs,
            self.state_ch,
            self.data_ch,
            self.vote_ch,
            self.bits_ch,
            self.shell.peer_manager.subscribe(),
            gossip_sleep=self.net.gossip_sleep,
            stall_refresh_s=self.net.stall_refresh_s,
            catchup_rate=self.net.catchup_rate,
            catchup_burst=self.net.catchup_burst,
        )
        # the evidence reactor rides the same peer-update feed: pending
        # DuplicateVoteEvidence gossips over the real (chaos-wrapped)
        # byte path instead of moving only inside proposed blocks
        self.ev_reactor = EvidenceReactor(
            self.inner.evidence_pool,
            self.ev_ch,
            self.shell.peer_manager.subscribe(),
        )
        if self.net.statesync:
            self.ss_reactor = StateSyncReactor(
                self.net.genesis.chain_id,
                self.inner.app_conns,
                self.inner.state_store,
                self.inner.block_store,
                self.ss_snapshot_ch,
                self.ss_chunk_ch,
                self.ss_lb_ch,
                self.ss_params_ch,
                self.shell.peer_manager.subscribe(),
                initial_height=self.net.genesis.initial_height,
                bootd_config=self.net.bootd_config,
            )
        await self.shell.router.start()
        await self.reactor.start()
        await self.ev_reactor.start()
        if self.ss_reactor is not None:
            await self.ss_reactor.start()
        if self.net.prepare_hook is not None:
            self.net.prepare_hook(self)

    async def go(self) -> None:
        await self.inner.cs.start()

    async def start(self) -> None:
        await self.prepare()
        await self.go()

    async def stop(self) -> None:
        if self.ss_reactor is not None:
            await self.ss_reactor.stop()
        if self.ev_reactor is not None:
            await self.ev_reactor.stop()
        if self.reactor is not None:
            await self.reactor.stop()
        await self.inner.stop()
        await self.shell.router.stop()

    async def statesync_join(self, sync_config: SyncConfig) -> None:
        """Cold-join the running committee: statesync a snapshot (chunks
        from donors' BootDs, backfill sigs batched onto the hub backfill
        lane), point the consensus SM at the restored state, then start
        it — the reactor's own catch-up gossip closes the snapshot->tip
        gap, exactly like a restarted node."""
        if self.ss_reactor is None:
            raise RuntimeError("statesync_join requires RouterNet(statesync=True)")
        state = await self.ss_reactor.sync(sync_config)
        self.inner.cs.update_to_state(state)
        await self.go()


class RouterNet:
    """N consensus nodes over real routers under one seeded
    ChaosNetwork. First `n_vals` nodes are validators; `n_full` extra
    nodes follow consensus without voting (and exercise the catch-up
    gossip as perpetual non-signers)."""

    def __init__(
        self,
        n_vals: int,
        *,
        n_full: int = 0,
        config=None,
        chaos=None,  # libs/chaos.ChaosNetwork (shared controller)
        base_clock=None,  # frozen ManualClock => bit-reproducible stamps
        key_type: str = "ed25519",
        degree: int = 8,
        topo_seed: int = 0,
        gossip_sleep: float | None = None,
        stall_refresh_s: float | None = None,
        use_hub: bool = True,
        fs_factory=None,  # index -> libs/chaosfs.ChaosFS | None (per node)
        app_factory=None,  # index -> ABCI app | None (default KVStore)
        # called with each RouterNode at the end of prepare() — after
        # router+reactors are up, before the SM signs anything. The
        # Byzantine injection seam (consensus/byzantine.byz_prepare_hook)
        # and the only way a traitor enters a net: RouterNet itself
        # never imports the strategy layer (byz-containment).
        prepare_hook=None,
        # per-peer catch-up pacing (reactor token bucket): None = auto
        # (unlimited on small nets, bounded at committee scale — a byz
        # lag-storm must not let laggards eat the donors' loop share)
        catchup_rate: float | None = None,
        catchup_burst: int | None = None,
        # BootFleet: every node opens the statesync channels and runs a
        # StateSyncReactor (serving through its BootD); joiners built via
        # make_joiner() use the same reactor to cold-join the committee
        statesync: bool = False,
        bootd_config=None,
    ):
        self.genesis, self.keys = make_genesis(n_vals, key_type=key_type)
        self.config = config or fast_config()
        self.chaos = chaos
        self.base_clock = base_clock
        self.memory = MemoryNetwork()
        self.degree = degree
        self.n = n_vals + n_full
        # big nets: slower per-peer gossip polls (tasks scale with edges)
        if gossip_sleep is None:
            gossip_sleep = 0.05 if self.n <= 16 else 0.3
        self.gossip_sleep = gossip_sleep
        if stall_refresh_s is None and self.n > 16:
            # committee-scale rounds legitimately idle for many seconds
            # (storm-sized timeouts); a 1s refresh would resend-storm
            self.stall_refresh_s = 4.0 + self.n / 25.0
        else:
            self.stall_refresh_s = stall_refresh_s
        # commit-time storms at committee scale overflow the default
        # 1024-slot channel buffers; a dropped NewRoundStep/HasVote is
        # recoverable (stall-refresh) but costs seconds each time
        self.queue_size = 1024 if self.n <= 16 else 16384
        self.use_hub = use_hub
        self.statesync = statesync
        self.bootd_config = bootd_config
        self._joiners = 0
        self._hub = None
        self._fs_factory = fs_factory
        self._app_factory = app_factory
        self.prepare_hook = prepare_hook
        # catch-up pacing auto-sizing: small nets stay unlimited (every
        # existing smoke keeps its latency); committees bound each
        # lagging peer to a vote budget so N stragglers (or N liars
        # claiming to lag) cost the donor O(N * rate), not O(N * chain)
        if catchup_rate is None and self.n > 16:
            catchup_rate = 64.0 * self.n  # votes/s per lagging peer
        self.catchup_rate = catchup_rate
        self.catchup_burst = catchup_burst
        self._fs: dict[int, object] = {}
        self.edges = topology_edges(self.n, degree, topo_seed)
        # construction hook: routernet_xl's worker slice overrides this
        # to build only the node indices its process hosts
        self.nodes: list[RouterNode] = self._build_nodes()
        # cold nodes built by make_joiner(): stopped with the net but
        # deliberately NOT in self.nodes — heights()/wait_for_height
        # measure the committee, and a joiner mid-statesync has no height
        self.joiners: list[RouterNode] = []

    # -- construction ----------------------------------------------------

    def _clock_for(self, node_id: str):
        if self.chaos is not None:
            # per-validator skew/drift drawn from (seed, node_id): node
            # ids are derived from (key_seed, index), so clocks are
            # identical across same-seed runs
            return self.chaos.clock_for(node_id, base=self.base_clock)
        return self.base_clock

    def _node_fs(self, i: int):
        if i not in self._fs:
            self._fs[i] = (
                self._fs_factory(i) if self._fs_factory is not None else None
            )
        return self._fs[i]

    def _build_nodes(self) -> list[RouterNode]:
        return [self._build_node(i) for i in range(self.n)]

    def _extra_transports_for(self, index: int) -> list:
        """Additional (socket) transports for node `index` — the
        routernet_xl worker-slice seam; in-process nets run none."""
        return []

    def _build_node(
        self, i: int, *, app=None, block_store=None, state_store=None,
        wal_dir=None,
    ) -> RouterNode:
        key = self.keys[i] if i < len(self.keys) else None
        if app is None and self._app_factory is not None:
            app = self._app_factory(i)
        return RouterNode(
            self,
            i,
            key,
            fs=self._node_fs(i),
            app=app,
            block_store=block_store,
            state_store=state_store,
            wal_dir=wal_dir,
        )

    def make_joiner(self, *, app=None, donors: int = 3) -> RouterNode:
        """Build a cold full node (no validator key, empty stores) wired
        to `donors` committee members' addresses. Caller drives the join:
        `await j.prepare(); await j.statesync_join(cfg)`. Requires
        statesync=True (the joiner needs donors serving snapshots)."""
        if not self.statesync:
            raise RuntimeError("make_joiner requires RouterNet(statesync=True)")
        idx = self.n + self._joiners
        self._joiners += 1
        if app is None and self._app_factory is not None:
            app = self._app_factory(idx)
        node = RouterNode(self, idx, None, app=app)
        self.joiners.append(node)
        # deterministic donor choice: spread joiners across the
        # committee so N joiners don't all dogpile node 0
        for k in range(min(donors, self.n)):
            donor = self.nodes[(idx + k) % self.n]
            node.shell.peer_manager.add_address(donor.shell.address())
        return node

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        if self.use_hub:
            from ..crypto import verify_hub as vh

            self._hub = vh.acquire_hub()
        # bring every router+reactor up first, then connect, then start
        # every SM together: node i must not burn rounds alone while
        # node i+1..N-1 are still constructing
        for node in self.nodes:
            await node.prepare()
        self._connect()
        await asyncio.gather(*(node.go() for node in self.nodes))

    def _connect(self) -> None:
        for i, j in self.edges:
            self.nodes[i].shell.peer_manager.add_address(
                self.nodes[j].shell.address()
            )

    async def stop(self) -> None:
        results = await asyncio.gather(
            *(node.stop() for node in self.nodes + self.joiners),
            return_exceptions=True,
        )
        for r in results:
            if isinstance(r, Exception):
                # teardown best-effort; surface in debug logs only
                import logging

                logging.getLogger("routernet").debug("node stop: %r", r)
        if self._hub is not None:
            from ..crypto import verify_hub as vh

            vh.release_hub()
            self._hub = None

    # -- chaos-fs crash model -------------------------------------------

    async def crash(self, i: int) -> None:
        """Kill node i mid-consensus. With a per-node ChaosFS the crash
        model applies: `halt()` first so the clean in-process teardown's
        WAL flush/fsync can't launder durability, then
        `simulate_crash()` drops every un-fsynced byte (possibly tearing
        a record) exactly as if the process had died."""
        node = self.nodes[i]
        fs = node.fs
        if fs is not None:
            fs.halt()
        await node.stop()
        if fs is not None:
            fs.simulate_crash()

    async def restart(self, i: int) -> RouterNode:
        """Bring node i back on the SAME stores/app/WAL dir (and node
        key): WAL open-time repair + ABCI handshake + reactor catch-up
        gossip do the recovery — no harness assistance."""
        old = self.nodes[i]
        node = self._build_node(
            i,
            app=old.inner.app,  # harness.Node wraps it in fresh AppConns
            block_store=old.inner.block_store,
            state_store=old.inner.state_store,
            wal_dir=old.inner.wal_dir,
        )
        self.nodes[i] = node
        await node.start()
        # re-advertise addresses in both directions: the restarted side
        # redials its topology neighbors and they redial it
        for a, b in self.edges:
            if a == i or b == i:
                other = self.nodes[b if a == i else a]
                node.shell.peer_manager.add_address(other.shell.address())
                other.shell.peer_manager.add_address(node.shell.address())
        return node

    # -- observation -----------------------------------------------------

    def heights(self) -> list[int]:
        return [n.block_store.height() for n in self.nodes]

    def min_height(self) -> int:
        return min(self.heights())

    async def wait_for_height(self, height: int, timeout: float = 60.0) -> None:
        await asyncio.gather(
            *(n.cs.wait_for_height(height, timeout) for n in self.nodes)
        )

    def block_fingerprints(self, upto: int, node: int = 0) -> list[bytes]:
        """Encoded block bytes for heights 1..upto from one node — the
        bit-reproducibility fingerprint (header + data + last commit,
        everything on the wire)."""
        store = self.nodes[node].block_store
        out = []
        for h in range(1, upto + 1):
            blk = store.load_block(h)
            out.append(blk.encode() if blk is not None else b"")
        return out

    def app_hash_chain(self, upto: int, node: int = 0) -> list[bytes]:
        store = self.nodes[node].block_store
        out = []
        for h in range(1, upto + 1):
            blk = store.load_block(h)
            out.append(blk.header.app_hash if blk is not None else b"")
        return out

    def hashes_agree(self, upto: int) -> bool:
        """Every node that holds height h agrees on its hash, for all
        h <= upto (a node may legitimately still be catching up)."""
        for h in range(1, upto + 1):
            seen = set()
            for n in self.nodes:
                blk = n.block_store.load_block(h)
                if blk is not None:
                    seen.add(blk.hash())
            if len(seen) > 1:
                return False
        return True
