"""ByzNet — Byzantine validator strategies over RouterNet.

Eleven PRs of chaos exercised crash, network, storage and clock faults;
this module finally tests the "B" in BFT: a validator that *lies*. A
`ByzantineNode` turns one RouterNode into a seeded, deterministic
traitor by wrapping exactly two seams — the node's signer
(`TraitorSigner` replaces the MockPV) and the consensus reactor's send
path (`ConsensusReactor._send_nowait`, overridden on the INSTANCE) — so
no honest code changes and production wiring is structurally unable to
reach this module (the tmtlint ``byz-containment`` rule pins that:
only the scenario harness and tests may import it).

Strategies (compose freely via `ByzConfig.strategies`):

  equivocate            double-sign prevotes/precommits: the honest vote
                        plus a properly-signed twin for a fabricated
                        conflicting block id at the same (H,R,S). In
                        ``both`` mode every peer receives the pair
                        back-to-back (deterministic local detection →
                        DuplicateVoteEvidence on every honest node); in
                        ``split`` mode half the peers get the twin
                        instead, so detection must happen where honest
                        relay gossip intersects.
  conflicting_proposal  as proposer, serve a signed conflicting proposal
                        (fabricated block id) to a seeded camp of peers.
  amnesia               ignore the lock: prevote the CURRENT proposal
                        (or nil) even while locked on an earlier block.
  withhold_votes        starve a seeded fraction of peers of our own
                        votes (honest relays may still heal them).
  withhold_precommits   never send our own precommits to anyone — the
                        committee must commit on honest votes alone
                        (this also pins the commit signer set, the
                        bit-reproducibility construction at f=1).
  withhold_parts        drop outbound block parts to the withheld peers.
  invalid_sig           gossip a vote with a garbage signature once per
                        (height, peer): stage-1 ingest disproves it and
                        the peer charges US (PeerError → score/ban —
                        audited, the accountability half).
  future_round_flood    broadcast properly-signed votes for far-future
                        rounds: the `HeightVoteSet.wanted` DoS guard
                        must drop them without burning verify capacity.
  lying_frames          lie on the state channel: NewRoundStep claims a
                        height behind ours (baiting donors into catch-up
                        service — what per-peer catch-up pacing bounds)
                        and HasVote claims votes that don't exist
                        (starvation that VoteSetBits reconciliation and
                        the stall-refresh must heal).

Every decision is a pure function of (seed, strategy, coordinates) —
never of arrival order or wall time — so two same-seed byz runs take
bit-identical actions, and with the RouterNet determinism construction
(frozen clock + pinned signer set) produce bit-identical block AND
evidence bytes (tests/test_byzantine.py).

`audit_net` is the cross-node safety auditor every byz scenario runs:
no two honest nodes may ever commit different block ids at any height,
app-hash chains must agree, every equivocator must yield
DuplicateVoteEvidence committed on chain within K heights, and
invalid-signature gossip must have cost the traitor (peer score/ban on
some honest node).
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import dataclass, field

from ..crypto.hashes import sha256
from ..p2p.types import Envelope
from ..privval import PrivValidator
from ..types.block import BlockID, PartSetHeader
from ..types.evidence import DuplicateVoteEvidence, LightClientAttackEvidence
from ..types.keys import SignedMsgType
from ..types.vote import Proposal, Vote
from . import messages as m
from .reactor import DATA_CHANNEL, STATE_CHANNEL, VOTE_CHANNEL

log = logging.getLogger("byzantine")

#: the full strategy taxonomy (ByzConfig.strategies ⊆ this)
STRATEGIES = frozenset(
    {
        "equivocate",
        "conflicting_proposal",
        "amnesia",
        "withhold_votes",
        "withhold_precommits",
        "withhold_parts",
        "invalid_sig",
        "future_round_flood",
        "lying_frames",
    }
)

#: bounded per-node action log (wedge dumps carry it; a runaway traitor
#: must not OOM the harness)
MAX_ACTION_LOG = 4096


@dataclass(frozen=True)
class ByzConfig:
    """One traitor's plan. All knobs deterministic in `seed`."""

    strategies: tuple[str, ...]
    seed: int = 0
    #: heights at which to equivocate / serve conflicting proposals
    #: (None = every height)
    equiv_heights: tuple[int, ...] | None = None
    #: vote types to double-sign
    equiv_types: tuple[SignedMsgType, ...] = (
        SignedMsgType.PREVOTE,
        SignedMsgType.PRECOMMIT,
    )
    #: False → every peer gets (honest, twin) back-to-back; True → a
    #: seeded half of the peers receives ONLY the twin
    equiv_split: bool = False
    #: fraction of peers starved by withhold_votes / withhold_parts
    withhold_frac: float = 0.5
    #: future_round_flood: votes per burst and how far ahead they claim
    flood_votes: int = 4
    flood_round_offset: int = 3
    #: lying_frames: how far behind NewRoundStep claims to be
    lie_behind: int = 2

    def __post_init__(self):
        unknown = set(self.strategies) - STRATEGIES
        if unknown:
            raise ValueError(f"unknown byzantine strategies: {sorted(unknown)}")

    def active(self, name: str) -> bool:
        return name in self.strategies

    def equivocates_at(self, height: int, type_: SignedMsgType) -> bool:
        if not self.active("equivocate"):
            return False
        if type_ not in self.equiv_types:
            return False
        return self.equiv_heights is None or height in self.equiv_heights


def _decide(seed: int, tag: str, *coords) -> float:
    """Deterministic decision draw in [0, 1): a pure function of the
    seed + coordinates, independent of arrival order and wall time —
    the same-seed bit-identity contract."""
    h = hashlib.sha256(
        f"tmtpu-byz:{seed}:{tag}:{coords!r}".encode()
    ).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


def _fabricated_block_id(seed: int, tag: str, *coords) -> BlockID:
    """A structurally-complete BlockID that can never match a real
    block: hash and part-set hash are seeded digests, total=1."""
    base = hashlib.sha256(f"tmtpu-byz-block:{seed}:{tag}:{coords!r}".encode())
    h1 = base.digest()
    h2 = sha256(h1)
    return BlockID(h1, PartSetHeader(1, h2))


class TraitorSigner(PrivValidator):
    """The traitor's signer: signs whatever the strategy calls for —
    the honest vote, an amnesiac rewrite, and (on demand) the
    equivocating twin — with NO double-sign guard. The sign-state
    protection is precisely what a Byzantine validator doesn't run."""

    def __init__(self, priv_key, owner: "ByzantineNode"):
        self.priv_key = priv_key
        self.owner = owner

    def get_pub_key(self):
        return self.priv_key.pub_key()

    # -- votes ----------------------------------------------------------

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        owner = self.owner
        cfg = owner.cfg
        if cfg.active("amnesia") and vote.type == SignedMsgType.PREVOTE:
            vote = self._amnesia_rewrite(vote)
        sig = self.priv_key.sign(vote.sign_bytes(chain_id))
        signed = Vote(**{**vote.__dict__, "signature": sig})
        if cfg.equivocates_at(vote.height, vote.type):
            self._make_twin(chain_id, signed)
        return signed

    def _amnesia_rewrite(self, vote: Vote) -> Vote:
        """Ignore the lock: while locked on block A, prevote whatever
        block is currently proposed (or nil) instead of re-confirming
        the lock — the classic amnesia deviation."""
        rs = self.owner.rs()
        if rs is None or rs.locked_round < 0 or vote.is_nil():
            return vote
        locked_hash = rs.locked_block.hash() if rs.locked_block else b""
        if vote.block_id.hash != locked_hash:
            return vote  # not a lock re-confirmation; nothing to forget
        if (
            rs.proposal_block is not None
            and rs.proposal_block.hash() != locked_hash
            and rs.proposal_block_parts is not None
        ):
            new_bid = BlockID(
                rs.proposal_block.hash(), rs.proposal_block_parts.header
            )
        else:
            from ..types.block import NIL_BLOCK_ID

            new_bid = NIL_BLOCK_ID
        self.owner.record(
            "amnesia", vote.height, vote.round, type=int(vote.type)
        )
        return Vote(**{**vote.__dict__, "block_id": new_bid})

    def _make_twin(self, chain_id: str, honest: Vote) -> None:
        """Sign the conflicting twin for the SAME (height, round, step)
        and park it for the send path. Same timestamp as the honest
        vote, so under a frozen clock the evidence pair is a pure
        function of (seed, height, round, type) — bit-identical across
        same-seed runs."""
        key = (honest.height, honest.round, honest.type)
        if key in self.owner.twins:
            return
        bid = _fabricated_block_id(
            self.owner.cfg.seed, "equiv", *key, self.owner.index
        )
        if bid == honest.block_id:  # can't happen, but never emit a dup
            return
        twin = Vote(**{**honest.__dict__, "block_id": bid, "signature": b""})
        sig = self.priv_key.sign(twin.sign_bytes(chain_id))
        self.owner.twins[key] = Vote(**{**twin.__dict__, "signature": sig})
        self.owner.record(
            "equivocate", honest.height, honest.round, type=int(honest.type)
        )

    # -- proposals ------------------------------------------------------

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        sig = self.priv_key.sign(proposal.sign_bytes(chain_id))
        signed = Proposal(**{**proposal.__dict__, "signature": sig})
        cfg = self.owner.cfg
        if cfg.active("conflicting_proposal") and (
            cfg.equiv_heights is None or proposal.height in cfg.equiv_heights
        ):
            key = (proposal.height, proposal.round)
            if key not in self.owner.proposal_twins:
                bid = _fabricated_block_id(
                    cfg.seed, "prop", *key, self.owner.index
                )
                twin = Proposal(
                    **{**proposal.__dict__, "block_id": bid, "signature": b""}
                )
                tsig = self.priv_key.sign(twin.sign_bytes(chain_id))
                self.owner.proposal_twins[key] = Proposal(
                    **{**twin.__dict__, "signature": tsig}
                )
                self.owner.record(
                    "conflicting_proposal", proposal.height, proposal.round
                )
        return signed


class ByzantineNode:
    """One traitor: wraps a prepared RouterNode (signer + reactor send
    path). Install happens between `RouterNode.prepare()` and `go()`,
    the same window node.py uses to attach reactors before the SM —
    no honest vote is ever signed by the original MockPV."""

    def __init__(self, net, index: int, cfg: ByzConfig):
        self.net = net
        self.index = index
        self.cfg = cfg
        self.node = None  # RouterNode, set by install()
        self.priv_key = net.keys[index]
        self.address = self.priv_key.pub_key().address()
        self.chain_id = net.genesis.chain_id
        self.twins: dict[tuple, Vote] = {}
        self.proposal_twins: dict[tuple, Proposal] = {}
        self.actions: list[dict] = []
        self.action_counts: dict[str, int] = {}
        self._flood_sent: set[tuple] = set()
        self._badsig_sent: set[tuple] = set()
        self._signer: TraitorSigner | None = None

    # -- install ---------------------------------------------------------

    def install(self, rnode) -> None:
        if rnode.index != self.index:
            raise ValueError("byzantine install on the wrong node")
        self.node = rnode
        self._signer = TraitorSigner(self.priv_key, self)
        rnode.inner.priv_val = self._signer
        rnode.inner.cs.priv_validator = self._signer
        reactor = rnode.reactor
        orig = reactor._send_nowait

        def byz_send(ch, env, _orig=orig):
            for c, e in self._rewrite(ch, env):
                _orig(c, e)

        # instance attribute shadows the class method: honest reactors
        # (and this reactor's honest *receive* half) are untouched
        reactor._send_nowait = byz_send

    def rs(self):
        cs = self.node.cs if self.node is not None else None
        return cs.rs if cs is not None else None

    @property
    def node_id(self) -> str:
        return self.node.node_id if self.node is not None else ""

    def record(self, action: str, height: int = 0, round_: int = 0, **detail):
        self.action_counts[action] = self.action_counts.get(action, 0) + 1
        if len(self.actions) < MAX_ACTION_LOG:
            entry = {"action": action, "height": height, "round": round_}
            entry.update(detail)
            self.actions.append(entry)

    def log_summary(self) -> dict:
        return {
            "index": self.index,
            "strategies": list(self.cfg.strategies),
            "seed": self.cfg.seed,
            "counts": dict(self.action_counts),
            "actions": self.actions[-256:],
        }

    # -- the send-path interceptor ---------------------------------------

    def _rewrite(self, ch, env: Envelope):
        """Map one outbound (channel, envelope) to zero or more — the
        entire byzantine wire behavior lives here. Unrecognized traffic
        passes through untouched."""
        msg = env.message
        try:
            if env.channel_id == VOTE_CHANNEL and isinstance(
                msg, (m.VoteMessage, m.VoteBatchMessage)
            ):
                return self._rewrite_votes(ch, env)
            if env.channel_id == DATA_CHANNEL and isinstance(
                msg, m.ProposalMessage
            ):
                return self._rewrite_proposal(ch, env)
            if env.channel_id == DATA_CHANNEL and isinstance(
                msg, m.BlockPartMessage
            ):
                return self._rewrite_part(ch, env)
            if env.channel_id == STATE_CHANNEL and isinstance(
                msg, m.NewRoundStepMessage
            ):
                return self._rewrite_round_step(ch, env)
            if env.channel_id == STATE_CHANNEL and isinstance(
                msg, (m.HasVoteMessage, m.HasVoteBatchMessage)
            ):
                return self._rewrite_has_votes(ch, env)
        except Exception:  # noqa: BLE001 — a buggy strategy must not
            # kill the gossip task; fall through to honest behavior
            log.exception("byzantine rewrite failed; sending honestly")
        return [(ch, env)]

    def _withheld(self, tag: str, height: int, peer_id: str) -> bool:
        return (
            _decide(self.cfg.seed, tag, height, peer_id)
            < self.cfg.withhold_frac
        )

    def _camp_b(self, height: int, round_: int, peer_id: str) -> bool:
        # camps are stable PER PEER (a traitor maintains one story per
        # neighbor): conflicting votes reach disjoint camps every
        # height, and detection must come from honest relay gossip
        # crossing the camp boundary
        del height, round_
        return _decide(self.cfg.seed, "camp", peer_id) < 0.5

    def _rewrite_votes(self, ch, env: Envelope):
        cfg = self.cfg
        votes = (
            env.message.votes
            if isinstance(env.message, m.VoteBatchMessage)
            else (env.message.vote,)
        )
        keep: list[Vote] = []
        extra: list[Vote] = []
        for v in votes:
            if v.validator_address != self.address:
                keep.append(v)  # relaying someone else's vote: honest
                continue
            if (
                cfg.active("withhold_precommits")
                and v.type == SignedMsgType.PRECOMMIT
            ):
                self.record("withhold_precommit", v.height, v.round)
                continue
            if (
                cfg.active("withhold_votes")
                and env.to
                and self._withheld("withhold", v.height, env.to)
            ):
                self.record(
                    "withhold_vote", v.height, v.round, peer=env.to[:8]
                )
                continue
            twin = self.twins.get((v.height, v.round, v.type))
            if twin is not None:
                if cfg.equiv_split and env.to and self._camp_b(
                    v.height, v.round, env.to
                ):
                    # camp B sees ONLY the twin; honest relays must
                    # bring the two halves together
                    keep.append(twin)
                    continue
                if not cfg.equiv_split:
                    # honest first, twin immediately after: FIFO per
                    # link means every receiver detects the conflict
                    # deterministically
                    extra.append(twin)
            keep.append(v)
        if (
            cfg.active("invalid_sig")
            and env.to
            and any(v.validator_address == self.address for v in votes)
        ):
            bad = self._bad_sig_vote(votes, env.to)
            if bad is not None:
                extra.append(bad)
        out = []
        if keep:
            out.append((ch, self._vote_env(keep, env)))
        for v in extra:
            out.append((ch, self._vote_env([v], env)))
        return out

    def _vote_env(self, votes: list[Vote], like: Envelope) -> Envelope:
        msg = (
            m.VoteMessage(votes[0])
            if len(votes) == 1
            else m.VoteBatchMessage(tuple(votes))
        )
        return Envelope(
            like.channel_id, msg, to=like.to, broadcast=like.broadcast
        )

    def _bad_sig_vote(self, votes, peer_id: str) -> Vote | None:
        """One garbage-signature vote per (height, peer): enough to
        prove the accountability path (stage-1 disproof → PeerError →
        score/ban) without turning the run into a disconnect storm."""
        own = next(v for v in votes if v.validator_address == self.address)
        key = (own.height, peer_id)
        if key in self._badsig_sent:
            return None
        self._badsig_sent.add(key)
        bid = _fabricated_block_id(
            self.cfg.seed, "badsig", own.height, own.round, self.index
        )
        garbage = hashlib.sha256(
            f"tmtpu-byz-badsig:{self.cfg.seed}:{key!r}".encode()
        ).digest() * 2  # 64 bytes, passes validate_basic, never verifies
        self.record("invalid_sig", own.height, own.round, peer=peer_id[:8])
        return Vote(
            **{**own.__dict__, "block_id": bid, "signature": garbage}
        )

    def _rewrite_proposal(self, ch, env: Envelope):
        msg = env.message
        twin = self.proposal_twins.get((msg.proposal.height, msg.proposal.round))
        if (
            twin is not None
            and env.to
            and self._camp_b(msg.proposal.height, msg.proposal.round, env.to)
        ):
            self.record(
                "serve_conflicting_proposal",
                msg.proposal.height,
                msg.proposal.round,
                peer=env.to[:8],
            )
            return [(ch, Envelope(env.channel_id, m.ProposalMessage(twin), to=env.to))]
        return [(ch, env)]

    def _rewrite_part(self, ch, env: Envelope):
        msg = env.message
        if (
            self.cfg.active("withhold_parts")
            and env.to
            and self._withheld("withhold_part", msg.height, env.to)
        ):
            self.record(
                "withhold_part", msg.height, msg.round, part=msg.part.index
            )
            return []
        return [(ch, env)]

    def _rewrite_round_step(self, ch, env: Envelope):
        out = []
        msg = env.message
        if self.cfg.active("lying_frames") and msg.height > 1:
            lied = m.NewRoundStepMessage(
                height=max(1, msg.height - self.cfg.lie_behind),
                round=0,
                step=1,
                seconds_since_start_time=msg.seconds_since_start_time,
                last_commit_round=0,
            )
            self.record("lie_round_step", msg.height, msg.round)
            out.append(
                (ch, Envelope(env.channel_id, lied, to=env.to, broadcast=env.broadcast))
            )
        else:
            out.append((ch, env))
        if self.cfg.active("future_round_flood"):
            out.extend(self._flood(ch, msg))
        return out

    def _flood(self, ch, step_msg):
        """Properly-signed votes for rounds far beyond round+1: the
        receiver's `HeightVoteSet.wanted` guard must shed them without
        spending signature verifications (the unwanted-round DoS drop
        the ingest pipeline mirrors)."""
        h = step_msg.height
        if (h,) in self._flood_sent:
            return []
        self._flood_sent.add((h,))
        rs = self.rs()
        base_round = (rs.round if rs is not None else 0) + self.cfg.flood_round_offset
        votes = []
        for i in range(self.cfg.flood_votes):
            r = base_round + i
            bid = _fabricated_block_id(self.cfg.seed, "flood", h, r, self.index)
            v = Vote(
                type=SignedMsgType.PREVOTE,
                height=h,
                round=r,
                block_id=bid,
                timestamp_ns=self.net.genesis.genesis_time_ns,
                validator_address=self.address,
                validator_index=self.index,
            )
            sig = self.priv_key.sign(v.sign_bytes(self.chain_id))
            votes.append(Vote(**{**v.__dict__, "signature": sig}))
        self.record("future_round_flood", h, base_round, n=len(votes))
        msg = (
            m.VoteBatchMessage(tuple(votes))
            if len(votes) > 1
            else m.VoteMessage(votes[0])
        )
        return [
            (
                self.node.reactor.vote_ch,
                Envelope(VOTE_CHANNEL, msg, broadcast=True),
            )
        ]

    def _rewrite_has_votes(self, ch, env: Envelope):
        if not self.cfg.active("lying_frames"):
            return [(ch, env)]
        msg = env.message
        entries = (
            list(msg.entries)
            if isinstance(msg, m.HasVoteBatchMessage)
            else [msg]
        )
        first = entries[0]
        n = len(self.net.keys)
        lies = []
        for idx in range(n):
            if _decide(
                self.cfg.seed, "lie_hasvote", first.height, first.round, idx
            ) < 0.5:
                lies.append(
                    m.HasVoteMessage(first.height, first.round, first.type, idx)
                )
        if lies:
            self.record(
                "lie_has_vote", first.height, first.round, n=len(lies)
            )
            entries.extend(lies)
        out_msg = (
            entries[0]
            if len(entries) == 1
            else m.HasVoteBatchMessage(tuple(entries[:m.MAX_BATCH_VOTES]))
        )
        return [
            (ch, Envelope(env.channel_id, out_msg, to=env.to, broadcast=env.broadcast))
        ]


def byz_prepare_hook(plan: dict[int, ByzConfig], registry: list | None = None):
    """RouterNet `prepare_hook` factory: wrap the planned indices as
    they come up (including crash→restart rebuilds — the traitor stays
    a traitor across its own crashes). `registry` collects the live
    ByzantineNode handles for the auditor; on a restart the fresh
    handle replaces its predecessor."""

    def hook(rnode) -> None:
        cfg = plan.get(rnode.index)
        if cfg is None:
            return
        bn = ByzantineNode(rnode.net, rnode.index, cfg)
        bn.install(rnode)
        if registry is not None:
            registry[:] = [b for b in registry if b.index != rnode.index]
            registry.append(bn)

    return hook


# -- the cross-node safety auditor ------------------------------------------


@dataclass
class AuditReport:
    """Structured verdict of `audit_net` — every byz scenario runs it."""

    ok: bool = True
    checked_height: int = 0
    honest: list[int] = field(default_factory=list)
    byzantine: list[int] = field(default_factory=list)
    conflicting_commits: list[dict] = field(default_factory=list)
    app_hash_mismatches: list[dict] = field(default_factory=list)
    #: equivocator address hex -> height its evidence committed at
    evidence_commit_heights: dict[str, int] = field(default_factory=dict)
    #: equivocator address hex -> commit height − equivocation height
    #: (the time-to-evidence-commit figure, in heights)
    evidence_lag_heights: dict[str, int] = field(default_factory=dict)
    missing_evidence: list[int] = field(default_factory=list)
    late_evidence: list[dict] = field(default_factory=list)
    #: light-client-attack accountability (the LightFleet axis):
    #: attributed signer address hex -> height its LCA evidence
    #: committed at, and commit height − conflicting height (the
    #: time-to-evidence-commit figure for light attacks)
    lca_commit_heights: dict[str, int] = field(default_factory=dict)
    lca_lag_heights: dict[str, int] = field(default_factory=dict)
    #: expected lunatic signers whose attack never reached the chain
    missing_lca: list[str] = field(default_factory=list)
    #: byz index -> {honest index: peer score} where penalized
    peer_penalties: dict[int, dict] = field(default_factory=dict)
    unpenalized: list[int] = field(default_factory=list)
    notes: list[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "checked_height": self.checked_height,
            "honest": self.honest,
            "byzantine": self.byzantine,
            "conflicting_commits": self.conflicting_commits,
            "app_hash_mismatches": self.app_hash_mismatches,
            "evidence_commit_heights": dict(self.evidence_commit_heights),
            "evidence_lag_heights": dict(self.evidence_lag_heights),
            "missing_evidence": self.missing_evidence,
            "late_evidence": self.late_evidence,
            "lca_commit_heights": dict(self.lca_commit_heights),
            "lca_lag_heights": dict(self.lca_lag_heights),
            "missing_lca": self.missing_lca,
            "peer_penalties": {
                str(k): v for k, v in self.peer_penalties.items()
            },
            "unpenalized": self.unpenalized,
            "notes": self.notes,
        }


def committed_duplicate_vote_evidence(node) -> dict[bytes, tuple[int, object]]:
    """Scan one node's committed chain for DuplicateVoteEvidence:
    equivocator address -> (first height committed at, the evidence)."""
    out: dict[bytes, tuple[int, object]] = {}
    store = node.block_store
    for h in range(1, store.height() + 1):
        blk = store.load_block(h)
        if blk is None:
            continue
        for ev in blk.evidence:
            if isinstance(ev, DuplicateVoteEvidence):
                addr = ev.vote_a.validator_address
                if addr not in out:
                    out[addr] = (h, ev)
    return out


def committed_light_client_attack_evidence(
    node,
) -> dict[bytes, tuple[int, object]]:
    """Scan one node's committed chain for LightClientAttackEvidence:
    attributed (byzantine) signer address -> (first height its evidence
    committed at, the evidence)."""
    out: dict[bytes, tuple[int, object]] = {}
    store = node.block_store
    for h in range(1, store.height() + 1):
        blk = store.load_block(h)
        if blk is None:
            continue
        for ev in blk.evidence:
            if isinstance(ev, LightClientAttackEvidence):
                for val in ev.byzantine_validators:
                    out.setdefault(val.address, (h, ev))
    return out


def audit_net(
    net,
    byz_nodes: list[ByzantineNode] | None = None,
    *,
    k_heights: int = 3,
    require_evidence: bool = True,
    expect_lca: tuple[bytes, ...] = (),
) -> AuditReport:
    """The safety + accountability audit (module docstring): agreement
    over every committed height, evidence accountability for every
    equivocator that actually produced a twin, and peer-level cost for
    invalid-signature gossip. Pure observation — reads stores and peer
    managers, never mutates the net."""
    byz_nodes = byz_nodes or []
    byz_idx = {b.index for b in byz_nodes}
    rep = AuditReport(
        honest=[n.index for n in net.nodes if n.index not in byz_idx],
        byzantine=sorted(byz_idx),
    )
    honest = [n for n in net.nodes if n.index not in byz_idx]
    if not honest:
        rep.ok = False
        rep.notes.append("no honest nodes to audit")
        return rep

    # 1+2: commit + app-hash agreement at every height any two honest
    # nodes share (a laggard legitimately misses the tip)
    max_h = max(n.block_store.height() for n in honest)
    rep.checked_height = max_h
    for h in range(1, max_h + 1):
        seen: dict[bytes, list[int]] = {}
        apps: dict[bytes, list[int]] = {}
        for n in honest:
            blk = n.block_store.load_block(h)
            if blk is None:
                continue
            seen.setdefault(blk.hash(), []).append(n.index)
            apps.setdefault(blk.header.app_hash, []).append(n.index)
        if len(seen) > 1:
            rep.conflicting_commits.append(
                {"height": h, "hashes": {k.hex()[:16]: v for k, v in seen.items()}}
            )
        if len(apps) > 1:
            rep.app_hash_mismatches.append(
                {"height": h, "hashes": {k.hex()[:16]: v for k, v in apps.items()}}
            )

    # 3: accountability — every equivocator that actually double-signed
    # must be committed on chain within K heights of the equivocation
    # the evidence attributes (a traitor double-signing every height is
    # measured against the height its COMMITTED pair came from).
    # `require_evidence=False` is for split-camp strategies where
    # detection rides probabilistic relay timing: safety and promptness
    # still bind; complete escape merely stops being an audit failure.
    best = max(honest, key=lambda n: n.block_store.height())
    committed = committed_duplicate_vote_evidence(best)
    for b in byz_nodes:
        if not b.twins:
            continue  # never actually equivocated (strategy inactive/idle)
        hit = committed.get(b.address)
        if hit is None:
            if require_evidence:
                rep.missing_evidence.append(b.index)
            else:
                rep.notes.append(
                    f"equivocator {b.index} escaped (best-effort detection)"
                )
            continue
        commit_h, ev = hit
        rep.evidence_commit_heights[b.address.hex()] = commit_h
        rep.evidence_lag_heights[b.address.hex()] = commit_h - ev.height
        if commit_h - ev.height > k_heights:
            rep.late_evidence.append(
                {
                    "index": b.index,
                    "equivocated_at": ev.height,
                    "committed_at": commit_h,
                    "k": k_heights,
                }
            )

    # 3b: light-client-attack accountability — every expected lunatic
    # signer (addresses from the scenario's LunaticProvider plan) must
    # appear in a committed LightClientAttackEvidence's attribution
    # within K heights of the conflicting (forged) height
    if expect_lca:
        lca = committed_light_client_attack_evidence(best)
        for addr in expect_lca:
            hit = lca.get(addr)
            if hit is None:
                rep.missing_lca.append(addr.hex())
                continue
            commit_h, ev = hit
            rep.lca_commit_heights[addr.hex()] = commit_h
            lag = commit_h - ev.conflicting_height
            rep.lca_lag_heights[addr.hex()] = lag
            if lag > k_heights:
                rep.late_evidence.append(
                    {
                        "lca_signer": addr.hex(),
                        "forged_at": ev.conflicting_height,
                        "committed_at": commit_h,
                        "k": k_heights,
                    }
                )

    # 4: invalid-signature gossip must have COST the traitor on at
    # least one honest node (score drop or ban — the PeerError path)
    for b in byz_nodes:
        if b.action_counts.get("invalid_sig", 0) == 0:
            continue
        penalties = {}
        for n in honest:
            score = n.shell.peer_manager.peer_score(b.node_id)
            if score is not None and score < 0:
                penalties[n.index] = score
        if penalties:
            rep.peer_penalties[b.index] = penalties
        else:
            rep.unpenalized.append(b.index)

    rep.ok = not (
        rep.conflicting_commits
        or rep.app_hash_mismatches
        or rep.missing_evidence
        or rep.late_evidence
        or rep.missing_lca
        or rep.unpenalized
    )
    return rep
