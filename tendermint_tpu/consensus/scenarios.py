"""Declarative chaos scenario sweep over RouterNet.

A `Scenario` names one fault shape — steady per-link rates
(`ChaosConfig`), a storage fault plan (`ChaosFSConfig`), and a timed
`Event` script (partitions forming and healing, a peer going gray, a
node crashing mid-consensus and restarting) — independent of committee
size and seed, so the SAME scenario runs as a 4-validator tier-1 smoke,
a 50-validator sweep, and a 150-validator soak (tests/test_routernet.py)
and as the `bench.py chaos_soak` config.

`run_scenario` drives it: build a RouterNet over real routers +
ChaosTransport, play the event script, and watch liveness — every node
must keep committing. The watchdog asserts all-nodes-progress (min
committed height advances and reaches the target); on a wedge it dumps
the flight recorder (libs/trace) plus the per-class chaos fault
counters, per-node heights and round states to disk, then reports a
structured outcome instead of hanging — the bench contract (bounded,
structured outcomes; the multichip discipline).

Node references in events are indices into the net (resolved modulo n,
so `node=-1` is "the last node"); partition groups may use the string
"rest" for "every node not named elsewhere in the event"."""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
from dataclasses import dataclass, field, replace

from ..libs.chaos import ChaosConfig, ChaosNetwork
from ..libs.chaosfs import ChaosFS, ChaosFSConfig
from .harness import GENESIS_TIME_NS, MS, fast_config
from .routernet import RouterNet, committee_config


@dataclass(frozen=True)
class Event:
    """One timed fault transition. `at_s` is scenario time (scaled by
    the runner's `time_scale` so the same script fits 4-validator and
    150-validator block cadences)."""

    at_s: float
    action: str  # partition | oneway | heal | gray | ungray | crash | restart
    groups: tuple = ()  # partition: tuple of groups (indices or "rest")
    src: tuple = ()  # oneway: sender group (indices or "rest")
    dst: tuple = ()  # oneway: receiver group
    node: int = 0  # gray/ungray/crash/restart target (index mod n)
    delay_ms: float = 0.0  # gray: fixed per-message delay


@dataclass(frozen=True)
class Scenario:
    name: str
    summary: str
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    events: tuple[Event, ...] = ()
    fs: ChaosFSConfig | None = None  # per-node storage faults (crash model)


# -- the named taxonomy ----------------------------------------------------

SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario(
            "baseline",
            "no faults — the control run every other scenario is read against",
        ),
        Scenario(
            "lossy_links",
            "drops + exponential delay + duplication + reordering on every link",
            chaos=ChaosConfig(
                drop_rate=0.05, delay_ms=5.0, duplicate_rate=0.02,
                reorder_rate=0.02,
            ),
        ),
        Scenario(
            "corrupt_wire",
            "seeded byte corruption on the live gossip byte-stream "
            "(malformed frames cost the sender its connection; redial heals)",
            chaos=ChaosConfig(corrupt_rate=0.02, delay_ms=2.0),
        ),
        Scenario(
            "asym_partition",
            "half-open link: node 0 stops RECEIVING while its own votes "
            "still flow out; heals mid-run — recovery must ride the "
            "reactor's catch-up gossip",
            events=(
                Event(0.8, "oneway", src=("rest",), dst=(0,)),
                Event(2.4, "heal"),
            ),
        ),
        Scenario(
            "gray_failure",
            "one peer goes slow-but-alive (fixed delay tuned near the "
            "gossip cadence), then recovers",
            events=(
                Event(0.5, "gray", node=1, delay_ms=120.0),
                Event(2.5, "ungray", node=1),
            ),
        ),
        Scenario(
            "bandwidth_crunch",
            "per-link leaky-bucket shaping: block parts queue behind "
            "votes and backlog becomes delivery delay",
            chaos=ChaosConfig(bandwidth_rate=192.0 * 1024),
        ),
        Scenario(
            "clock_skew",
            "per-validator wall-clock skew + oscillator drift (timeouts "
            "fire early/late); the vote-time floor keeps output deterministic",
            chaos=ChaosConfig(clock_skew_ms=80.0, clock_drift=0.02),
        ),
        Scenario(
            "crash_fs",
            "chaos-fs crash mid-consensus: a node dies with a torn WAL "
            "tail, restarts on the same stores, repairs, and catches up "
            "through catch-up gossip",
            fs=ChaosFSConfig(torn_write_rate=1.0),
            events=(
                Event(1.2, "crash", node=-1),
                Event(2.0, "restart", node=-1),
            ),
        ),
        Scenario(
            "full_taxonomy",
            "everything at once: lossy + corrupt + shaped links, clock "
            "skew/drift, a gray peer, an asymmetric partition cycle, and "
            "a chaos-fs crash/restart mid-consensus",
            chaos=ChaosConfig(
                drop_rate=0.02, delay_ms=3.0, duplicate_rate=0.01,
                reorder_rate=0.01, corrupt_rate=0.008,
                bandwidth_rate=512.0 * 1024, clock_skew_ms=60.0,
                clock_drift=0.01,
            ),
            fs=ChaosFSConfig(torn_write_rate=1.0),
            events=(
                Event(0.5, "gray", node=1, delay_ms=100.0),
                Event(0.8, "oneway", src=("rest",), dst=(0,)),
                Event(1.2, "crash", node=-1),
                Event(2.0, "restart", node=-1),
                Event(2.4, "heal"),
                Event(2.6, "ungray", node=1),
            ),
        ),
    )
}


@dataclass
class ScenarioResult:
    scenario: str
    seed: int
    n_vals: int
    n_full: int
    target_height: int
    ok: bool
    wedged: bool
    events_applied: list[str]
    heights: list[int]
    elapsed_s: float
    blocks_per_s: float
    recover_s: float | None  # last fault event -> all nodes past target
    faults: dict
    fs_faults: dict
    error: str = ""
    dump_path: str = ""

    def as_dict(self) -> dict:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "n_vals": self.n_vals,
            "n_full": self.n_full,
            "target_height": self.target_height,
            "outcome": "ok" if self.ok else ("wedged" if self.wedged else "error"),
            "events_applied": self.events_applied,
            "heights": self.heights,
            "elapsed_s": round(self.elapsed_s, 3),
            "blocks_per_s": round(self.blocks_per_s, 4),
            "recover_s": (
                round(self.recover_s, 3) if self.recover_s is not None else None
            ),
            "faults": self.faults,
            "fs_faults": self.fs_faults,
            "error": self.error,
            "dump_path": self.dump_path,
        }


def _resolve_group(group, n: int, named: set[int]) -> set[int]:
    out: set[int] = set()
    for g in group:
        if g == "rest":
            out |= set(range(n)) - named
        else:
            out.add(g % n)
    return out


def _event_indices(ev: Event, n: int) -> set[int]:
    named: set[int] = set()
    for group in (*ev.groups, ev.src, ev.dst):
        for g in group:
            if g != "rest":
                named.add(g % n)
    return named


async def _apply_event(ev: Event, net: RouterNet, chaos: ChaosNetwork) -> None:
    n = net.n
    named = _event_indices(ev, n)
    ids = lambda idxs: {net.nodes[i].node_id for i in idxs}  # noqa: E731
    if ev.action == "partition":
        chaos.partition(
            *(ids(_resolve_group(g, n, named)) for g in ev.groups)
        )
    elif ev.action == "oneway":
        chaos.partition_oneway(
            ids(_resolve_group(ev.src, n, named)),
            ids(_resolve_group(ev.dst, n, named)),
        )
    elif ev.action == "heal":
        chaos.heal()
    elif ev.action == "gray":
        chaos.set_gray(net.nodes[ev.node % n].node_id, ev.delay_ms)
    elif ev.action == "ungray":
        chaos.set_peer_config(net.nodes[ev.node % n].node_id, chaos.config)
    elif ev.action == "crash":
        await net.crash(ev.node % n)
    elif ev.action == "restart":
        await net.restart(ev.node % n)
    else:
        raise ValueError(f"unknown scenario event action {ev.action!r}")


def _round_states(net: RouterNet) -> list[dict]:
    out = []
    for node in net.nodes:
        cs = node.cs
        if cs is None:
            out.append({"index": node.index, "state": "down"})
            continue
        out.append(
            {
                "index": node.index,
                "height": cs.rs.height,
                "round": cs.rs.round,
                "step": int(cs.rs.step),
                "committed": node.block_store.height(),
                "running": bool(cs.is_running),
            }
        )
    return out


def _dump_wedge(
    scenario: Scenario,
    net: RouterNet,
    chaos: ChaosNetwork | None,
    dump_dir: str,
    detail: dict,
) -> str:
    """Auto-dump on wedge: flight recorder ring (when tracing is on)
    plus a JSON snapshot of per-class chaos fault counters and every
    node's round state — the post-mortem the 150-validator soak promises
    (acceptance: any wedge is diagnosable from disk)."""
    from ..libs import trace

    os.makedirs(dump_dir, exist_ok=True)
    flight = trace.auto_dump(f"chaos-wedge-{scenario.name}")
    path = os.path.join(dump_dir, f"chaos-wedge-{scenario.name}.json")
    payload = {
        "scenario": scenario.name,
        "summary": scenario.summary,
        "faults": dict(chaos.faults) if chaos is not None else {},
        "fs_faults": {
            i: dict(fs.faults)
            for i, fs in net._fs.items()
            if fs is not None
        },
        "nodes": _round_states(net),
        "flight_dump": flight or "",
        **detail,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, default=str)
    return path


async def run_scenario(
    scenario: Scenario | str,
    *,
    n_vals: int = 4,
    n_full: int = 0,
    target_height: int = 3,
    seed: int = 1,
    config=None,
    degree: int = 8,
    timeout_s: float = 60.0,
    stall_s: float = 20.0,
    time_scale: float = 1.0,
    gossip_sleep: float | None = None,
    use_hub: bool = True,
    dump_dir: str | None = None,
    base_clock=None,
) -> ScenarioResult:
    """One seeded scenario run. Returns a structured result — it does
    NOT raise on a wedge (`result.ok` / `result.wedged`); the hard
    `timeout_s` bound means a caller can sweep the whole taxonomy and
    still terminate."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    if dump_dir is None:
        dump_dir = os.environ.get("TMTPU_CHAOS_DUMP_DIR") or tempfile.mkdtemp(
            prefix="chaos-dumps-"
        )
    chaos_cfg = replace(scenario.chaos, seed=seed)
    # events (partitions/gray) need the controller even when every steady
    # rate is zero
    chaos = (
        ChaosNetwork(chaos_cfg)
        if (chaos_cfg.enabled() or scenario.events)
        else None
    )
    fs_factory = None
    if scenario.fs is not None:
        fs_cfg = scenario.fs

        def fs_factory(i: int, _cfg=fs_cfg, _seed=seed):
            # one ChaosFS per node: a crash must only tear ITS WAL
            return ChaosFS(replace(_cfg, seed=_seed * 1009 + i))

    if base_clock is None:
        from ..libs.clock import ManualClock

        # frozen behind genesis: the vote-time floor pins every stamp
        base_clock = ManualClock(GENESIS_TIME_NS - 500 * MS)
    if config is None:
        # small nets: fast multi-round timeouts; committees: storm-sized
        # timers (see routernet.committee_config — timers only bound the
        # unhappy path, quorum drives the happy one)
        config = fast_config() if n_vals <= 16 else committee_config(n_vals)
    net = RouterNet(
        n_vals,
        n_full=n_full,
        config=config,
        chaos=chaos,
        base_clock=base_clock,
        degree=degree,
        topo_seed=seed,
        gossip_sleep=gossip_sleep,
        use_hub=use_hub,
        fs_factory=fs_factory,
    )
    loop = asyncio.get_running_loop()
    heights: list[int] = []
    faults: dict = {}
    fs_faults: dict = {}
    ok = wedged = False
    error = dump_path = ""
    recover_s: float | None = None
    t0 = loop.time()
    t_done = t0
    try:
        await net.start()
    except Exception as e:  # noqa: BLE001 — structured outcome contract
        # best-effort teardown of the partially-started net: the hub
        # refcount and any already-running routers/reactors must not
        # leak into the caller's loop (run_sweep runs more scenarios)
        await net.stop()
        return ScenarioResult(
            scenario=scenario.name, seed=seed, n_vals=n_vals, n_full=n_full,
            target_height=target_height, ok=False, wedged=False,
            events_applied=[], heights=net.heights(), elapsed_s=0.0,
            blocks_per_s=0.0, recover_s=None,
            faults=dict(chaos.faults) if chaos is not None else {},
            fs_faults={}, error=f"start failed: {e!r}",
        )
    event_err: list[str] = []
    events_applied: list[str] = []
    last_event_t = [t0]

    async def drive_events() -> None:
        for ev in sorted(scenario.events, key=lambda e: e.at_s):
            await asyncio.sleep(
                max(0.0, ev.at_s * time_scale - (loop.time() - t0))
            )
            try:
                await _apply_event(ev, net, chaos)
                events_applied.append(ev.action)
            except Exception as e:  # noqa: BLE001 — recorded, run continues
                event_err.append(f"{ev.action}@{ev.at_s}: {e!r}")
            last_event_t[0] = loop.time()

    events_task = loop.create_task(drive_events(), name="scenario.events")
    try:
        # -- liveness watchdog: all nodes must progress ----------------
        # Completion is gated on the WHOLE event script having fired
        # plus at least one height of post-event progress: a fast
        # committee must not "pass" a crash scenario by reaching the
        # target before the crash happens.
        deadline = t0 + timeout_s
        last_min = -1
        last_progress = loop.time()
        post_event_target: int | None = (
            target_height if not scenario.events else None
        )
        while True:
            await asyncio.sleep(0.2)
            mh = net.min_height()
            now = loop.time()
            if mh > last_min:
                last_min = mh
                last_progress = now
            if post_event_target is None and events_task.done():
                post_event_target = max(target_height, mh + 1)
            if post_event_target is not None and mh >= post_event_target:
                ok = True
                t_done = now
                break
            if now > deadline or (now - last_progress) > stall_s * time_scale:
                wedged = True
                t_done = now
                break
    except Exception as e:  # noqa: BLE001 — structured outcome, not a raise
        error = repr(e)
        t_done = loop.time()
    finally:
        events_task.cancel()
        # reap without absorbing our own cancellation
        await asyncio.gather(events_task, return_exceptions=True)
        heights = net.heights()
        faults = dict(chaos.faults) if chaos is not None else {}
        fs_faults = {
            str(i): dict(fs.faults)
            for i, fs in net._fs.items()
            if fs is not None
        }
        if wedged or error:
            dump_path = _dump_wedge(
                scenario,
                net,
                chaos,
                dump_dir,
                {
                    "seed": seed,
                    "n_vals": n_vals,
                    "target_height": target_height,
                    "elapsed_s": round(t_done - t0, 3),
                    "event_errors": event_err,
                    "error": error,
                },
            )
        await net.stop()
    if event_err and not error:
        error = "; ".join(event_err)
    elapsed = max(t_done - t0, 1e-9)
    if ok and scenario.events:
        recover_s = max(0.0, t_done - last_event_t[0])
    # throughput from what was actually COMMITTED net-wide (the min
    # height), not the requested target: an event-gated run can outrun
    # target_height, and chaos_soak compares these numbers across rounds
    committed = min(heights) if heights else 0
    return ScenarioResult(
        scenario=scenario.name,
        seed=seed,
        n_vals=n_vals,
        n_full=n_full,
        target_height=target_height,
        ok=ok,
        wedged=wedged,
        events_applied=events_applied,
        heights=heights,
        elapsed_s=elapsed,
        blocks_per_s=(committed / elapsed) if ok else 0.0,
        recover_s=recover_s,
        faults=faults,
        fs_faults=fs_faults,
        error=error,
        dump_path=dump_path,
    )


async def run_sweep(
    names: list[str] | None = None,
    **kwargs,
) -> list[ScenarioResult]:
    """Run a list of named scenarios sequentially (the full registry by
    default) with shared runner kwargs; always returns one structured
    result per scenario."""
    out = []
    for name in names or list(SCENARIOS):
        out.append(await run_scenario(name, **kwargs))
    return out
