"""Declarative chaos scenario sweep over RouterNet.

A `Scenario` names one fault shape — steady per-link rates
(`ChaosConfig`), a storage fault plan (`ChaosFSConfig`), and a timed
`Event` script (partitions forming and healing, a peer going gray, a
node crashing mid-consensus and restarting) — independent of committee
size and seed, so the SAME scenario runs as a 4-validator tier-1 smoke,
a 50-validator sweep, and a 150-validator soak (tests/test_routernet.py)
and as the `bench.py chaos_soak` config.

`run_scenario` drives it: build a RouterNet over real routers +
ChaosTransport, play the event script, and watch liveness — every node
must keep committing. The watchdog asserts all-nodes-progress (min
committed height advances and reaches the target); on a wedge it dumps
the flight recorder (libs/trace) plus the per-class chaos fault
counters, per-node heights and round states to disk, then reports a
structured outcome instead of hanging — the bench contract (bounded,
structured outcomes; the multichip discipline).

Node references in events are indices into the net (resolved modulo n,
so `node=-1` is "the last node"); partition groups may use the string
"rest" for "every node not named elsewhere in the event"."""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
from dataclasses import dataclass, field, replace

from ..libs.chaos import ChaosConfig, ChaosNetwork
from ..libs.chaosfs import ChaosFS, ChaosFSConfig
from .byzantine import (
    ByzConfig,
    audit_net,
    byz_prepare_hook,
    committed_light_client_attack_evidence,
)
from .harness import GENESIS_TIME_NS, MS, fast_config
from .routernet import RouterNet, committee_config


@dataclass(frozen=True)
class Event:
    """One timed fault transition. `at_s` is scenario time (scaled by
    the runner's `time_scale` so the same script fits 4-validator and
    150-validator block cadences)."""

    at_s: float
    # partition | oneway | heal | gray | ungray | crash | restart |
    # churn_join | churn_leave | churn_power | churn_rogue_join
    action: str
    groups: tuple = ()  # partition: tuple of groups (indices or "rest")
    src: tuple = ()  # oneway: sender group (indices or "rest")
    dst: tuple = ()  # oneway: receiver group
    node: int = 0  # gray/ungray/crash/restart/churn target (index mod n;
    # for churn_join/churn_rogue_join it seeds the PHANTOM key instead)
    delay_ms: float = 0.0  # gray: fixed per-message delay
    power: int = 1  # churn_join/churn_power: requested voting power


@dataclass(frozen=True)
class Scenario:
    name: str
    summary: str
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    events: tuple[Event, ...] = ()
    fs: ChaosFSConfig | None = None  # per-node storage faults (crash model)
    # -- the Byzantine axis (consensus/byzantine.py), composable with
    # every fault class above: (validator index, plan) pairs — indices
    # resolve mod n_vals at run time, like event node references
    byz: tuple[tuple[int, ByzConfig], ...] = ()
    # plan applied to the LAST f = ⌊(n_vals−1)⁄3⌋ validators — the
    # protocol's full fault budget at any committee size (keeps the
    # early proposer slots honest so runs make progress from height 1)
    byz_f_max: ByzConfig | None = None
    # False for strategies whose detection is probabilistic by design
    # (split-camp equivocation on a small fast net: the conflicting
    # pair must cross camps via relay gossip before the height moves
    # on). Safety and evidence PROMPTNESS always bind; only complete
    # escape stops being an audit failure.
    audit_require_evidence: bool = True
    # storm-sized timeouts at EVERY committee size (committee_config),
    # not just n>16: f-max traitors + lossy links split round-0 locks,
    # and re-assembling the POL polka across the committee takes the
    # gossip-heal latency (stall-refresh cadence ≥1s) — fast_config's
    # sub-second rounds then churn faster than the polka can converge.
    # Timers only bound the unhappy path, so clean heights stay fast.
    storm_timeouts: bool = False


# -- the named taxonomy ----------------------------------------------------

SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario(
            "baseline",
            "no faults — the control run every other scenario is read against",
        ),
        Scenario(
            "lossy_links",
            "drops + exponential delay + duplication + reordering on every link",
            chaos=ChaosConfig(
                drop_rate=0.05, delay_ms=5.0, duplicate_rate=0.02,
                reorder_rate=0.02,
            ),
        ),
        Scenario(
            "corrupt_wire",
            "seeded byte corruption on the live gossip byte-stream "
            "(malformed frames cost the sender its connection; redial heals)",
            chaos=ChaosConfig(corrupt_rate=0.02, delay_ms=2.0),
        ),
        Scenario(
            "asym_partition",
            "half-open link: node 0 stops RECEIVING while its own votes "
            "still flow out; heals mid-run — recovery must ride the "
            "reactor's catch-up gossip",
            events=(
                Event(0.8, "oneway", src=("rest",), dst=(0,)),
                Event(2.4, "heal"),
            ),
        ),
        Scenario(
            "gray_failure",
            "one peer goes slow-but-alive (fixed delay tuned near the "
            "gossip cadence), then recovers",
            events=(
                Event(0.5, "gray", node=1, delay_ms=120.0),
                Event(2.5, "ungray", node=1),
            ),
        ),
        Scenario(
            "bandwidth_crunch",
            "per-link leaky-bucket shaping: block parts queue behind "
            "votes and backlog becomes delivery delay",
            chaos=ChaosConfig(bandwidth_rate=192.0 * 1024),
        ),
        Scenario(
            "clock_skew",
            "per-validator wall-clock skew + oscillator drift (timeouts "
            "fire early/late); the vote-time floor keeps output deterministic",
            chaos=ChaosConfig(clock_skew_ms=80.0, clock_drift=0.02),
        ),
        Scenario(
            "crash_fs",
            "chaos-fs crash mid-consensus: a node dies with a torn WAL "
            "tail, restarts on the same stores, repairs, and catches up "
            "through catch-up gossip",
            fs=ChaosFSConfig(torn_write_rate=1.0),
            events=(
                Event(1.2, "crash", node=-1),
                Event(2.0, "restart", node=-1),
            ),
        ),
        Scenario(
            "validator_churn",
            "live validator-set churn under lossy links: a phantom key "
            "joins via a val-tx, a sitting validator's power shifts, "
            "the last validator leaves (power 0), and a rogue bls12381 "
            "join WITHOUT proof of possession bounces off every mempool "
            "(the PR 9 PoP-on-update defense, exercised live)",
            chaos=ChaosConfig(drop_rate=0.02, delay_ms=3.0),
            events=(
                Event(0.6, "churn_join", node=100, power=1),
                Event(1.2, "churn_rogue_join", node=101, power=1),
                Event(1.8, "churn_power", node=1, power=3),
                Event(2.4, "churn_leave", node=-1),
            ),
        ),
        # -- the Byzantine axis: validators that LIE, composed with the
        # network/storage/clock fault classes above. Every run is
        # audited (consensus/byzantine.audit_net): honest commit + app
        # hash agreement, DuplicateVoteEvidence accountability within K
        # heights for every equivocator, peer cost for invalid-sig
        # gossip.
        Scenario(
            "byz_equivocation",
            "one traitor double-signs prevotes+precommits at every "
            "height (both votes to every peer): every honest node must "
            "detect, pool, gossip and COMMIT the DuplicateVoteEvidence",
            byz=((-1, ByzConfig(("equivocate",))),),
        ),
        Scenario(
            "byz_equivocation_partition",
            "split-mode equivocation under an asymmetric partition: "
            "conflicting votes go to disjoint camps, so detection must "
            "happen where honest relay gossip intersects — while node 0 "
            "is half-deaf",
            byz=((-1, ByzConfig(("equivocate",), equiv_split=True)),),
            events=(
                Event(0.8, "oneway", src=("rest",), dst=(0,)),
                Event(2.4, "heal"),
            ),
            audit_require_evidence=False,
        ),
        Scenario(
            "byz_amnesia_skew",
            "a traitor that forgets its lock (amnesia prevotes) on a "
            "committee with skewed/drifting clocks — the lock rules "
            "must hold safety on honest nodes alone",
            chaos=ChaosConfig(clock_skew_ms=80.0, clock_drift=0.02),
            byz=((-1, ByzConfig(("amnesia", "equivocate"))),),
        ),
        Scenario(
            "byz_withhold",
            "selective vote/part withholding per peer over lossy links: "
            "starved peers must heal through honest relay gossip and "
            "catch-up (paced — the donors' loop share stays bounded)",
            chaos=ChaosConfig(drop_rate=0.02, delay_ms=3.0),
            byz=(
                (
                    -1,
                    ByzConfig(
                        ("withhold_votes", "withhold_parts"),
                        withhold_frac=0.5,
                    ),
                ),
            ),
        ),
        Scenario(
            "byz_invalid_sig",
            "invalid-signature gossip: stage-1 ingest disproves the "
            "forgery and the traitor pays (PeerError → score/ban, "
            "audited on every honest peer manager)",
            byz=((-1, ByzConfig(("invalid_sig", "equivocate"))),),
        ),
        Scenario(
            "byz_flood_lies",
            "future-round vote floods plus lying NewRoundStep/HasVote "
            "frames: the unwanted-round guard sheds the flood without "
            "verify spend; VoteSetBits reconciliation + stall-refresh "
            "heal the poisoned gossip marks; catch-up pacing bounds the "
            "lag-bait service",
            byz=((-1, ByzConfig(("future_round_flood", "lying_frames"))),),
        ),
        Scenario(
            "byz_full_taxonomy",
            "f = ⌊(n−1)/3⌋ traitors equivocating, forgetting locks, "
            "withholding and forging signatures under network chaos — "
            "the protocol's entire fault budget, demonstrated live. "
            "(lying_frames/future_round_flood stay out of the f-max "
            "mix by design: a traitor lying about its own height makes "
            "its voting power vanish from every later round, and at "
            "f-max that parks the committee at EXACTLY the honest "
            "quorum — Tendermint is still safe but round alignment "
            "under chaos stops being wall-clock-feasible; those "
            "strategies run at f=1 in byz_flood_lies instead)",
            chaos=ChaosConfig(
                drop_rate=0.02, delay_ms=3.0, duplicate_rate=0.01,
                reorder_rate=0.01, corrupt_rate=0.008,
                clock_skew_ms=60.0, clock_drift=0.01,
            ),
            byz_f_max=ByzConfig(
                (
                    "equivocate",
                    "amnesia",
                    "withhold_votes",
                    "invalid_sig",
                )
            ),
            events=(
                Event(0.8, "oneway", src=("rest",), dst=(0,)),
                Event(2.4, "heal"),
            ),
            storm_timeouts=True,
        ),
        Scenario(
            "full_taxonomy",
            "everything at once: lossy + corrupt + shaped links, clock "
            "skew/drift, a gray peer, an asymmetric partition cycle, and "
            "a chaos-fs crash/restart mid-consensus",
            chaos=ChaosConfig(
                drop_rate=0.02, delay_ms=3.0, duplicate_rate=0.01,
                reorder_rate=0.01, corrupt_rate=0.008,
                bandwidth_rate=512.0 * 1024, clock_skew_ms=60.0,
                clock_drift=0.01,
            ),
            fs=ChaosFSConfig(torn_write_rate=1.0),
            events=(
                Event(0.5, "gray", node=1, delay_ms=100.0),
                Event(0.8, "oneway", src=("rest",), dst=(0,)),
                Event(1.2, "crash", node=-1),
                Event(2.0, "restart", node=-1),
                Event(2.4, "heal"),
                Event(2.6, "ungray", node=1),
            ),
        ),
    )
}


@dataclass
class ScenarioResult:
    scenario: str
    seed: int
    n_vals: int
    n_full: int
    target_height: int
    ok: bool
    wedged: bool
    events_applied: list[str]
    heights: list[int]
    elapsed_s: float
    blocks_per_s: float
    recover_s: float | None  # last fault event -> all nodes past target
    faults: dict
    fs_faults: dict
    error: str = ""
    dump_path: str = ""
    # cross-node safety auditor verdict (byzantine.audit_net) — present
    # for EVERY scenario (agreement checks are byz-independent); the
    # evidence/penalty checks only bind when traitors were installed
    audit: dict | None = None
    byz_indices: list = field(default_factory=list)
    byz_actions: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "n_vals": self.n_vals,
            "n_full": self.n_full,
            "target_height": self.target_height,
            "outcome": "ok" if self.ok else ("wedged" if self.wedged else "error"),
            "events_applied": self.events_applied,
            "heights": self.heights,
            "elapsed_s": round(self.elapsed_s, 3),
            "blocks_per_s": round(self.blocks_per_s, 4),
            "recover_s": (
                round(self.recover_s, 3) if self.recover_s is not None else None
            ),
            "faults": self.faults,
            "fs_faults": self.fs_faults,
            "error": self.error,
            "dump_path": self.dump_path,
            "audit": self.audit,
            "byz_indices": self.byz_indices,
            "byz_actions": self.byz_actions,
        }


def _resolve_group(group, n: int, named: set[int]) -> set[int]:
    out: set[int] = set()
    for g in group:
        if g == "rest":
            out |= set(range(n)) - named
        else:
            out.add(g % n)
    return out


def _event_indices(ev: Event, n: int) -> set[int]:
    named: set[int] = set()
    for group in (*ev.groups, ev.src, ev.dst):
        for g in group:
            if g != "rest":
                named.add(g % n)
    return named


def churn_join_key(seed: int, index: int):
    """The deterministic phantom validator key a `churn_join` event
    introduces — a pure function of (run seed, event node index) so
    every process in a multi-worker run derives the same key."""
    import hashlib

    from ..crypto import ed25519 as _ed

    return _ed.Ed25519PrivKey(
        hashlib.sha256(f"tmtpu:churn:{seed}:{index}".encode()).digest()
    )


async def _inject_tx(net: RouterNet, tx: bytes, *, expect_reject: bool) -> None:
    """Broadcast one tx into every live node's mempool (RouterNet wires
    no mempool gossip channel, so whichever validator proposes next must
    already hold the tx). `expect_reject` inverts the contract: the tx
    MUST bounce off CheckTx on every node — the live PoP-on-update
    defense — and acceptance anywhere is the failure."""
    from ..mempool.pool import TxInCacheError, TxRejectedError

    accepted = rejected = 0
    for node in net.nodes:
        inner = node.inner
        if inner is None or inner.mempool is None:
            continue  # crashed mid-scenario; survivors carry the churn
        try:
            await inner.mempool.check_tx(tx)
            accepted += 1
        except TxRejectedError:
            rejected += 1
        except TxInCacheError:
            accepted += 1
    if expect_reject:
        if accepted:
            raise AssertionError(
                f"rogue churn tx accepted by {accepted} mempools"
            )
    elif not accepted:
        raise AssertionError(f"churn tx rejected by all {rejected} mempools")


def _churn_tx(ev: Event, net: RouterNet, seed: int) -> tuple[bytes, bool]:
    """Build the validator-tx for a churn event; returns (tx,
    expect_reject)."""
    from ..abci.kvstore import VALIDATOR_TX_PREFIX

    if ev.action == "churn_join":
        pub = churn_join_key(seed, ev.node).pub_key()
        body = f"{pub.bytes().hex()}!{ev.power}"
        return VALIDATOR_TX_PREFIX + body.encode(), False
    if ev.action == "churn_rogue_join":
        # a bls12381 join WITHOUT proof of possession: the rogue-key
        # shape PR 9 closed at genesis, now arriving through the only
        # post-genesis entry point — every mempool must bounce it
        from ..crypto import bls
        import hashlib

        priv = bls.BLSPrivKey(
            hashlib.sha256(f"tmtpu:rogue:{seed}:{ev.node}".encode()).digest()
        )
        body = f"bls12381:{priv.pub_key().bytes().hex()}!{ev.power}"
        return VALIDATOR_TX_PREFIX + body.encode(), True
    # churn_leave / churn_power target a SITTING validator by index
    pub = net.keys[ev.node % net.n].pub_key()
    power = 0 if ev.action == "churn_leave" else ev.power
    if pub.TYPE == "ed25519":
        body = f"{pub.bytes().hex()}!{power}"
    else:
        body = f"{pub.TYPE}:{pub.bytes().hex()}!{power}"
    return VALIDATOR_TX_PREFIX + body.encode(), False


async def _apply_event(
    ev: Event, net: RouterNet, chaos: ChaosNetwork, seed: int = 0
) -> None:
    n = net.n
    named = _event_indices(ev, n)
    ids = lambda idxs: {net.nodes[i].node_id for i in idxs}  # noqa: E731
    if ev.action.startswith("churn_"):
        tx, expect_reject = _churn_tx(ev, net, seed)
        await _inject_tx(net, tx, expect_reject=expect_reject)
    elif ev.action == "partition":
        chaos.partition(
            *(ids(_resolve_group(g, n, named)) for g in ev.groups)
        )
    elif ev.action == "oneway":
        chaos.partition_oneway(
            ids(_resolve_group(ev.src, n, named)),
            ids(_resolve_group(ev.dst, n, named)),
        )
    elif ev.action == "heal":
        chaos.heal()
    elif ev.action == "gray":
        chaos.set_gray(net.nodes[ev.node % n].node_id, ev.delay_ms)
    elif ev.action == "ungray":
        chaos.set_peer_config(net.nodes[ev.node % n].node_id, chaos.config)
    elif ev.action == "crash":
        await net.crash(ev.node % n)
    elif ev.action == "restart":
        await net.restart(ev.node % n)
    else:
        raise ValueError(f"unknown scenario event action {ev.action!r}")


def _round_states(net: RouterNet) -> list[dict]:
    out = []
    for node in net.nodes:
        cs = node.cs
        if cs is None:
            out.append({"index": node.index, "state": "down"})
            continue
        out.append(
            {
                "index": node.index,
                "height": cs.rs.height,
                "round": cs.rs.round,
                "step": int(cs.rs.step),
                "committed": node.block_store.height(),
                "running": bool(cs.is_running),
            }
        )
    return out


def _snapshot_wedge(
    scenario: Scenario,
    net: RouterNet,
    chaos: ChaosNetwork | None,
    detail: dict,
) -> dict:
    """Build the wedge post-mortem payload ON THE LOOP: the routers are
    still live here (run_scenario stops them after the dump so round
    state is readable), so fault counters and round states must be
    copied in one loop step — iterating them from a worker thread races
    their writers (dict-changed-size mid-dump). The flight ring is
    dumped here too (its own small file; the recorder's state is
    loop-mutated)."""
    from ..libs import trace

    flight = trace.auto_dump(f"chaos-wedge-{scenario.name}")
    return {
        "scenario": scenario.name,
        "summary": scenario.summary,
        "faults": dict(chaos.faults) if chaos is not None else {},
        "fs_faults": {
            i: dict(fs.faults)
            for i, fs in net._fs.items()
            if fs is not None
        },
        "nodes": _round_states(net),
        "flight_dump": flight or "",
        **detail,
    }


def _write_wedge(dump_dir: str, name: str, payload: dict) -> str:
    """Write the (already-snapshotted) payload — the blocking half,
    pushed off the loop via asyncio.to_thread so a slow disk cannot
    stall the routers the dump describes (acceptance: any wedge is
    diagnosable from disk)."""
    os.makedirs(dump_dir, exist_ok=True)
    path = os.path.join(dump_dir, f"chaos-wedge-{name}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, default=str)
    return path


async def run_scenario(
    scenario: Scenario | str,
    *,
    n_vals: int = 4,
    n_full: int = 0,
    target_height: int = 3,
    seed: int = 1,
    config=None,
    degree: int = 8,
    timeout_s: float = 60.0,
    stall_s: float = 20.0,
    time_scale: float = 1.0,
    gossip_sleep: float | None = None,
    use_hub: bool = True,
    dump_dir: str | None = None,
    base_clock=None,
    audit_k: int = 3,  # heights an equivocator's evidence may take to commit
) -> ScenarioResult:
    """One seeded scenario run. Returns a structured result — it does
    NOT raise on a wedge (`result.ok` / `result.wedged`); the hard
    `timeout_s` bound means a caller can sweep the whole taxonomy and
    still terminate."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    if dump_dir is None:
        dump_dir = os.environ.get("TMTPU_CHAOS_DUMP_DIR") or tempfile.mkdtemp(
            prefix="chaos-dumps-"
        )
    chaos_cfg = replace(scenario.chaos, seed=seed)
    # events (partitions/gray) need the controller even when every steady
    # rate is zero
    chaos = (
        ChaosNetwork(chaos_cfg)
        if (chaos_cfg.enabled() or scenario.events)
        else None
    )
    fs_factory = None
    if scenario.fs is not None:
        fs_cfg = scenario.fs

        def fs_factory(i: int, _cfg=fs_cfg, _seed=seed):
            # one ChaosFS per node: a crash must only tear ITS WAL
            return ChaosFS(replace(_cfg, seed=_seed * 1009 + i))

    if base_clock is None:
        from ..libs.clock import ManualClock

        # frozen behind genesis: the vote-time floor pins every stamp
        base_clock = ManualClock(GENESIS_TIME_NS - 500 * MS)
    if config is None:
        # small nets: fast multi-round timeouts; committees (and
        # scenarios that declare storm_timeouts — f-max byz runs):
        # storm-sized timers (see routernet.committee_config — timers
        # only bound the unhappy path, quorum drives the happy one)
        config = (
            fast_config()
            if n_vals <= 16 and not scenario.storm_timeouts
            else committee_config(n_vals)
        )
    # -- the Byzantine plan: explicit (index, config) pairs plus the
    # f-max budget; per-traitor seeds derive from the RUN seed so two
    # same-seed runs produce bit-identical byzantine behavior
    byz_registry: list = []
    byz_plan: dict[int, ByzConfig] = {}
    for idx, bcfg in scenario.byz:
        i = idx % n_vals
        byz_plan[i] = replace(bcfg, seed=seed * 1013 + i)
    if scenario.byz_f_max is not None:
        f = max(0, (n_vals - 1) // 3)
        for i in range(n_vals - f, n_vals):
            byz_plan.setdefault(
                i, replace(scenario.byz_f_max, seed=seed * 1013 + i)
            )
    net = RouterNet(
        n_vals,
        n_full=n_full,
        config=config,
        chaos=chaos,
        base_clock=base_clock,
        degree=degree,
        topo_seed=seed,
        gossip_sleep=gossip_sleep,
        use_hub=use_hub,
        fs_factory=fs_factory,
        prepare_hook=(
            byz_prepare_hook(byz_plan, byz_registry) if byz_plan else None
        ),
    )
    loop = asyncio.get_running_loop()
    heights: list[int] = []
    faults: dict = {}
    fs_faults: dict = {}
    ok = wedged = False
    error = dump_path = ""
    recover_s: float | None = None
    t0 = loop.time()
    t_done = t0
    try:
        await net.start()
    except Exception as e:  # noqa: BLE001 — structured outcome contract
        # best-effort teardown of the partially-started net: the hub
        # refcount and any already-running routers/reactors must not
        # leak into the caller's loop (run_sweep runs more scenarios)
        await net.stop()
        return ScenarioResult(
            scenario=scenario.name, seed=seed, n_vals=n_vals, n_full=n_full,
            target_height=target_height, ok=False, wedged=False,
            events_applied=[], heights=net.heights(), elapsed_s=0.0,
            blocks_per_s=0.0, recover_s=None,
            faults=dict(chaos.faults) if chaos is not None else {},
            fs_faults={}, error=f"start failed: {e!r}",
        )
    event_err: list[str] = []
    events_applied: list[str] = []
    last_event_t = [t0]
    # liveness is a guarantee for CORRECT nodes: a traitor can always
    # wedge itself (e.g. lying_frames under-reports its own height and
    # starves its own catch-up), so the all-nodes-progress gate and the
    # throughput figure read the minimum over HONEST nodes only
    honest_idx = [i for i in range(net.n) if i not in byz_plan] or list(
        range(net.n)
    )

    def honest_min() -> int:
        return min(net.heights()[i] for i in honest_idx)

    async def drive_events() -> None:
        for ev in sorted(scenario.events, key=lambda e: e.at_s):
            await asyncio.sleep(
                max(0.0, ev.at_s * time_scale - (loop.time() - t0))
            )
            try:
                await _apply_event(ev, net, chaos, seed)
                events_applied.append(ev.action)
            except Exception as e:  # noqa: BLE001 — recorded, run continues
                event_err.append(f"{ev.action}@{ev.at_s}: {e!r}")
            last_event_t[0] = loop.time()

    events_task = loop.create_task(drive_events(), name="scenario.events")
    try:
        # -- liveness watchdog: all nodes must progress ----------------
        # Completion is gated on the WHOLE event script having fired
        # plus at least one height of post-event progress: a fast
        # committee must not "pass" a crash scenario by reaching the
        # target before the crash happens.
        deadline = t0 + timeout_s
        last_min = -1
        last_progress = loop.time()
        post_event_target: int | None = (
            target_height if not scenario.events else None
        )
        while True:
            await asyncio.sleep(0.2)
            mh = honest_min()
            now = loop.time()
            if mh > last_min:
                last_min = mh
                last_progress = now
            if post_event_target is None and events_task.done():
                post_event_target = max(target_height, mh + 1)
            if post_event_target is not None and mh >= post_event_target:
                ok = True
                t_done = now
                break
            if now > deadline or (now - last_progress) > stall_s * time_scale:
                wedged = True
                t_done = now
                break
    except Exception as e:  # noqa: BLE001 — structured outcome, not a raise
        error = repr(e)
        t_done = loop.time()
    finally:
        events_task.cancel()
        # reap without absorbing our own cancellation
        await asyncio.gather(events_task, return_exceptions=True)
        heights = net.heights()
        faults = dict(chaos.faults) if chaos is not None else {}
        fs_faults = {
            str(i): dict(fs.faults)
            for i, fs in net._fs.items()
            if fs is not None
        }
        byz_actions = [b.log_summary() for b in byz_registry]
        # the cross-node safety auditor runs on EVERY scenario outcome —
        # a wedged net must still never have double-committed
        try:
            audit = audit_net(
                net,
                byz_registry,
                k_heights=audit_k,
                require_evidence=scenario.audit_require_evidence,
            ).as_dict()
        except Exception as e:  # noqa: BLE001 — observation must not mask
            audit = {"ok": False, "notes": [f"audit failed: {e!r}"]}
        if wedged or error:
            # snapshot on the loop (atomic view of live state), write
            # off the loop (a slow disk can't stall the routers the
            # dump describes)
            payload = _snapshot_wedge(
                scenario,
                net,
                chaos,
                {
                    "seed": seed,
                    "n_vals": n_vals,
                    "target_height": target_height,
                    "elapsed_s": round(t_done - t0, 3),
                    "event_errors": event_err,
                    "error": error,
                    "byz": byz_actions,
                    "audit": audit,
                },
            )
            dump_path = await asyncio.to_thread(
                _write_wedge, dump_dir, scenario.name, payload
            )
        await net.stop()
    if event_err and not error:
        error = "; ".join(event_err)
    elapsed = max(t_done - t0, 1e-9)
    if ok and scenario.events:
        recover_s = max(0.0, t_done - last_event_t[0])
    # throughput from what was actually COMMITTED net-wide (the min
    # HONEST height), not the requested target: an event-gated run can
    # outrun target_height, and chaos_soak compares these numbers
    # across rounds; a self-wedged traitor does not zero the figure
    committed = min((heights[i] for i in honest_idx), default=0) if heights else 0
    return ScenarioResult(
        scenario=scenario.name,
        seed=seed,
        n_vals=n_vals,
        n_full=n_full,
        target_height=target_height,
        ok=ok,
        wedged=wedged,
        events_applied=events_applied,
        heights=heights,
        elapsed_s=elapsed,
        blocks_per_s=(committed / elapsed) if ok else 0.0,
        recover_s=recover_s,
        faults=faults,
        fs_faults=fs_faults,
        error=error,
        dump_path=dump_path,
        audit=audit,
        byz_indices=sorted(byz_plan),
        byz_actions=byz_actions,
    )


async def run_light_attack(
    *,
    n_vals: int = 3,
    seed: int = 1,
    trust_height: int = 1,
    attack_offset: int = 2,
    k_heights: int = 3,
    timeout_s: float = 90.0,
    commit_window_s: float = 2.5,
    chaos_cfg: ChaosConfig | None = None,
    app_factory=None,
    use_hub: bool = True,
    degree: int = 8,
    config=None,
) -> dict:
    """The live lunatic light-client attack over RouterNet — the
    LightFleet Byzantine axis (light/byzantine.py), end to end:

      honest committee commits over real routers (chaos-wrapped when
      `chaos_cfg` is set) → a LightD (light/fleet.py) syncs through a
      traitor primary (`LunaticProvider`: a forged header signed out of
      band by a seeded >1/3-power subset reusing their REAL keys) with
      honest witnesses → the witness cross-check detects the divergence
      → `LightClientAttackEvidence` forms and lands in every honest
      pool → evidence-channel gossip → on-chain commitment →
      BeginBlock misbehavior — audited by `audit_net` (agreement + LCA
      accountability within `k_heights` of the forged height).

    Determinism construction (the bit-identity contract at n_vals=3):
    frozen clock + 3 equal-power validators pin every commit signer
    set and timestamp; the colluders behave HONESTLY in consensus (the
    forgery is an offline key reuse), so the chain itself is the
    deterministic baseline; a `commit_window_s` timeout_commit opens a
    pause after the attack height inside which detection + direct
    evidence reporting to every witness pool completes, pinning the
    evidence's commit height. Two same-seed runs then produce
    bit-identical block AND evidence bytes.

    Attack heights sit `attack_offset >= 2` above the trust anchor:
    adjacent hops pin the exact next validator set by hash and reject
    the forgery before the witness cross-check — a negative test, not
    an attack.

    Returns a structured outcome dict (never raises on wedge/timeout —
    the chaos_soak contract)."""
    from ..libs.clock import ManualClock
    from ..light.byzantine import LunaticConfig, LunaticProvider
    from ..light.client import Divergence, TrustOptions
    from ..light.fleet import LightD
    from ..light.provider import BlockStoreProvider
    from ..state.state import state_from_genesis

    attack_height = trust_height + attack_offset
    if attack_offset < 2:
        raise ValueError("lunatic attack heights must be non-adjacent")
    if config is None:
        base = fast_config() if n_vals <= 16 else committee_config(n_vals)
        config = replace(
            base,
            timeout_commit_ns=int(commit_window_s * 1e9),
            skip_timeout_commit=False,
        )
    chaos = (
        ChaosNetwork(replace(chaos_cfg, seed=seed))
        if chaos_cfg is not None and chaos_cfg.enabled()
        else None
    )
    net = RouterNet(
        n_vals,
        config=config,
        chaos=chaos,
        base_clock=ManualClock(GENESIS_TIME_NS - 500 * MS),
        degree=degree,
        topo_seed=seed,
        use_hub=use_hub,
        app_factory=app_factory,
    )
    chain_id = net.genesis.chain_id
    out: dict = {
        "outcome": "error",
        "n_vals": n_vals,
        "seed": seed,
        "attack_height": attack_height,
        "divergence_detected": False,
        "served_forged": 0,
        "traitors": [],
        "lca_committed_at": None,
        "time_to_lca_commit_heights": None,
        "audit": None,
        "blocks_hex": [],
        "lca_evidence_hex": "",
        "heights": [],
        "elapsed_s": 0.0,
        "error": "",
    }
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    lightd = None
    try:
        await asyncio.wait_for(net.start(), timeout_s)
        await asyncio.wait_for(
            net.wait_for_height(attack_height, timeout_s), timeout_s
        )
        genesis_vals = state_from_genesis(net.genesis).validators
        keys_by_addr = {k.pub_key().address(): k for k in net.keys}
        providers = [
            BlockStoreProvider(
                chain_id,
                n.block_store,
                n.inner.state_store,
                evidence_pool=n.inner.evidence_pool,
            )
            for n in net.nodes
        ]
        lunatic = LunaticProvider(
            providers[0],
            LunaticConfig(
                (attack_height,), seed=seed, n_traitors=n_vals // 3 + 1
            ),
            genesis_vals,
            keys_by_addr,
        )
        out["traitors"] = [a.hex() for a in lunatic.traitor_addresses()]
        anchor_meta = net.nodes[0].block_store.load_block_meta(trust_height)
        trust = TrustOptions(
            period_ns=10 * 365 * 24 * 3600 * 10**9,
            height=trust_height,
            hash=anchor_meta.header.hash(),
        )
        lightd = LightD(chain_id, trust, lunatic, witnesses=providers)
        await lightd.start()
        tip_time = net.nodes[0].block_store.load_block_meta(
            attack_height
        ).header.time_ns
        try:
            await asyncio.wait_for(
                lightd.sync(attack_height, now_ns=tip_time + 10**9), 60.0
            )
        except Divergence:
            out["divergence_detected"] = True
        out["served_forged"] = len(lunatic.served_forged)
        out["lightd_stats"] = dict(lightd.stats)
        # wait (bounded by K heights) for the evidence to reach a block
        expect = lunatic.traitor_addresses()
        target = attack_height + 1
        for _ in range(k_heights + 1):
            await asyncio.wait_for(
                net.wait_for_height(target, timeout_s), timeout_s
            )
            lca = committed_light_client_attack_evidence(net.nodes[0])
            if all(a in lca for a in expect):
                commit_h, ev = lca[expect[0]]
                out["lca_committed_at"] = commit_h
                out["time_to_lca_commit_heights"] = (
                    commit_h - ev.conflicting_height
                )
                out["lca_evidence_hex"] = ev.encode().hex()
                break
            target += 1
        audit = audit_net(
            net, [], k_heights=k_heights, expect_lca=expect
        )
        out["audit"] = audit.as_dict()
        out["blocks_hex"] = [
            b.hex() for b in net.block_fingerprints(target, node=0)
        ]
        out["outcome"] = (
            "ok"
            if out["divergence_detected"]
            and out["lca_committed_at"] is not None
            and audit.ok
            else "failed"
        )
    except Exception as e:  # noqa: BLE001 — structured outcome contract
        out["error"] = repr(e)
    finally:
        if lightd is not None:
            await lightd.stop()
        out["heights"] = net.heights()
        out["elapsed_s"] = round(loop.time() - t0, 3)
        await net.stop()
    return out


async def run_boot_wave(
    *,
    n_vals: int = 4,
    n_joiners: int = 2,
    seed: int = 1,
    snapshot_height: int = 12,
    timeout_s: float = 120.0,
    join_timeout_s: float = 90.0,
    chaos_cfg: ChaosConfig | None = None,
    donor_crash: bool = False,
    poison_donors: tuple[int, ...] = (),
    use_hub: bool = True,
    degree: int = 8,
    config=None,
    bootd_config=None,
    donors_per_joiner: int = 3,
    snapshot_interval: int = 10,
    commit_window_s: float | None = None,
    gossip_sleep: float | None = None,
) -> dict:
    """The BootFleet mass-onboarding scenario: a wave of `n_joiners`
    cold nodes statesyncs into a live `n_vals`-validator RouterNet
    committee — chunks served by the donors' BootDs, backfill commit
    signatures batched onto the VerifyHub backfill lane — while the
    committee keeps committing (optionally under link chaos).

    Fault variants, composable:

      * `donor_crash`: one donor is killed mid-wave (real `net.crash`);
        joiners must re-fetch from survivors (chunk-timeout → breaker →
        rotation), and the committee must keep quorum (n_vals >= 4).
      * `poison_donors`: those validator indices serve poisoned chunk
        bytes (`statesync/byzantine.PoisonedSnapshotApp`, seeded): the
        restore's whole-blob hash check must reject the state, cost the
        serving peer a `PeerError(ban=True)`, and move on to the next
        candidate — a joiner may land on an older snapshot but NEVER on
        the poisoned state.

    Success: every joiner syncs within `join_timeout_s` AND every
    header it holds matches the committee's chain (the honest app-hash
    chain), and `audit_net` passes over the committee. Returns a
    structured outcome dict; never raises (the chaos_soak contract)."""
    from ..libs.clock import ManualClock
    from ..statesync.byzantine import PoisonedSnapshotApp
    from ..statesync.reactor import SyncConfig

    if config is None:
        if n_vals <= 16:
            config = fast_config()
        else:
            # committee scale: a wide commit window is the catch-up
            # lever — every height gives laggards a quiet gossip window
            # (run_light_attack's construction; 200 ms churns at 150)
            config = replace(
                committee_config(n_vals),
                timeout_commit_ns=int((commit_window_s or 30.0) * 1e9),
                skip_timeout_commit=False,
            )
    chaos = (
        ChaosNetwork(replace(chaos_cfg, seed=seed))
        if chaos_cfg is not None and chaos_cfg.enabled()
        else None
    )
    poison_idx = {p % n_vals for p in poison_donors}

    def _app(i):
        # `snapshot_height` must be a cadence height: committee-scale
        # soaks shrink the interval so the wave starts heights earlier
        if i in poison_idx:
            return PoisonedSnapshotApp(
                seed=seed, snapshot_interval=snapshot_interval
            )
        if snapshot_interval != 10:
            from ..abci.kvstore import KVStoreApp

            return KVStoreApp(snapshot_interval=snapshot_interval)
        return None

    app_factory = _app if (poison_idx or snapshot_interval != 10) else None
    net = RouterNet(
        n_vals,
        config=config,
        chaos=chaos,
        base_clock=ManualClock(GENESIS_TIME_NS - 500 * MS),
        degree=degree,
        topo_seed=seed,
        use_hub=use_hub,
        app_factory=app_factory,
        statesync=True,
        bootd_config=bootd_config,
        **({"gossip_sleep": gossip_sleep} if gossip_sleep is not None else {}),
    )
    out: dict = {
        "outcome": "error",
        "n_vals": n_vals,
        "n_joiners": n_joiners,
        "seed": seed,
        "donor_crash": donor_crash,
        "poison_donors": sorted(poison_idx),
        "joined": 0,
        "join_errors": [],
        "time_to_synced_s": [],
        "joiner_heights": [],
        "honest_chain_ok": None,
        "poisoned_rejects": 0,
        "busy_sheds": 0,
        "chunks_served": 0,
        "cache_hits": 0,
        "backfill_sigs": 0,
        "backfill_agg_heights": 0,
        "audit": None,
        "heights": [],
        "elapsed_s": 0.0,
        "error": "",
    }
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    try:
        await asyncio.wait_for(net.start(), timeout_s)
        # snapshots at the interval height + the h+2 headers light
        # verification pins against must exist before the wave starts —
        # on the ANCHOR node and the DONORS only, not the whole
        # committee: at 150 validators the slowest laggard trails the
        # quorum by heights (it catches up inside commit windows)
        donor_idx = {0} | {
            (n_vals + j + k) % n_vals
            for j in range(n_joiners)
            for k in range(min(donors_per_joiner, n_vals))
        }
        await asyncio.wait_for(
            asyncio.gather(
                *(
                    net.nodes[i].cs.wait_for_height(
                        snapshot_height + 2, timeout_s
                    )
                    for i in sorted(donor_idx)
                )
            ),
            timeout_s,
        )
        anchor = net.nodes[0].block_store.load_block_meta(snapshot_height)
        cfg = SyncConfig(
            trust_height=snapshot_height,
            trust_hash=anchor.header.hash(),
            trust_period_ns=10 * 365 * 24 * 3600 * 10**9,
        )
        joiners = [
            net.make_joiner(donors=donors_per_joiner) for _ in range(n_joiners)
        ]
        for j in joiners:
            await j.prepare()

        async def join_one(j):
            jt0 = loop.time()
            await asyncio.wait_for(j.statesync_join(cfg), join_timeout_s)
            return loop.time() - jt0

        tasks = [asyncio.create_task(join_one(j)) for j in joiners]
        if donor_crash:
            # kill a donor while the wave is in flight: every joiner
            # dials donors starting at a distinct offset, so (joiner 0's
            # first donor) is in some joiner's rotation
            await asyncio.sleep(0.3)
            await net.crash(n_vals - 1)
        results = await asyncio.gather(*tasks, return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                out["join_errors"].append(repr(r))
            else:
                out["joined"] += 1
                out["time_to_synced_s"].append(round(r, 3))

        # honest-chain check: every header a joiner holds must be the
        # committee's block at that height (a poisoned restore that
        # slipped through would fork the app-hash chain here)
        ref = net.nodes[0].block_store
        honest = True
        for j in joiners:
            jh = j.block_store.height()
            out["joiner_heights"].append(jh)
            base = j.block_store.base()
            for h in range(max(1, base), jh + 1):
                meta = j.block_store.load_block_meta(h)
                ref_meta = ref.load_block_meta(h)
                if meta is None or ref_meta is None:
                    continue
                if meta.header.hash() != ref_meta.header.hash():
                    honest = False
        out["honest_chain_ok"] = honest

        for node in net.nodes + net.joiners:
            if node.ss_reactor is None:
                continue
            st = node.ss_reactor.bootd.stats
            out["poisoned_rejects"] += st["poisoned_rejects"]
            out["busy_sheds"] += st["sheds"]
            out["chunks_served"] += st["chunks_served"]
            out["cache_hits"] += st["cache_hits"]
            out["backfill_sigs"] += st["backfill_sigs"]
            out["backfill_agg_heights"] += st["backfill_agg_heights"]

        crashed = {n_vals - 1} if donor_crash else set()
        audit = audit_net(
            net,
            [],
            k_heights=3,
            require_evidence=False,
        )
        # a crashed donor legitimately stops committing; agreement over
        # what it DID commit still binds (audit_net only compares
        # heights both sides hold)
        out["audit"] = audit.as_dict()
        ok = out["joined"] == n_joiners and honest and audit.ok
        out["outcome"] = "ok" if ok else "failed"
        out["crashed"] = sorted(crashed)
    except Exception as e:  # noqa: BLE001 — structured outcome contract
        out["error"] = repr(e)
    finally:
        out["heights"] = net.heights()
        out["elapsed_s"] = round(loop.time() - t0, 3)
        await net.stop()
    return out


async def run_sweep(
    names: list[str] | None = None,
    **kwargs,
) -> list[ScenarioResult]:
    """Run a list of named scenarios sequentially (the full registry by
    default) with shared runner kwargs; always returns one structured
    result per scenario."""
    out = []
    for name in names or list(SCENARIOS):
        out.append(await run_scenario(name, **kwargs))
    return out
