"""RouterNet-XL — multi-process committees over real sockets, with
process-level fault injection and socket-layer chaos.

Every earlier soak shares one interpreter, so committee scale is
GIL-bound and chaos only ever exercised the in-memory transport.
RouterNet-XL splits the committee across K worker OS processes:

  * each worker hosts a SLICE of RouterNodes (`XLSliceNet`, a RouterNet
    that builds only its indices);
  * intra-slice links stay on the memory transport; cross-slice links
    run over real TCP or UDS with the full SecretConnection handshake
    (`p2p/tcp.py` finally carrying consensus load);
  * one `XLNet` supervisor owns spawn/join/teardown, drives the
    scenario event script over a small protoenc control protocol, and
    aggregates per-worker reports + wedge dumps into one structured
    outcome (the chaos_soak contract: bounded, structured, never
    hangs);
  * verification amortizes host-wide: workers point their VerifyHub at
    one verifyd sidecar via `TMTPU_VERIFYD_SOCK`; killing the daemon
    mid-soak degrades every worker to inline-local (hub breaker), never
    wedges.

Chaos ports to the socket layer unchanged: RouterShell chaos-wraps the
socket transport exactly like the memory transport, so drops, corrupt
frames, delay, bandwidth shaping and partitions apply at the TCP
frame boundary. Determinism across processes comes from
`ChaosConfig.link_seeded`: every (src, dst) link draws from its own
`random.Random(f"{seed}:{src}:{dst}")` stream, so a link's fault
schedule depends only on its own message sequence — identical no
matter which process hosts which end.

Process-level faults are first-class scenario events:

  * `kill_worker` (Event.node = worker index): SIGKILL the worker
    process group — torn WAL tails on every node in the slice;
  * `restart_worker`: respawn it. Durable per-node stores (SQLite) +
    consensus-WAL open-time repair + SecretConnection re-handshake +
    reactor catch-up gossip recover the whole slice;
  * `kill_verifyd`: SIGKILL the shared verification sidecar.

Determinism contract (ROADMAP split): frozen-clock in-process runs keep
pinning bytes; wall-clock multi-process runs pin app-hash chains (pure
functions of the committed tx sequence) plus the audit invariants —
zero conflicting honest commits, evidence accountability — aggregated
across workers.

Identities are pure functions of the node index (RouterShell key_seed
"routernet"), so every process derives every node's key, id and byz
plan from (scenario, seed) alone — the control protocol moves only
endpoints, events, heights and reports, never key material.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
from dataclasses import dataclass, replace

from ..crypto import ed25519
from ..libs import protoenc as pe
from ..libs.chaos import ChaosNetwork
from ..p2p.tcp import TCPTransport, UDSTransport
from ..p2p.types import NodeAddress, node_id_from_pubkey
from .byzantine import audit_net, byz_prepare_hook
from .harness import make_genesis  # noqa: F401  (re-export for callers)
from .routernet import RouterNet, committee_config, topology_edges
from .scenarios import (
    SCENARIOS,
    Event,
    Scenario,
    _churn_tx,
    _event_indices,
    _inject_tx,
    _resolve_group,
    _snapshot_wedge,
    _write_wedge,
)

# -- control protocol -------------------------------------------------------
#
# Supervisor <-> worker frames over the control UDS:
#   [4-byte BE length][protoenc message], field 1 = frame type.
# Bounds are enforced BEFORE allocation (the decode-bound discipline —
# a hostile/corrupt worker stream must not OOM the supervisor).

MAX_CTL_FRAME = 16 * 1024 * 1024
MAX_XL_NODES = 2048  # endpoints / heights / node-reports per frame
MAX_XL_CHAIN = 4096  # hash-chain entries per node report
MAX_XL_DIAG = 4 * 1024 * 1024  # diagnostic JSON blob per report

CTL_HELLO = 1
CTL_TOPOLOGY = 2
CTL_GO = 3
CTL_EVENT = 4
CTL_STATUS = 5
CTL_STOP = 6
CTL_REPORT = 7


@dataclass(frozen=True)
class CtlHello:
    """Worker -> supervisor: my slice's socket listen endpoints.
    Re-sent after an in-worker node restart re-binds a listener."""

    worker: int
    endpoints: tuple[tuple[int, str], ...] = ()  # (global index, endpoint)


@dataclass(frozen=True)
class CtlTopology:
    """Supervisor -> workers: the merged index -> endpoint map."""

    endpoints: tuple[tuple[int, str], ...] = ()


@dataclass(frozen=True)
class CtlGo:
    """Supervisor -> worker: start consensus. `preload` holds on
    respawn too: mempool contents died with the process, and if the
    worker was SIGKILLed before height 1 its txs exist nowhere else —
    an empty respawned mempool would let an empty height-1 block
    diverge from the in-process control. Re-injection is safe for the
    deterministic workload: already-committed txs are purged from the
    mempool as catch-up replays blocks, and the kv txs are idempotent
    assignments, so even a duplicate commit leaves the app-hash chain
    unchanged."""

    preload: bool = True


@dataclass(frozen=True)
class CtlEvent:
    """One scenario event, broadcast to every worker; group tuples ride
    as (bounded) JSON strings — they mix ints with the literal "rest"."""

    action: str
    node: int = 0
    delay_us: int = 0
    power: int = 1
    groups_json: str = ""
    src_json: str = ""
    dst_json: str = ""


@dataclass(frozen=True)
class CtlStatus:
    worker: int
    heights: tuple[tuple[int, int], ...] = ()  # (global index, height)


@dataclass(frozen=True)
class CtlStop:
    wedged: bool = False  # ask the worker for a wedge dump


@dataclass(frozen=True)
class NodeReport:
    index: int
    height: int
    app_hashes: tuple[bytes, ...] = ()  # heights 1..len
    block_hashes: tuple[bytes, ...] = ()
    evidence: int = 0  # evidence committed in this node's chain


@dataclass(frozen=True)
class CtlReport:
    worker: int
    nodes: tuple[NodeReport, ...] = ()
    diag_json: bytes = b""  # faults/audit/byz/wedge-path diagnostics
    error: str = ""


def _encode_endpoint(index: int, endpoint: str) -> bytes:
    return pe.varint_field(1, index) + pe.string_field(2, endpoint)


def _encode_node_report(nr: NodeReport) -> bytes:
    out = pe.varint_field(1, nr.index) + pe.varint_field(2, nr.height)
    # chain entries ride as embedded messages (always emitted, even for
    # an empty hash) — bytes_field's proto3 default-elision would shift
    # every later height down a slot and fabricate cross-node conflicts
    for h in nr.app_hashes:
        out += pe.message_field(3, pe.bytes_field(1, h))
    for h in nr.block_hashes:
        out += pe.message_field(4, pe.bytes_field(1, h))
    out += pe.varint_field(5, nr.evidence)
    return out


def _unwrap_hash(data: bytes) -> bytes:
    if not data:
        return b""
    r = pe.Reader(data)
    out = b""
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            out = r.read_bytes()
        else:
            r.skip(wt)
    return out


def encode_ctl(msg) -> bytes:
    """Encode one control frame body (the 4-byte length prefix is the
    stream framer's job — see write_ctl)."""
    if isinstance(msg, CtlHello):
        body = pe.varint_field(2, msg.worker)
        for i, ep in msg.endpoints:
            body += pe.message_field(3, _encode_endpoint(i, ep))
        return pe.varint_field(1, CTL_HELLO) + body
    if isinstance(msg, CtlTopology):
        body = b"".join(
            pe.message_field(3, _encode_endpoint(i, ep))
            for i, ep in msg.endpoints
        )
        return pe.varint_field(1, CTL_TOPOLOGY) + body
    if isinstance(msg, CtlGo):
        return pe.varint_field(1, CTL_GO) + pe.bool_field(2, msg.preload)
    if isinstance(msg, CtlEvent):
        body = pe.string_field(2, msg.action)
        body += pe.varint_field(3, msg.node & 0xFFFFFFFF)
        body += pe.varint_field(4, msg.delay_us)
        body += pe.varint_field(5, msg.power)
        if msg.groups_json:
            body += pe.string_field(6, msg.groups_json)
        if msg.src_json:
            body += pe.string_field(7, msg.src_json)
        if msg.dst_json:
            body += pe.string_field(8, msg.dst_json)
        return pe.varint_field(1, CTL_EVENT) + body
    if isinstance(msg, CtlStatus):
        body = pe.varint_field(2, msg.worker)
        for i, h in msg.heights:
            body += pe.message_field(
                3, pe.varint_field(1, i) + pe.varint_field(2, h)
            )
        return pe.varint_field(1, CTL_STATUS) + body
    if isinstance(msg, CtlStop):
        return pe.varint_field(1, CTL_STOP) + pe.bool_field(2, msg.wedged)
    if isinstance(msg, CtlReport):
        body = pe.varint_field(2, msg.worker)
        for nr in msg.nodes:
            body += pe.message_field(3, _encode_node_report(nr))
        if msg.diag_json:
            body += pe.bytes_field(4, msg.diag_json)
        if msg.error:
            body += pe.string_field(5, msg.error)
        return pe.varint_field(1, CTL_REPORT) + body
    raise TypeError(f"unknown control message {type(msg).__name__}")


def _decode_endpoint(data: bytes) -> tuple[int, str]:
    r = pe.Reader(data)
    idx, ep = 0, ""
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            idx = r.read_uvarint()
        elif f == 2:
            ep = r.read_string()
        else:
            r.skip(wt)
    return idx, ep


def _decode_node_report(data: bytes) -> NodeReport:
    r = pe.Reader(data)
    idx = height = evidence = 0
    app_hashes: list[bytes] = []
    block_hashes: list[bytes] = []
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            idx = r.read_uvarint()
        elif f == 2:
            height = r.read_uvarint()
        elif f == 3:
            app_hashes.append(_unwrap_hash(r.read_bytes()))
            pe.check_repeat(app_hashes, MAX_XL_CHAIN, "xl app hashes")
        elif f == 4:
            block_hashes.append(_unwrap_hash(r.read_bytes()))
            pe.check_repeat(block_hashes, MAX_XL_CHAIN, "xl block hashes")
        elif f == 5:
            evidence = r.read_uvarint()
        else:
            r.skip(wt)
    return NodeReport(
        idx, height, tuple(app_hashes), tuple(block_hashes), evidence
    )


def decode_ctl(data: bytes):
    """Decode one control frame body; every repeated field is bounded
    and the diagnostic blob capped (MAX_XL_DIAG) before it is kept."""
    r = pe.Reader(data)
    ftype = None
    worker = node = delay_us = 0
    power = 1
    preload = wedged = False
    action = groups_json = src_json = dst_json = error = ""
    endpoints: list[tuple[int, str]] = []
    heights: list[tuple[int, int]] = []
    nodes: list[NodeReport] = []
    diag = b""
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            ftype = r.read_uvarint()
        elif f == 2:
            if ftype == CTL_EVENT:
                action = r.read_string()
            elif ftype in (CTL_GO, CTL_STOP):
                flag = bool(r.read_uvarint())
                preload = wedged = flag
            else:
                worker = r.read_uvarint()
        elif f == 3:
            if ftype in (CTL_HELLO, CTL_TOPOLOGY):
                endpoints.append(_decode_endpoint(r.read_bytes()))
                pe.check_repeat(endpoints, MAX_XL_NODES, "xl endpoints")
            elif ftype == CTL_STATUS:
                er = pe.Reader(r.read_bytes())
                i = h = 0
                while not er.eof():
                    ef, ewt = er.read_tag()
                    if ef == 1:
                        i = er.read_uvarint()
                    elif ef == 2:
                        h = er.read_uvarint()
                    else:
                        er.skip(ewt)
                heights.append((i, h))
                pe.check_repeat(heights, MAX_XL_NODES, "xl heights")
            elif ftype == CTL_REPORT:
                nodes.append(_decode_node_report(r.read_bytes()))
                pe.check_repeat(nodes, MAX_XL_NODES, "xl node reports")
            else:
                node = r.read_uvarint()
        elif f == 4:
            if ftype == CTL_EVENT:
                delay_us = r.read_uvarint()
            else:
                diag = r.read_bytes()
                if len(diag) > MAX_XL_DIAG:
                    raise ValueError("xl diag blob exceeds bound")
        elif f == 5:
            if ftype == CTL_EVENT:
                power = r.read_uvarint()
            else:
                error = r.read_string()
        elif f == 6:
            groups_json = r.read_string()
        elif f == 7:
            src_json = r.read_string()
        elif f == 8:
            dst_json = r.read_string()
        else:
            r.skip(wt)
    if ftype == CTL_HELLO:
        return CtlHello(worker, tuple(endpoints))
    if ftype == CTL_TOPOLOGY:
        return CtlTopology(tuple(endpoints))
    if ftype == CTL_GO:
        return CtlGo(preload)
    if ftype == CTL_EVENT:
        # Event.node references are taken mod n, so the unsigned wrap in
        # encode round-trips negative indices (node=-1 = last node)
        if node >= 0x80000000:
            node -= 0x100000000
        return CtlEvent(
            action, node, delay_us, power, groups_json, src_json, dst_json
        )
    if ftype == CTL_STATUS:
        return CtlStatus(worker, tuple(heights))
    if ftype == CTL_STOP:
        return CtlStop(wedged)
    if ftype == CTL_REPORT:
        return CtlReport(worker, tuple(nodes), diag, error)
    raise ValueError(f"unknown control frame type {ftype}")


async def write_ctl(writer: asyncio.StreamWriter, msg) -> None:
    data = encode_ctl(msg)
    if len(data) > MAX_CTL_FRAME:
        raise ValueError("control frame exceeds bound")
    writer.write(len(data).to_bytes(4, "big") + data)
    await writer.drain()


async def read_ctl(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(4)
    n = int.from_bytes(hdr, "big")
    if n > MAX_CTL_FRAME:
        raise ValueError("oversized control frame")
    return decode_ctl(await reader.readexactly(n))


def event_to_ctl(ev: Event) -> CtlEvent:
    return CtlEvent(
        action=ev.action,
        node=ev.node,
        delay_us=int(ev.delay_ms * 1000),
        power=ev.power,
        groups_json=json.dumps(ev.groups) if ev.groups else "",
        src_json=json.dumps(ev.src) if ev.src else "",
        dst_json=json.dumps(ev.dst) if ev.dst else "",
    )


def ctl_to_event(c: CtlEvent) -> Event:
    def _grp(s: str) -> tuple:
        return tuple(json.loads(s)) if s else ()

    def _grps(s: str) -> tuple:
        return tuple(tuple(g) for g in json.loads(s)) if s else ()

    return Event(
        at_s=0.0,
        action=c.action,
        groups=_grps(c.groups_json),
        src=_grp(c.src_json),
        dst=_grp(c.dst_json),
        node=c.node,
        delay_ms=c.delay_us / 1000.0,
        power=c.power,
    )


# -- identities -------------------------------------------------------------

_NODE_ID_CACHE: dict[int, str] = {}


def xl_node_id(index: int) -> str:
    """Node id of RouterNet node `index` — RouterShell's derivation
    (key_seed "routernet"), computable in ANY process without building
    the node. The cross-process partition/gray events resolve indices
    through this."""
    nid = _NODE_ID_CACHE.get(index)
    if nid is None:
        priv = ed25519.Ed25519PrivKey(
            hashlib.sha256(f"tmtpu:routernet:{index}".encode()).digest()
        )
        nid = node_id_from_pubkey(priv.pub_key())
        _NODE_ID_CACHE[index] = nid
    return nid


def slice_assignment(n_vals: int, workers: int) -> list[list[int]]:
    """Contiguous balanced slices, worker w hosting slice w — a pure
    function of (n_vals, workers) so every process computes it."""
    base, extra = divmod(n_vals, workers)
    out, start = [], 0
    for w in range(workers):
        size = base + (1 if w < extra else 0)
        out.append(list(range(start, start + size)))
        start += size
    return out


def xl_topology_edges(
    n: int,
    degree: int,
    seed: int,
    slices: list[list[int]],
    bridges: int = 4,
) -> list[tuple[int, int]]:
    """Locality-aware topology for multi-process nets: each slice keeps
    the standard seeded RouterNet topology internally (those links ride
    the memory transport — cheap), while each PAIR of slices gets at
    most `bridges` deterministic bridge edges — the only links that pay
    the real-socket + SecretConnection AEAD cost. Gossip relay carries
    votes/parts through the bridges, so connectivity (slice subgraphs
    are connected, slice pairs are bridged) is all consensus needs.
    Without this, a 500-validator × 4-worker net wires ~1500 encrypted
    cross-process links and — on images where the AEAD is pure Python —
    vote gossip can't reach quorum within any wall budget; with it, the
    encrypted link count is K·(K−1)/2 · bridges. Pure function of
    (n, degree, seed, slices, bridges): every worker derives the same
    edge set without coordination."""
    edges: set[tuple[int, int]] = set()
    for sl in slices:
        for a, b in topology_edges(len(sl), degree, seed):
            ga, gb = sl[a], sl[b]
            edges.add((min(ga, gb), max(ga, gb)))
    rng = random.Random(
        f"routernet-xl-topo:{seed}:{n}:{len(slices)}:{bridges}"
    )
    for ai in range(len(slices)):
        for bi in range(ai + 1, len(slices)):
            sa, sb = slices[ai], slices[bi]
            want = min(bridges, len(sa) * len(sb))
            picked: set[tuple[int, int]] = set()
            attempts = 0
            while len(picked) < want and attempts < 50 * max(1, want):
                attempts += 1
                a = sa[rng.randrange(len(sa))]
                b = sb[rng.randrange(len(sb))]
                if a != b:
                    picked.add((min(a, b), max(a, b)))
            edges |= picked
    return sorted(edges)


def preload_txs(seed: int, count: int) -> list[bytes]:
    """The deterministic workload every validator preloads before Go:
    the committed tx sequence — and therefore the app-hash chain — is a
    pure function of (seed, count), which is what lets a wall-clock
    multi-process run be compared hash-for-hash against a frozen-clock
    in-process control run."""
    return [f"xl:{seed}:{k}=v{k}".encode() for k in range(count)]


# -- the worker-side slice net ---------------------------------------------


class XLSliceNet(RouterNet):
    """A RouterNet that builds only `slice_indices` of the committee.
    Each local node carries its memory transport (intra-slice links)
    plus one TCP/UDS transport (cross-slice links), both chaos-wrapped
    by RouterShell. Cross-slice wiring happens in `wire_topology` once
    the supervisor broadcasts the merged endpoint map."""

    def __init__(
        self,
        n_vals: int,
        *,
        slice_indices,
        transport_kind: str = "tcp",
        state_dir: str | None = None,
        durable: bool = True,
        workers: int | None = None,
        locality: bool = True,
        bridges: int = 4,
        **kw,
    ):
        self.slice_indices = tuple(sorted(slice_indices))
        self.transport_kind = transport_kind
        self.state_dir = state_dir or tempfile.mkdtemp(prefix="xl-slice-")
        # unix-transport socket paths live here even when stores are
        # not durable — the directory must exist either way
        os.makedirs(self.state_dir, exist_ok=True)
        self.durable = durable
        self.sock_transports: dict[int, TCPTransport] = {}
        super().__init__(n_vals, **kw)
        if locality and workers and workers > 1:
            # bound the encrypted cross-process link count: dense
            # in-slice (memory transport), `bridges` links per slice
            # pair (real sockets). Every worker derives the same set.
            self.edges = xl_topology_edges(
                self.n,
                kw.get("degree", 8),
                kw.get("topo_seed", 0),
                slice_assignment(self.n, workers),
                bridges,
            )
        self.by_index = {node.index: node for node in self.nodes}

    def _build_nodes(self):
        return [self._build_node(i) for i in self.slice_indices]

    def _extra_transports_for(self, index: int) -> list:
        if self.transport_kind == "memory":
            return []
        cls = TCPTransport if self.transport_kind == "tcp" else UDSTransport
        t = cls()
        self.sock_transports[index] = t
        return [t]

    def _build_node(self, i, *, app=None, block_store=None,
                    state_store=None, wal_dir=None):
        if self.durable:
            # durable per-node stores: a SIGKILLed worker's respawn
            # recovers block/state/app from SQLite + consensus-WAL
            # open-time repair — the CLI node's persistence shape.
            # (MemDB stores would leave the WAL AHEAD of state, which
            # catchup_replay correctly refuses as a double-sign hazard.)
            from ..abci.kvstore import KVStoreApp
            from ..state.store import StateStore
            from ..store.blockstore import BlockStore
            from ..store.db import SQLiteDB

            d = os.path.join(self.state_dir, f"n{i}")
            os.makedirs(d, exist_ok=True)
            if app is None and self._app_factory is None:
                app = KVStoreApp(SQLiteDB(os.path.join(d, "app.db")))
            if block_store is None:
                block_store = BlockStore(SQLiteDB(os.path.join(d, "blocks.db")))
            if state_store is None:
                state_store = StateStore(SQLiteDB(os.path.join(d, "state.db")))
            wal_dir = wal_dir or os.path.join(d, "wal")
        return super()._build_node(
            i, app=app, block_store=block_store, state_store=state_store,
            wal_dir=wal_dir,
        )

    def _connect(self) -> None:
        # wiring waits for the supervisor's topology broadcast
        pass

    async def listen(self) -> dict[int, str]:
        """Bind every local node's socket transport; returns the
        index -> endpoint map for the Hello frame."""
        eps: dict[int, str] = {}
        for i, t in sorted(self.sock_transports.items()):
            if self.transport_kind == "tcp":
                await t.listen("127.0.0.1:0")
                eps[i] = t.endpoint()
            else:
                path = os.path.join(self.state_dir, f"n{i}.sock")
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                await t.listen(path)
                eps[i] = path
        return eps

    async def listen_one(self, index: int) -> str:
        """Re-bind one node's transport after an in-worker restart."""
        t = self.sock_transports[index]
        if self.transport_kind == "tcp":
            await t.listen("127.0.0.1:0")
            return t.endpoint()
        path = os.path.join(self.state_dir, f"n{index}.sock")
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        await t.listen(path)
        return path

    def _sock_address(self, index: int, endpoint: str) -> NodeAddress:
        if self.transport_kind == "tcp":
            host, _, port = endpoint.rpartition(":")
            return NodeAddress(
                node_id=xl_node_id(index), protocol="tcp",
                host=host, port=int(port),
            )
        return NodeAddress(
            node_id=xl_node_id(index), protocol="unix",
            host=endpoint, port=0,
        )

    def wire_topology(self, endpoints: dict[int, str]) -> None:
        """Add peer addresses for every topology edge touching this
        slice: memory for local-local, socket for cross-slice (both
        sides dial; the router dedups the double connection). Safe to
        re-run on every topology broadcast — a respawned worker's new
        endpoints just become additional dial candidates."""
        local = self.by_index
        for a, b in self.edges:
            if a in local and b in local:
                local[a].shell.peer_manager.add_address(
                    local[b].shell.address()
                )
            elif a in local or b in local:
                li, ri = (a, b) if a in local else (b, a)
                ep = endpoints.get(ri)
                if ep:
                    local[li].shell.peer_manager.add_address(
                        self._sock_address(ri, ep)
                    )

    # crash/restart by GLOBAL index (RouterNet's are positional)

    def _pos(self, gi: int) -> int:
        for p, node in enumerate(self.nodes):
            if node.index == gi:
                return p
        raise KeyError(gi)

    async def crash(self, gi: int) -> None:
        node = self.by_index[gi]
        fs = node.fs
        if fs is not None:
            fs.halt()
        await node.stop()
        if fs is not None:
            fs.simulate_crash()

    async def restart(self, gi: int):
        old = self.by_index[gi]
        node = self._build_node(
            gi,
            app=old.inner.app,
            block_store=old.inner.block_store,
            state_store=old.inner.state_store,
            wal_dir=old.inner.wal_dir,
        )
        self.nodes[self._pos(gi)] = node
        self.by_index[gi] = node
        await node.start()
        return node


# -- worker process ---------------------------------------------------------


def _load_cfg(ctl_sock: str) -> dict:
    with open(
        os.path.join(os.path.dirname(ctl_sock), "xl_config.json"),
        encoding="utf-8",
    ) as f:
        return json.load(f)


def _resolve_scenario(cfg: dict) -> Scenario:
    scenario = SCENARIOS[cfg["scenario"]]
    if cfg.get("chaos_overrides"):
        scenario = replace(
            scenario, chaos=replace(scenario.chaos, **cfg["chaos_overrides"])
        )
    return scenario


def _build_slice(cfg: dict, widx: int, run_dir: str) -> XLSliceNet:
    scenario = _resolve_scenario(cfg)
    seed = cfg["seed"]
    n_vals = cfg["n_vals"]
    slices = slice_assignment(n_vals, cfg["workers"])
    chaos_cfg = replace(scenario.chaos, seed=seed, link_seeded=True)
    chaos = (
        ChaosNetwork(chaos_cfg)
        if (chaos_cfg.enabled() or scenario.events)
        else None
    )
    fs_factory = None
    if scenario.fs is not None:
        from ..libs.chaosfs import ChaosFS

        fs_cfg = scenario.fs

        def fs_factory(i: int, _cfg=fs_cfg, _seed=seed):
            return ChaosFS(replace(_cfg, seed=_seed * 1009 + i))

    config = None
    if (
        n_vals > 16
        or scenario.storm_timeouts
        or scenario.byz
        or scenario.byz_f_max is not None
    ):
        # storm-sized timers whenever rounds may churn: at committee
        # scale, under declared vote storms, and — multi-process
        # specific — whenever traitors withhold/lie over real sockets,
        # where per-frame AEAD + handshake latency makes fast
        # sub-second timers churn rounds faster than honest relay
        # gossip can heal the starved peers (steps advance on quorum,
        # not timers, so generous timers cost the happy path nothing).
        config = committee_config(max(n_vals, 10))
    byz_plan = {}
    for idx, bcfg in scenario.byz:
        i = idx % n_vals
        byz_plan[i] = replace(bcfg, seed=seed * 1013 + i)
    if scenario.byz_f_max is not None:
        f = max(0, (n_vals - 1) // 3)
        for i in range(n_vals - f, n_vals):
            byz_plan.setdefault(
                i, replace(scenario.byz_f_max, seed=seed * 1013 + i)
            )
    byz_registry: list = []
    net = XLSliceNet(
        n_vals,
        slice_indices=slices[widx],
        transport_kind=cfg.get("transport", "tcp"),
        state_dir=os.path.join(run_dir, f"w{widx}"),
        durable=cfg.get("durable", True),
        workers=cfg["workers"],
        locality=cfg.get("locality", True),
        bridges=cfg.get("bridges", 4),
        config=config,
        chaos=chaos,
        base_clock=None,  # wall-clock: multi-process runs pin app hashes
        degree=cfg.get("degree", 8),
        topo_seed=seed,
        gossip_sleep=cfg.get("gossip_sleep"),
        use_hub=True,
        fs_factory=fs_factory,
        prepare_hook=(
            byz_prepare_hook(byz_plan, byz_registry) if byz_plan else None
        ),
    )
    net._byz_plan = byz_plan
    net._byz_registry = byz_registry
    net._scenario = scenario
    return net


async def _apply_xl_event(ev: Event, net: XLSliceNet, seed: int) -> None:
    """Worker-side event application: identical semantics to
    scenarios._apply_event, with index -> node-id resolution through
    `xl_node_id` (events name GLOBAL indices; this slice may host none
    of them) and crash/restart applied only to local nodes."""
    n = net.n
    chaos = net.chaos
    named = _event_indices(ev, n)
    ids = lambda idxs: {xl_node_id(i) for i in idxs}  # noqa: E731
    if ev.action.startswith("churn_"):
        tx, expect_reject = _churn_tx(ev, net, seed)
        await _inject_tx(net, tx, expect_reject=expect_reject)
    elif ev.action == "partition":
        chaos.partition(*(ids(_resolve_group(g, n, named)) for g in ev.groups))
    elif ev.action == "oneway":
        chaos.partition_oneway(
            ids(_resolve_group(ev.src, n, named)),
            ids(_resolve_group(ev.dst, n, named)),
        )
    elif ev.action == "heal":
        chaos.heal()
    elif ev.action == "gray":
        chaos.set_gray(xl_node_id(ev.node % n), ev.delay_ms)
    elif ev.action == "ungray":
        chaos.set_peer_config(xl_node_id(ev.node % n), chaos.config)
    elif ev.action in ("crash", "restart"):
        gi = ev.node % n
        if gi in net.by_index:
            if ev.action == "crash":
                await net.crash(gi)
            else:
                await net.restart(gi)
                return gi  # caller re-binds the listener + re-Hellos
    else:
        raise ValueError(f"unknown xl event action {ev.action!r}")
    return None


def _slice_report(net: XLSliceNet, widx: int, diag: dict, error: str) -> CtlReport:
    nodes = []
    for node in net.nodes:
        store = node.inner.block_store
        height = store.height()
        upto = min(height, MAX_XL_CHAIN)
        app_hashes, block_hashes, evidence = [], [], 0
        for h in range(1, upto + 1):
            blk = store.load_block(h)
            if blk is None:
                app_hashes.append(b"")
                block_hashes.append(b"")
                continue
            app_hashes.append(blk.header.app_hash)
            block_hashes.append(blk.hash())
            evidence += len(blk.evidence)
        nodes.append(
            NodeReport(
                node.index, height, tuple(app_hashes), tuple(block_hashes),
                evidence,
            )
        )
    blob = json.dumps(diag, default=str).encode()
    if len(blob) > MAX_XL_DIAG:
        blob = json.dumps({"truncated": True}).encode()
    return CtlReport(widx, tuple(nodes), blob, error)


async def _worker(ctl_sock: str, widx: int, respawn: bool) -> int:
    cfg = await asyncio.to_thread(_load_cfg, ctl_sock)
    run_dir = os.path.dirname(ctl_sock)
    seed = cfg["seed"]
    net = _build_slice(cfg, widx, run_dir)
    scenario = net._scenario
    reader, writer = await asyncio.open_unix_connection(ctl_sock)
    error = ""
    stop_wedged = False
    event_tasks: set[asyncio.Task] = set()
    from ..crypto import verify_hub as vh

    hub = vh.acquire_hub()
    try:
        for node in net.nodes:
            await node.prepare()
        eps = await net.listen()
        await write_ctl(writer, CtlHello(widx, tuple(sorted(eps.items()))))

        started = False
        status_task: asyncio.Task | None = None

        async def status_loop():
            while True:
                await asyncio.sleep(cfg.get("status_interval_s", 0.4))
                hs = tuple(
                    (node.index, node.inner.block_store.height())
                    for node in net.nodes
                )
                try:
                    await write_ctl(writer, CtlStatus(widx, hs))
                except (ConnectionError, OSError):
                    return

        async def handle_event(ev: Event):
            rebind = await _apply_xl_event(ev, net, seed)
            if rebind is not None:
                ep = await net.listen_one(rebind)
                eps[rebind] = ep
                net.wire_topology(dict(_topology[0]))
                await write_ctl(
                    writer, CtlHello(widx, tuple(sorted(eps.items())))
                )

        _topology: list[dict[int, str]] = [{}]
        while True:
            msg = await read_ctl(reader)
            if isinstance(msg, CtlTopology):
                _topology[0] = dict(msg.endpoints)
                net.wire_topology(_topology[0])
            elif isinstance(msg, CtlGo):
                if not started:
                    if msg.preload:
                        txs = preload_txs(seed, cfg.get("preload_txs", 8))
                        from ..mempool.pool import (
                            TxInCacheError,
                            TxRejectedError,
                        )

                        for node in net.nodes:
                            for tx in txs:
                                try:
                                    await node.inner.mempool.check_tx(tx)
                                except (TxInCacheError, TxRejectedError):
                                    pass
                    await asyncio.gather(*(node.go() for node in net.nodes))
                    status_task = asyncio.get_running_loop().create_task(
                        status_loop()
                    )
                    started = True
            elif isinstance(msg, CtlEvent):
                t = asyncio.get_running_loop().create_task(
                    handle_event(ctl_to_event(msg))
                )
                event_tasks.add(t)
                t.add_done_callback(event_tasks.discard)
            elif isinstance(msg, CtlStop):
                stop_wedged = msg.wedged
                break
        if status_task is not None:
            status_task.cancel()
            await asyncio.gather(status_task, return_exceptions=True)
    except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
        error = f"control link lost: {e!r}"
    except Exception as e:  # noqa: BLE001 — reported, never a silent exit
        error = repr(e)
    finally:
        for t in event_tasks:
            t.cancel()
        await asyncio.gather(*event_tasks, return_exceptions=True)
        # build + send the report best-effort, then tear down
        try:
            audit = audit_net(
                net,
                net._byz_registry,
                k_heights=cfg.get("audit_k", 3),
                require_evidence=(
                    scenario.audit_require_evidence
                    and bool(net._byz_registry)
                ),
            ).as_dict()
        except Exception as e:  # noqa: BLE001
            audit = {"ok": False, "notes": [f"audit failed: {e!r}"]}
        diag = {
            "worker": widx,
            "slice": list(net.slice_indices),
            "faults": dict(net.chaos.faults) if net.chaos else {},
            "audit": audit,
            "byz": [b.log_summary() for b in net._byz_registry],
        }
        try:
            diag["verify_stats"] = hub.stats()
        except Exception:  # noqa: BLE001 — diagnostics only
            diag["verify_stats"] = {}
        if stop_wedged or error:
            payload = _snapshot_wedge(
                scenario, net, net.chaos,
                {"worker": widx, "seed": seed, "error": error},
            )
            try:
                diag["wedge_dump"] = await asyncio.to_thread(
                    _write_wedge,
                    os.path.join(run_dir, "dumps"),
                    f"w{widx}",
                    payload,
                )
            except Exception as e:  # noqa: BLE001
                diag["wedge_dump_error"] = repr(e)
        try:
            await write_ctl(writer, _slice_report(net, widx, diag, error))
        except (ConnectionError, OSError):
            pass
        try:
            writer.close()
        except Exception:
            pass
        await net.stop()
        vh.release_hub()
    return 1 if error else 0


def worker_main(argv: list[str] | None = None) -> int:
    """Worker process entry: `python -c "...; worker_main()" <ctl_sock>
    <worker_index> <fresh|respawn>` (spawned by XLNet)."""
    argv = argv if argv is not None else sys.argv[1:]
    ctl_sock, widx, mode = argv[0], int(argv[1]), argv[2]
    return asyncio.run(_worker(ctl_sock, widx, respawn=(mode == "respawn")))


# -- supervisor -------------------------------------------------------------


def aggregate_reports(
    reports: dict[int, CtlReport],
    *,
    byz_indices: set[int],
    require_evidence: bool,
) -> dict:
    """Cross-worker safety aggregation: every node that committed a
    height must agree on its block hash AND app hash (zero conflicting
    commits, net-wide) and every worker's local audit must pass.

    Accountability is enforced by the per-worker `audit_net` runs, not
    re-derived here: committed evidence rides the shared chain, so the
    worker hosting a twin-producing traitor fails its own audit if the
    evidence never lands — while withhold/flood strategies that never
    double-sign legitimately commit zero evidence. `evidence_total`
    (duplicate-vote evidence observed on honest chains) is surfaced as
    telemetry; `require_evidence` only annotates the notes when traitors
    were installed and no evidence committed anywhere."""
    block_conflicts: list[int] = []
    app_conflicts: list[int] = []
    by_h_block: dict[int, set[bytes]] = {}
    by_h_app: dict[int, set[bytes]] = {}
    evidence_total = 0
    worker_audits_ok = True
    notes: list[str] = []
    for rep in reports.values():
        try:
            diag = json.loads(rep.diag_json or b"{}")
        except ValueError:
            diag = {}
        audit = diag.get("audit") or {}
        if not audit.get("ok", False):
            worker_audits_ok = False
            notes.append(f"worker {rep.worker} audit: {audit.get('notes')}")
        for nr in rep.nodes:
            if nr.index not in byz_indices:
                evidence_total += nr.evidence
            for h0, bh in enumerate(nr.block_hashes):
                if bh:
                    by_h_block.setdefault(h0 + 1, set()).add(bh)
            for h0, ah in enumerate(nr.app_hashes):
                if ah:
                    by_h_app.setdefault(h0 + 1, set()).add(ah)
    block_conflicts = sorted(h for h, s in by_h_block.items() if len(s) > 1)
    app_conflicts = sorted(h for h, s in by_h_app.items() if len(s) > 1)
    if byz_indices and require_evidence and evidence_total == 0:
        # informational: worker audits decide whether this is a failure
        # (only twin-producing equivocators owe committed evidence)
        notes.append("no committed evidence on honest chains")
    return {
        "ok": (
            not block_conflicts
            and not app_conflicts
            and worker_audits_ok
        ),
        "block_conflicts": block_conflicts,
        "app_conflicts": app_conflicts,
        "worker_audits_ok": worker_audits_ok,
        "evidence_total": evidence_total,
        "notes": notes,
    }


class XLNet:
    """The supervisor: owns worker spawn/join/teardown, the control
    UDS, the optional verifyd sidecar, the scenario event script
    (socket-chaos events broadcast to workers; process faults applied
    here), the aggregated liveness watchdog, and report collection.
    `run()` returns one structured outcome dict — the chaos_soak
    contract (bounded wall clock, never raises on a wedge)."""

    def __init__(
        self,
        scenario: Scenario | str = "baseline",
        *,
        n_vals: int = 4,
        workers: int = 2,
        transport: str = "tcp",
        seed: int = 1,
        target_height: int = 4,
        timeout_s: float = 180.0,
        stall_s: float = 60.0,
        time_scale: float = 1.0,
        process_events: tuple[Event, ...] = (),
        use_verifyd: bool = False,
        preload: int = 8,
        durable: bool = True,
        gossip_sleep: float | None = None,
        degree: int = 8,
        locality: bool = True,
        bridges: int = 4,
        chaos_overrides: dict | None = None,
        status_interval_s: float = 0.4,
        report_timeout_s: float = 60.0,
        run_dir: str | None = None,
    ):
        if isinstance(scenario, str):
            scenario = SCENARIOS[scenario]
        self.scenario = scenario
        self.n_vals = n_vals
        self.workers = workers
        self.transport = transport
        self.seed = seed
        self.target_height = target_height
        self.timeout_s = timeout_s
        self.stall_s = stall_s
        self.time_scale = time_scale
        self.process_events = tuple(process_events)
        self.use_verifyd = use_verifyd
        self.preload = preload
        self.durable = durable
        self.gossip_sleep = gossip_sleep
        self.degree = degree
        self.locality = locality
        self.bridges = bridges
        self.chaos_overrides = chaos_overrides
        self.status_interval_s = status_interval_s
        self.report_timeout_s = report_timeout_s
        self.run_dir = run_dir
        self.slices = slice_assignment(n_vals, workers)
        # byz plan mirrors the worker derivation (supervisor needs the
        # indices for the honest-min watchdog + evidence aggregation)
        self.byz_indices: set[int] = {i % n_vals for i, _ in scenario.byz}
        if scenario.byz_f_max is not None:
            f = max(0, (n_vals - 1) // 3)
            self.byz_indices |= set(range(n_vals - f, n_vals))
        # runtime state
        self.procs: dict[int, subprocess.Popen] = {}
        self.conns: dict[int, asyncio.StreamWriter] = {}
        self.endpoints: dict[int, str] = {}
        self.status: dict[int, int] = {}
        self.reports: dict[int, CtlReport] = {}
        self.dead_workers: set[int] = set()
        self.hello_events: dict[int, asyncio.Event] = {}
        self.verifyd_proc: subprocess.Popen | None = None
        self.verifyd_sock: str | None = None
        self._server: asyncio.AbstractServer | None = None
        self._ctl_sock: str | None = None

    # -- process management (spawn/join ride to_thread: the supervisor
    # loop also carries the control server and the watchdog) ------------

    def _worker_env(self) -> dict:
        import tendermint_tpu

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(tendermint_tpu.__file__))
        )
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            TMTPU_DISABLE_TPU="1",
            PYTHONPATH=repo_root,
        )
        env.setdefault("TMTPU_MAX_BUCKET", "64")
        if self.verifyd_sock:
            env["TMTPU_VERIFYD_SOCK"] = self.verifyd_sock
        else:
            env.pop("TMTPU_VERIFYD_SOCK", None)
        return env

    async def _spawn_worker(self, widx: int, mode: str) -> None:
        log_path = os.path.join(self.run_dir, f"worker{widx}.log")
        self.hello_events.setdefault(widx, asyncio.Event()).clear()

        def _spawn():
            with open(log_path, "ab") as logf:
                return subprocess.Popen(
                    [
                        sys.executable,
                        "-c",
                        "import sys; "
                        "from tendermint_tpu.consensus.routernet_xl "
                        "import worker_main; sys.exit(worker_main())",
                        self._ctl_sock,
                        str(widx),
                        mode,
                    ],
                    env=self._worker_env(),
                    stdout=logf,
                    stderr=logf,
                    start_new_session=True,
                )

        self.procs[widx] = await asyncio.to_thread(_spawn)

    async def _kill_worker(self, widx: int) -> None:
        proc = self.procs.get(widx)
        if proc is None:
            return
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        await asyncio.to_thread(proc.wait)
        self.dead_workers.add(widx)
        # frozen stale heights must not satisfy the watchdog
        for gi in self.slices[widx]:
            self.status.pop(gi, None)
        w = self.conns.pop(widx, None)
        if w is not None:
            try:
                w.close()
            except Exception:
                pass

    async def _spawn_verifyd(self) -> None:
        self.verifyd_sock = os.path.join(self.run_dir, "verifyd.sock")
        env = self._worker_env()
        env.pop("TMTPU_DISABLE_TPU", None)
        env.pop("TMTPU_VERIFYD_SOCK", None)
        log_path = os.path.join(self.run_dir, "verifyd.log")

        def _spawn():
            with open(log_path, "ab") as logf:
                return subprocess.Popen(
                    [
                        sys.executable,
                        "-c",
                        "import sys; from tendermint_tpu.cli import main; "
                        f"sys.exit(main(['verifyd', '--sock', "
                        f"{self.verifyd_sock!r}, '--no-warm']))",
                    ],
                    env=env,
                    stdout=logf,
                    stderr=logf,
                    start_new_session=True,
                )

        self.verifyd_proc = await asyncio.to_thread(_spawn)
        # wait for the daemon socket to come up
        deadline = asyncio.get_running_loop().time() + 60.0
        while asyncio.get_running_loop().time() < deadline:
            stats = await asyncio.to_thread(self._verifyd_stats)
            if stats is not None:
                return
            await asyncio.sleep(0.25)
        raise TimeoutError("verifyd never came up")

    def _verifyd_stats(self) -> dict | None:
        from ..crypto.verifyd import client_for

        if not self.verifyd_sock:
            return None
        try:
            return client_for(self.verifyd_sock).remote_stats()  # tmtlint: allow[verify-chokepoint] -- occupancy telemetry probe, not a verify path
        except Exception:  # noqa: BLE001 — absent/killed daemon is a state
            return None

    async def _kill_verifyd(self) -> None:
        if self.verifyd_proc is None:
            return
        try:
            os.killpg(self.verifyd_proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        await asyncio.to_thread(self.verifyd_proc.wait)

    # -- control server --------------------------------------------------

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        widx: int | None = None
        try:
            while True:
                msg = await read_ctl(reader)
                if isinstance(msg, CtlHello):
                    widx = msg.worker
                    self.conns[widx] = writer
                    self.endpoints.update(dict(msg.endpoints))
                    self.dead_workers.discard(widx)
                    self.hello_events.setdefault(widx, asyncio.Event()).set()
                    # every (re-)hello changes the endpoint map: rebroadcast
                    await self._broadcast(
                        CtlTopology(tuple(sorted(self.endpoints.items())))
                    )
                elif isinstance(msg, CtlStatus):
                    for gi, h in msg.heights:
                        self.status[gi] = h
                elif isinstance(msg, CtlReport):
                    self.reports[msg.worker] = msg
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    async def _broadcast(self, msg, *, only: int | None = None) -> None:
        targets = (
            [only]
            if only is not None
            else [w for w in self.conns if w not in self.dead_workers]
        )
        for w in targets:
            writer = self.conns.get(w)
            if writer is None:
                continue
            try:
                await write_ctl(writer, msg)
            except (ConnectionError, OSError):
                pass

    # -- observation -----------------------------------------------------

    def honest_min(self) -> int:
        dead_nodes = {
            gi for w in self.dead_workers for gi in self.slices[w]
        }
        alive = [
            gi
            for gi in range(self.n_vals)
            if gi not in self.byz_indices and gi not in dead_nodes
        ]
        if not alive:
            alive = [gi for gi in range(self.n_vals) if gi not in dead_nodes]
        if not alive:
            return 0
        return min(self.status.get(gi, 0) for gi in alive)

    def honest_max(self) -> int:
        """Highest committed height on any live honest node — the
        stall watchdog's progress signal: a commit ANYWHERE means 2/3
        precommits existed, so the committee is converging, not wedged
        (at 500 validators on one core, catch-up spread of a committed
        height to the LAST node takes minutes — honest_min alone would
        misread that window as a stall)."""
        dead_nodes = {
            gi for w in self.dead_workers for gi in self.slices[w]
        }
        heights = [
            h
            for gi, h in self.status.items()
            if gi not in self.byz_indices and gi not in dead_nodes
        ]
        return max(heights, default=0)

    # -- the run ---------------------------------------------------------

    async def run(self) -> dict:
        loop = asyncio.get_running_loop()
        if self.run_dir is None:
            self.run_dir = await asyncio.to_thread(
                tempfile.mkdtemp, prefix="xl-run-"
            )
        self._ctl_sock = os.path.join(self.run_dir, "ctl.sock")
        cfg = {
            "scenario": self.scenario.name,
            "seed": self.seed,
            "n_vals": self.n_vals,
            "workers": self.workers,
            "transport": self.transport,
            "durable": self.durable,
            "degree": self.degree,
            "locality": self.locality,
            "bridges": self.bridges,
            "gossip_sleep": self.gossip_sleep,
            "preload_txs": self.preload,
            "status_interval_s": self.status_interval_s,
            "chaos_overrides": self.chaos_overrides,
        }

        def _write_cfg():
            with open(
                os.path.join(self.run_dir, "xl_config.json"),
                "w",
                encoding="utf-8",
            ) as f:
                json.dump(cfg, f)

        await asyncio.to_thread(_write_cfg)
        out: dict = {
            "outcome": "error",
            "scenario": self.scenario.name,
            "seed": self.seed,
            "n_vals": self.n_vals,
            "workers": self.workers,
            "transport": self.transport,
            "target_height": self.target_height,
            "events_applied": [],
            "process_events_applied": [],
            "heights": {},
            "honest_min": 0,
            "elapsed_s": 0.0,
            "blocks_per_s": 0.0,
            "recover_s": None,
            "faults": {},
            "audit": None,
            "app_hash_chain": [],
            "verifyd": None,
            "worker_errors": [],
            "dump_paths": [],
            "run_dir": self.run_dir,
            "error": "",
        }
        ok = wedged = False
        error = ""
        t0 = t_done = loop.time()
        events_task: asyncio.Task | None = None
        last_event_t = [t0]
        try:
            self._server = await asyncio.start_unix_server(
                self._handle_conn, self._ctl_sock
            )
            if self.use_verifyd:
                await self._spawn_verifyd()
            for w in range(self.workers):
                self.hello_events[w] = asyncio.Event()
            for w in range(self.workers):
                await self._spawn_worker(w, "fresh")
            await asyncio.wait_for(
                asyncio.gather(
                    *(self.hello_events[w].wait() for w in range(self.workers))
                ),
                self.timeout_s,
            )
            await self._broadcast(
                CtlTopology(tuple(sorted(self.endpoints.items())))
            )
            await self._broadcast(CtlGo(True))
            t0 = loop.time()

            events = sorted(
                (*self.scenario.events, *self.process_events),
                key=lambda e: e.at_s,
            )

            async def drive_events():
                for ev in events:
                    await asyncio.sleep(
                        max(0.0, ev.at_s * self.time_scale - (loop.time() - t0))
                    )
                    try:
                        if ev.action == "kill_worker":
                            await self._kill_worker(ev.node % self.workers)
                            out["process_events_applied"].append(
                                f"kill_worker:{ev.node % self.workers}"
                            )
                        elif ev.action == "restart_worker":
                            w = ev.node % self.workers
                            await self._spawn_worker(w, "respawn")
                            await asyncio.wait_for(
                                self.hello_events[w].wait(), 120.0
                            )
                            await self._broadcast(
                                CtlGo(self.preload > 0), only=w
                            )
                            out["process_events_applied"].append(
                                f"restart_worker:{w}"
                            )
                        elif ev.action == "kill_verifyd":
                            await self._kill_verifyd()
                            out["process_events_applied"].append("kill_verifyd")
                        else:
                            await self._broadcast(event_to_ctl(ev))
                            out["events_applied"].append(ev.action)
                    except Exception as e:  # noqa: BLE001 — recorded
                        out["worker_errors"].append(
                            f"event {ev.action}@{ev.at_s}: {e!r}"
                        )
                    last_event_t[0] = loop.time()

            events_task = loop.create_task(drive_events(), name="xl.events")

            # -- aggregated liveness watchdog (run_scenario's gate) ----
            deadline = t0 + self.timeout_s
            last_min = -1
            last_progress = loop.time()
            post_event_target: int | None = (
                self.target_height if not events else None
            )
            while True:
                await asyncio.sleep(0.25)
                mh = self.honest_min()
                now = loop.time()
                # stall resets on progress ANYWHERE (honest_max): a
                # commit on any node proves quorum; the min-height
                # target below still gates success on full catch-up
                if max(mh, self.honest_max()) > last_min:
                    last_min = max(mh, self.honest_max())
                    last_progress = now
                if post_event_target is None and events_task.done():
                    post_event_target = max(self.target_height, mh + 1)
                if post_event_target is not None and mh >= post_event_target:
                    ok = True
                    t_done = now
                    break
                if (
                    now > deadline
                    or (now - last_progress) > self.stall_s * self.time_scale
                ):
                    wedged = True
                    t_done = now
                    break
        except Exception as e:  # noqa: BLE001 — structured outcome contract
            error = repr(e)
            t_done = loop.time()
        finally:
            if events_task is not None:
                events_task.cancel()
                await asyncio.gather(events_task, return_exceptions=True)
            # verifyd occupancy BEFORE teardown (daemon may be gone: None)
            if self.use_verifyd:
                out["verifyd"] = await asyncio.to_thread(self._verifyd_stats)
            # collect reports from every live worker
            await self._broadcast(CtlStop(wedged or bool(error)))
            waited = loop.time()
            want = set(range(self.workers)) - self.dead_workers
            while (
                want - set(self.reports)
                and loop.time() - waited < self.report_timeout_s
            ):
                await asyncio.sleep(0.2)
            # teardown: SIGKILL anything still running, reap off-loop
            for w, proc in self.procs.items():
                if proc.poll() is None:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            await asyncio.gather(
                *(
                    asyncio.to_thread(p.wait)
                    for p in self.procs.values()
                ),
                return_exceptions=True,
            )
            await self._kill_verifyd()
            if self._server is not None:
                self._server.close()
                await self._server.wait_closed()

        agg = aggregate_reports(
            self.reports,
            byz_indices=self.byz_indices,
            require_evidence=self.scenario.audit_require_evidence,
        )
        out["audit"] = agg
        for rep in self.reports.values():
            if rep.error:
                out["worker_errors"].append(f"worker {rep.worker}: {rep.error}")
            try:
                diag = json.loads(rep.diag_json or b"{}")
            except ValueError:
                diag = {}
            if diag.get("wedge_dump"):
                out["dump_paths"].append(diag["wedge_dump"])
            for k, v in (diag.get("faults") or {}).items():
                out["faults"][k] = out["faults"].get(k, 0) + v
            for nr in rep.nodes:
                out["heights"][nr.index] = nr.height
        # canonical app-hash chain: the longest honest reported chain
        best: tuple[bytes, ...] = ()
        for rep in self.reports.values():
            for nr in rep.nodes:
                if nr.index not in self.byz_indices and len(
                    nr.app_hashes
                ) > len(best):
                    best = nr.app_hashes
        out["app_hash_chain"] = [h.hex() for h in best]
        out["honest_min"] = self.honest_min()
        elapsed = max(t_done - t0, 1e-9)
        out["elapsed_s"] = round(elapsed, 3)
        committed = out["honest_min"]
        out["blocks_per_s"] = round(committed / elapsed, 4) if ok else 0.0
        if ok and (self.scenario.events or self.process_events):
            out["recover_s"] = round(max(0.0, t_done - last_event_t[0]), 3)
        out["error"] = error
        if error:
            out["outcome"] = "error"
        elif wedged:
            out["outcome"] = "wedged"
        elif ok and agg["ok"]:
            out["outcome"] = "ok"
        else:
            out["outcome"] = "audit_failed"
        return out


async def run_xl(scenario: Scenario | str = "baseline", **kwargs) -> dict:
    """One multi-process XL run; see XLNet. Returns the structured
    outcome dict (never raises on a wedge)."""
    return await XLNet(scenario, **kwargs).run()
