"""The consensus state machine (reference internal/consensus/state.go:79).

Single-threaded by construction, exactly like the reference's
`receiveRoutine` (state.go:759): ALL state transitions happen on one
asyncio task consuming three inputs — peer messages, internal (self-
originated) messages, and timer ticks. Every input is WAL-written before
it is acted on, so a crash at any point replays deterministically
(`catchup_replay`, reference replay.go:94).

Step functions mirror the reference one-for-one:
  enter_new_round (state.go:1010) → enter_propose (:1092)
  → enter_prevote (:1270) → enter_prevote_wait → enter_precommit (:1366)
  → enter_precommit_wait → enter_commit (:1520) → finalize_commit (:1611)

The Tendermint locking rules live in `_add_vote` (prevote polka ⇒
valid-block update + possible unlock, state.go:2095-2160) and
`enter_precommit` (lock on polka, state.go:1412-1480).

Outbound messages (proposal, block parts, votes) are pushed through
`broadcast_hook`, which the consensus reactor (or an in-process test
network) installs; the SM never talks to the network directly.
"""

from __future__ import annotations

import asyncio
import logging
import os
import weakref
from dataclasses import dataclass
from typing import Callable

from ..config import ConsensusConfig
from ..evidence import EvidencePoolI, NopEvidencePool
from ..libs import trace
from ..libs.clock import SYSTEM, Clock
from ..libs.metrics import Histogram
from ..libs.service import Service
from ..privval import PrivValidator
from ..state.execution import BlockExecutor
from ..state.state import State
from ..state.validation import BlockValidationError
from ..store.blockstore import BlockStore
from ..types.block import Block, BlockID, NIL_BLOCK_ID
from ..types.events import (
    EventBus,
    EventDataCompleteProposal,
    EventDataVote,
)
from ..types.keys import SignedMsgType
from ..types.part_set import Part, PartSet
from ..types.vote import Proposal, Vote
from ..types.vote_set import ConflictingVoteError, VoteSet, VoteSetError
from ..libs import fail
from . import messages as m
from .ingest import IngestPipeline
from .ticker import TimeoutInfo, TimeoutTicker
from .types import HeightVoteSet, RoundState, RoundStep
from .wal import WAL, KIND_END_HEIGHT, KIND_MESSAGE


@dataclass(frozen=True)
class MsgInfo:
    msg: object
    peer_id: str = ""  # "" = internally generated
    # pipelined-ingest verdict (consensus/ingest.py): True = signature
    # proven in stage 1, don't re-check at apply; False = proven bad,
    # drop at apply; None = unknown, apply verifies synchronously
    sig_ok: bool | None = None
    # flight-recorder context (libs/trace.TraceCtx) following this
    # message end-to-end; None when tracing is off or the message is
    # internally generated. NEVER serialized into the WAL.
    trace: object = None


# queue sentinel: mempool signalled txs-available (create_empty_blocks=false)
_TXS_AVAILABLE = object()


class ConsensusError(RuntimeError):
    pass


# -- step-latency metrics ---------------------------------------------------
#
# consensus_step_duration_seconds{step=} + consensus_time_to_commit_seconds:
# round progress used to be invisible outside test asserts. Each running
# ConsensusState keeps its own histograms (multi-node in-process tests run
# several); NodeMetrics folds the registry at render time, mirroring
# consensus/ingest.aggregate.

#: step-duration buckets (seconds): fast_config rounds are tens of ms,
#: production rounds seconds
STEP_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

#: metric label per RoundStep — the wait variants fold into their step
#: (PREVOTE_WAIT is still time spent deciding the prevote outcome)
STEP_LABELS = ("new_height", "new_round", "propose", "prevote", "precommit", "commit")

_STEP_LABEL = {
    RoundStep.NEW_HEIGHT: "new_height",
    RoundStep.NEW_ROUND: "new_round",
    RoundStep.PROPOSE: "propose",
    RoundStep.PREVOTE: "prevote",
    RoundStep.PREVOTE_WAIT: "prevote",
    RoundStep.PRECOMMIT: "precommit",
    RoundStep.PRECOMMIT_WAIT: "precommit",
    RoundStep.COMMIT: "commit",
}

_step_states: "weakref.WeakSet[ConsensusState]" = weakref.WeakSet()


def aggregate_step_metrics():
    """({step label: (counts, sum, count)}, time-to-commit fold) across
    every running ConsensusState, or (None, None) when none is up."""
    states = [s for s in _step_states]
    if not states:
        return None, None

    def fold(hists):
        counts = [0] * (len(STEP_BUCKETS) + 1)
        total_sum, total_count = 0.0, 0
        for h in hists:
            for i, c in enumerate(h._counts):
                counts[i] += c
            total_sum += h._sum
            total_count += h._count
        return counts, total_sum, total_count

    per_step = {
        label: fold([s.step_hist[label] for s in states]) for label in STEP_LABELS
    }
    return per_step, fold([s.ttc_hist for s in states])


class ConsensusState(Service):
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        block_exec: BlockExecutor,
        block_store: BlockStore,
        *,
        priv_validator: PrivValidator | None = None,
        evidence_pool: EvidencePoolI | None = None,
        wal: WAL | None = None,
        event_bus: EventBus | None = None,
        mempool=None,
        clock: Clock | None = None,
        logger: logging.Logger | None = None,
    ):
        super().__init__("consensus", logger)
        self.config = config
        # injectable time source: every wall-clock reading the SM stamps
        # into protocol output (vote/proposal times, commit_time, the
        # NewHeight schedule) goes through this, so chaos runs can freeze
        # or skew it per validator (libs/clock.py)
        self.clock = clock or SYSTEM
        self.block_exec = block_exec
        self.block_store = block_store
        self.mempool = mempool
        self.priv_validator = priv_validator
        self.evidence_pool = evidence_pool or NopEvidencePool()
        self.wal = wal
        self.event_bus = event_bus

        self.rs = RoundState()
        self.state: State | None = None
        # one-shot log guard for the aggregate-commit fallback path
        self._warned_aggregate_fallback = False

        # one merged input queue for peer msgs and timer ticks — the
        # reference's select{} across three channels is pseudo-random among
        # ready cases, so a single FIFO is an equivalent (and fully
        # cancellable) discipline; internal msgs are handled synchronously
        # in _send_internal
        self.msg_queue: asyncio.Queue[MsgInfo | TimeoutInfo] = asyncio.Queue(
            maxsize=2000
        )
        self.ticker = TimeoutTicker(self.msg_queue, clock=self.clock)

        # reactor hooks: called with consensus Messages to gossip out
        self.broadcast_hook: Callable[[object], None] | None = None
        # step-change hook (reactor broadcasts NewRoundStep from it)
        self.step_hook: Callable[[RoundState], None] | None = None
        # called (peer_id, vote) when the pipeline proved a peer-supplied
        # signature bad — the reactor turns it into a PeerError
        self.invalid_sig_hook: Callable[[str, Vote], None] | None = None

        # two-stage pipelined ingest (consensus/ingest.py): stage 1
        # verifies signatures concurrently through the async hub API,
        # stage 2 applies in strict arrival order. Env wins over config
        # (same contract as the TMTPU_VERIFYHUB_* knobs).
        pipe_on = config.ingest_pipeline
        env = os.environ.get("TMTPU_INGEST_PIPELINE")
        if env:
            pipe_on = env.lower() not in ("0", "false", "no")
        inflight = config.ingest_max_inflight
        env = os.environ.get("TMTPU_INGEST_INFLIGHT")
        if env:
            inflight = int(env)
        self.ingest: IngestPipeline | None = None
        if pipe_on:
            self.ingest = IngestPipeline(
                self,
                max_inflight=inflight,
                logger=self.logger.getChild("ingest"),
            )

        self._replay_mode = False
        self._paused = False  # switch-back-to-blocksync gate
        self._n_started_height = 0
        self._wake = asyncio.Event()  # new-height nudge for tests
        self._decided: asyncio.Event = asyncio.Event()
        self._sign_jobs: list[tuple] = []  # deferred privval signing

        # step-latency instrumentation (folded into /metrics via
        # aggregate_step_metrics; durations on the injected clock's
        # monotonic domain so chaos runs stay deterministic)
        self.step_hist = {
            label: Histogram(
                f"consensus_step_duration_seconds_{label}",
                "time spent in this consensus step",
                buckets=STEP_BUCKETS,
            )
            for label in STEP_LABELS
        }
        self.ttc_hist = Histogram(
            "consensus_time_to_commit_seconds",
            "height start to committed block",
            buckets=STEP_BUCKETS,
        )
        self._step_entered: tuple | None = None  # (RoundStep, entered_at)
        self._height_t0 = self.clock.monotonic()

        self.update_to_state(state)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def on_start(self) -> None:
        _step_states.add(self)
        if self.wal is not None:
            self.catchup_replay()
        if self.ingest is not None:
            self.ingest.start()
        self.spawn(self._receive_routine(), name="cs.receive")
        if not self.config.create_empty_blocks and self.mempool is not None:
            # reference receiveRoutine's txsAvailable case (state.go:770):
            # with create_empty_blocks=false the proposer blocks in
            # NEW_ROUND until the mempool signals txs — without this
            # consumer the chain stalls permanently at the first empty
            # height when the interval is 0
            self.spawn(self._txs_available_routine(), name="cs.txs_available")
        # kick off the first height
        self._schedule_timeout(
            self.config.timeout_commit_ns, self.rs.height, 0, RoundStep.NEW_HEIGHT
        )

    async def on_stop(self) -> None:
        _step_states.discard(self)  # stop folding into /metrics
        self.ticker.stop()
        if self.ingest is not None:
            self.ingest.stop()
        if self.wal is not None:
            self.wal.close()

    # ------------------------------------------------------------------
    # public input
    # ------------------------------------------------------------------

    async def add_proposal(
        self, proposal: Proposal, peer_id: str = "", trace_ctx=None
    ) -> None:
        await self._ingest_put(
            MsgInfo(m.ProposalMessage(proposal), peer_id, trace=trace_ctx)
        )

    async def add_block_part(
        self, height: int, round_: int, part: Part, peer_id: str = "",
        trace_ctx=None,
    ) -> None:
        await self._ingest_put(
            MsgInfo(m.BlockPartMessage(height, round_, part), peer_id, trace=trace_ctx)
        )

    async def add_vote(self, vote: Vote, peer_id: str = "", trace_ctx=None) -> None:
        await self._ingest_put(
            MsgInfo(m.VoteMessage(vote), peer_id, trace=trace_ctx)
        )

    async def _ingest_put(self, mi: MsgInfo) -> None:
        """Peer inputs enter through the pipelined ingest when it is
        running (stage-1 concurrent verify, in-order release); otherwise
        — pipeline disabled, or the SM not yet started — straight onto
        the input queue, the sequential facade."""
        if self.ingest is not None and self.ingest.started and not self._stopping:
            await self.ingest.submit(mi)
        else:
            await self.msg_queue.put(mi)

    def get_round_state(self) -> RoundState:
        return self.rs

    # ------------------------------------------------------------------
    # state setup
    # ------------------------------------------------------------------

    def update_to_state(self, state: State) -> None:
        """Prepare the round state for height state.last_block_height+1
        (reference updateToState state.go:xxx after finalize)."""
        if (
            self.rs.commit_round > -1
            and 0 < self.rs.height <= state.last_block_height
        ):
            # finished a height; sanity check
            if self.rs.height != state.last_block_height:
                raise ConsensusError(
                    f"updateToState expected height {self.rs.height}, "
                    f"state at {state.last_block_height}"
                )
        height = state.last_block_height + 1
        if height == 1:
            last_precommits = None
        else:
            if self.rs.commit_round > -1 and self.rs.votes is not None:
                last_precommits = self.rs.votes.precommits(self.rs.commit_round)
                if last_precommits is None or not last_precommits.has_two_thirds_majority():
                    raise ConsensusError("commit round has no +2/3 precommits")
            else:
                last_precommits = self.rs.last_commit  # restart path
        validators = state.validators.copy()

        rs = self.rs
        rs.height = height
        rs.round = 0
        rs.step = RoundStep.NEW_HEIGHT
        if rs.commit_time_ns == 0:
            rs.start_time_ns = self.config.commit_time_ns(self.clock.now_ns())
        else:
            rs.start_time_ns = self.config.commit_time_ns(rs.commit_time_ns)
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_commit = last_precommits
        rs.last_validators = state.last_validators.copy() if state.last_validators else None
        rs.triggered_timeout_precommit = False
        self.state = state
        self._height_t0 = self.clock.monotonic()  # time-to-commit anchor
        self._new_step()

    def _new_step(self) -> None:
        # step-duration accounting: observe the step being LEFT (wait
        # variants fold into their parent step's label)
        now = self.clock.monotonic()
        prev = self._step_entered
        self._step_entered = (self.rs.step, now)
        if prev is not None and prev[0] != self.rs.step and not self._replay_mode:
            label = _STEP_LABEL.get(prev[0])
            if label is not None:
                self.step_hist[label].observe(max(0.0, now - prev[1]))
        if self.step_hook is not None:
            self.step_hook(self.rs)
        if self.event_bus is not None:
            self.event_bus.publish_new_round_step(self.rs.round_state_event())

    # ------------------------------------------------------------------
    # WAL replay
    # ------------------------------------------------------------------

    def catchup_replay(self) -> None:
        """Replay WAL messages for the in-progress height (reference
        replay.go:94 catchupReplay)."""
        cs_height = self.rs.height
        recs = self.wal.search_for_end_height(cs_height - 1)
        if recs is None:
            # Distinguish "WAL simply ends before that height" (fine: the
            # node advanced via block-sync/state-sync, nothing to replay —
            # the reference's io.EOF case) from "WAL reaches beyond it but
            # the marker is missing" (corruption / double-sign hazard).
            max_marker = -1
            for rec in self.wal.iter_records():
                if rec.kind == KIND_END_HEIGHT:
                    max_marker = max(max_marker, rec.height)
            if max_marker > cs_height - 1:
                raise ConsensusError(
                    f"WAL contains end-height {max_marker} beyond expected "
                    f"{cs_height - 1}; refusing to start (double-sign hazard)"
                )
            if cs_height == self.state.initial_height or max_marker < cs_height - 1:
                self.logger.info(
                    "WAL ends before height %d; skipping replay", cs_height - 1
                )
                recs = []
        self._replay_mode = True
        try:
            for rec in recs:
                if rec.kind != KIND_MESSAGE:
                    continue
                msg, peer = m.decode_wal_message(rec.data)
                if isinstance(msg, TimeoutInfo):
                    self._handle_timeout(msg)
                else:
                    self._handle_msg(MsgInfo(msg, peer or ""))
        finally:
            self._replay_mode = False
        self.logger.info("WAL replay done at height %d", cs_height)

    # ------------------------------------------------------------------
    # the single-threaded event loop
    # ------------------------------------------------------------------

    def pause(self) -> None:
        """Freeze the state machine while block-sync re-takes over (the
        node fell too far behind for vote gossip to catch up). Inputs are
        dropped; timers are ignored."""
        self._paused = True
        self._finalize_pending = False
        self._sign_jobs.clear()
        self.ticker.stop()

    def resume_with_state(self, state: State) -> None:
        """Resume after a re-sync at the new tip. Must be called from the
        same event loop (the SM is single-task; this mutation is atomic
        under cooperative scheduling)."""
        self.rs.commit_round = -1
        self.rs.last_commit = None
        self.rs.commit_time_ns = 0
        self.update_to_state(state)
        self._paused = False
        self._schedule_timeout(
            self.config.timeout_commit_ns, self.rs.height, 0, RoundStep.NEW_HEIGHT
        )

    # messages drained per receive wakeup: under a saturated event loop
    # (150-validator in-process nets) a task gets roughly one wakeup per
    # loop cycle, so one-message-per-wakeup caps the SM at the loop's
    # cycle rate regardless of how cheap an apply is — a catching-up
    # node with a 10k-vote backlog would take minutes to drain it.
    # Draining a bounded burst per wakeup amortizes the wakeup; order is
    # untouched (same single consumer, same queue order).
    RECV_BURST = 64

    async def _receive_routine(self) -> None:
        while True:
            item = await self.msg_queue.get()
            await self._process_input(item)
            for _ in range(self.RECV_BURST - 1):
                try:
                    item = self.msg_queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                await self._process_input(item)

    async def _process_input(self, item) -> None:
        if self._paused:
            return
        # WAL-first, OUTSIDE the survive-the-message guard below: a node
        # that cannot persist its inputs must fail-stop (the WAL crash
        # model depends on every acted-on input being on disk), so a
        # write/fsync error here still kills the receive loop. Peer
        # msgs are buffered writes (group flush); internal msgs are
        # WAL-synced in _send_internal (reference state.go:782-806).
        if isinstance(item, TimeoutInfo):
            self._wal_write(m.encode_wal_message(item), sync=True)
        elif item is not _TXS_AVAILABLE:
            self._wal_write(
                m.encode_wal_message(item.msg, item.peer_id), sync=False
            )
        try:
            if item is _TXS_AVAILABLE:
                self._handle_txs_available()
            elif isinstance(item, TimeoutInfo):
                self._handle_timeout(item)
            else:
                ctx = item.trace
                if ctx is None:
                    self._handle_msg(item)
                else:
                    # apply span starts at the reorder release so the
                    # four ingest stages tile the end-to-end span:
                    # wait + verify + reorder + apply == msg, exactly
                    t_apply = ctx.marks.get("release", self.clock.monotonic())
                    try:
                        self._handle_msg(item)
                    finally:
                        t_done = self.clock.monotonic()
                        kind = type(item.msg).__name__
                        trace.record(
                            ctx, "consensus", "apply", t_apply, t_done, msg=kind
                        )
                        trace.record(
                            ctx, "consensus", "msg",
                            ctx.marks.get("submit", ctx.t0), t_done,
                            msg=kind, peer=item.peer_id, sig_ok=item.sig_ok,
                        )
        except ConflictingVoteError as e:
            self.evidence_pool.report_conflicting_votes(e.existing, e.new)
            self.logger.info(
                "found conflicting vote, sent to evidence pool: %s", e.new
            )
        except (VoteSetError, BlockValidationError, ValueError) as e:
            self.logger.info("dropped invalid consensus input: %r", e)
        except Exception:  # noqa: BLE001 — the ONE receive task
            # Any other exception here kills the single receive task
            # and silently freezes the node: ingest permits drain,
            # msg_queue fills, and the only symptom is a validator
            # that stops voting (the router-chaos matrix caught
            # exactly this as 150-validator stragglers frozen behind
            # a dead SM). An unexpected input failure is loud but
            # survivable — fail the MESSAGE, never the machine.
            self.logger.error(
                "consensus input failed at h=%d r=%d (dropped): %s",
                self.rs.height,
                self.rs.round,
                type(item).__name__,
                exc_info=True,
            )
        # run async follow-ups scheduled by handlers (off-loop privval
        # signing, then finalize) until quiescent — a signed own-vote
        # can trigger transitions that queue more signing; a failure
        # here must not kill the receive loop
        try:
            while (self._sign_jobs or self._finalize_pending) and (
                not self._paused
            ):
                await self._drain_signing()
                await self._drain_finalize()
        except Exception as e:
            self.logger.error(
                "finalize failed at height %d: %r", self.rs.height, e
            )

    def _wal_write(self, payload: bytes, *, sync: bool) -> None:
        if self.wal is None or self._replay_mode:
            return
        if sync:
            self.wal.write_sync(payload)
        else:
            self.wal.write(payload)

    _finalize_pending: bool = False

    async def _drain_finalize(self) -> None:
        while self._finalize_pending:
            self._finalize_pending = False
            await self._finalize_commit()

    def _queue_signing(self, sign_fn, on_signed, what: str) -> None:
        """Defer a privval signing call: the blocking I/O (remote signer
        socket + retry backoff, FilePV fsync) runs in a worker thread and
        only the consensus task waits on it — like the reference, where
        SignVote blocks receiveRoutine but no other goroutine."""
        self._sign_jobs.append((sign_fn, on_signed, what))

    async def _drain_signing(self) -> None:
        while self._sign_jobs and not self._paused:
            sign_fn, on_signed, what = self._sign_jobs.pop(0)
            try:
                signed = await asyncio.to_thread(sign_fn)
            except Exception as e:
                self.logger.error("failed signing %s: %r", what, e)
                continue
            if self._paused:
                # pause() landed while the sign was in flight: block-sync
                # owns block application now — drop the result
                return
            on_signed(signed)

    # ------------------------------------------------------------------
    # message dispatch (sync — mutations happen inline; the only async
    # part, ApplyBlock, is deferred via _finalize_pending)
    # ------------------------------------------------------------------

    def _handle_msg(self, mi: MsgInfo) -> None:
        msg = mi.msg
        if isinstance(msg, m.ProposalMessage):
            self._set_proposal(msg.proposal, sig_ok=mi.sig_ok)
        elif isinstance(msg, m.BlockPartMessage):
            self._add_proposal_block_part(msg, mi.peer_id)
        elif isinstance(msg, m.VoteMessage):
            self._try_add_vote(msg.vote, mi.peer_id, sig_ok=mi.sig_ok)
        else:
            self.logger.debug("ignoring message %s", type(msg).__name__)

    async def _txs_available_routine(self) -> None:
        """Bridge the mempool's txs-available signal into the state
        machine's input queue (reference state.go:770 txsAvailable case).
        Fires at most once per height: `notified_txs_available` is the
        latch, reset by mempool.update() after each commit."""
        while True:
            await self.mempool.wait_for_txs()
            if self.mempool.notified_txs_available:
                # already fired for this height; txs still resident —
                # sleep until the post-commit reset pulse
                await self.mempool.wait_notified_reset()
                continue
            if self.mempool.size() == 0:
                # raced a commit: between the txs-available wakeup and
                # this resumption, mempool.update() drained the pool and
                # reset the latch — firing now would propose an empty
                # block despite create_empty_blocks=false
                continue
            self.mempool.notified_txs_available = True
            await self.msg_queue.put(_TXS_AVAILABLE)

    def _need_proof_block(self, height: int) -> bool:
        """Reference needProofBlock state.go:1048: the app hash produced by
        executing height-1 only becomes part of a header at `height` — if
        it changed, that block must be proposed even with an empty mempool,
        or the new app state is never committed to any header."""
        if self.state is None or height == self.state.initial_height:
            return True
        meta_header = self.block_store.load_block(height - 1)
        if meta_header is None:
            return False
        return self.state.app_hash != meta_header.header.app_hash

    def _handle_txs_available(self) -> None:
        """Reference handleTxsAvailable state.go:919: with
        create_empty_blocks=false the proposer idles in NEW_HEIGHT /
        NEW_ROUND until the mempool has work; this kicks it forward."""
        rs = self.rs
        if self.config.create_empty_blocks:
            return
        if rs.step == RoundStep.NEW_HEIGHT:
            # commit timeout still pending — arm a NEW_ROUND step timeout
            # for its REMAINING time (state.go:927), so the block lands at
            # the configured inter-block cadence, not tx-arrival + full
            # commit timeout
            self._schedule_timeout(
                max(0, rs.start_time_ns - self.clock.now_ns()),
                rs.height,
                0,
                RoundStep.NEW_ROUND,
            )
        elif rs.step == RoundStep.NEW_ROUND:
            self._enter_propose(rs.height, 0)

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """Reference handleTimeout state.go:907."""
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < rs.step
        ):
            return
        if ti.step == RoundStep.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == RoundStep.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == RoundStep.PROPOSE:
            if self.event_bus is not None:
                self.event_bus.publish_timeout_propose(rs.round_state_event())
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == RoundStep.PREVOTE_WAIT:
            if self.event_bus is not None:
                self.event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStep.PRECOMMIT_WAIT:
            if self.event_bus is not None:
                self.event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise ConsensusError(f"invalid timeout step {ti.step}")

    def _schedule_timeout(
        self, duration_ns: int, height: int, round_: int, step: RoundStep
    ) -> None:
        # note: scheduling stays live during WAL replay (like the
        # reference's catchupReplay driving the real timeoutTicker) so a
        # node restarted mid-round has its step timeout armed
        self.ticker.schedule(TimeoutInfo(duration_ns, height, round_, step))

    def _broadcast(self, msg) -> None:
        if self.broadcast_hook is not None and not self._replay_mode:
            self.broadcast_hook(msg)

    def _send_internal(self, mi: MsgInfo) -> None:
        """Internal messages loop straight back into the queue (reference
        sendInternalMessage state.go) — but since we are single-threaded
        we can handle them synchronously for determinism."""
        self._wal_write(m.encode_wal_message(mi.msg, mi.peer_id), sync=True)
        try:
            self._handle_msg(mi)
        except ConflictingVoteError as e:
            self.evidence_pool.report_conflicting_votes(e.existing, e.new)

    # ------------------------------------------------------------------
    # step: NewRound
    # ------------------------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        """Reference enterNewRound state.go:1010."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            return
        self.logger.debug("enterNewRound %d/%d", height, round_)

        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy_increment_proposer_priority(
                round_ - rs.round
            )
            rs.validators = validators
        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        if round_ != 0:
            # round 0 keeps the proposal from NewHeight setup; later rounds
            # start fresh (but keep the proposal *block* if it repropagates)
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.triggered_timeout_precommit = False
        rs.votes.set_round(round_ + 1)
        if self.event_bus is not None:
            self.event_bus.publish_new_round(rs.round_state_event())
        self._new_step()

        wait_for_txs = (
            not self.config.create_empty_blocks
            and round_ == 0
            and not self._need_proof_block(height)
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval_ns > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval_ns,
                    height,
                    round_,
                    RoundStep.NEW_ROUND,
                )
        else:
            self._enter_propose(height, round_)

    # ------------------------------------------------------------------
    # step: Propose
    # ------------------------------------------------------------------

    def _enter_propose(self, height: int, round_: int) -> None:
        """Reference enterPropose state.go:1092."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PROPOSE
        ):
            return
        self.logger.debug("enterPropose %d/%d", height, round_)
        rs.round = round_
        rs.step = RoundStep.PROPOSE
        self._new_step()

        self._schedule_timeout(
            self.config.propose_timeout_ns(round_), height, round_, RoundStep.PROPOSE
        )
        if self._is_proposal_complete():
            self._enter_prevote(height, round_)
        if self._is_proposer():
            self._decide_proposal(height, round_)

    def _is_proposer(self) -> bool:
        if self.priv_validator is None:
            return False
        addr = self.priv_validator.get_pub_key().address()
        return self.rs.validators.get_proposer().address == addr

    def _is_proposal_complete(self) -> bool:
        """Reference isProposalComplete state.go:1216: need the proposal +
        full block; if POL round set, also need that round's polka."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _decide_proposal(self, height: int, round_: int) -> None:
        """Reference defaultDecideProposal state.go:1163."""
        if self._replay_mode:
            return  # our own proposal is in the WAL; don't re-sign
        rs = self.rs
        if rs.locked_block is not None:
            block, parts = rs.locked_block, rs.locked_block_parts
        elif rs.valid_block is not None:
            block, parts = rs.valid_block, rs.valid_block_parts
        else:
            proposer_addr = self.priv_validator.get_pub_key().address()
            last_commit = None
            if height > self.state.initial_height:
                last_commit = self.block_store.load_seen_commit(height - 1)
                if last_commit is None and rs.last_commit is not None:
                    last_commit = self._materialize_commit(rs.last_commit)
            try:
                block, parts = self.block_exec.create_proposal_block(
                    height, self.state, last_commit, proposer_addr
                )
            except Exception as e:
                self.logger.error("failed to create proposal block: %r", e)
                return

        block_id = BlockID(block.hash(), parts.header)
        proposal = Proposal(height, round_, rs.valid_round, block_id, self.clock.now_ns())

        def on_signed(signed: Proposal) -> None:
            self._send_internal(MsgInfo(m.ProposalMessage(signed)))
            self._broadcast(m.ProposalMessage(signed))
            for i in range(parts.header.total):
                part = parts.get_part(i)
                self._send_internal(MsgInfo(m.BlockPartMessage(height, round_, part)))
                self._broadcast(m.BlockPartMessage(height, round_, part))
            self.logger.info(
                "proposed block %d/%d %s", height, round_, block_id.hash.hex()[:12]
            )

        # signing may hit a remote signer (socket I/O + retry backoff) —
        # run it off-loop; the receive routine awaits the job before
        # taking the next input, so SM ordering is unchanged (the
        # reference's receiveRoutine blocks on SignProposal the same way)
        self._queue_signing(
            lambda: self.priv_validator.sign_proposal(self.state.chain_id, proposal),
            on_signed,
            "proposal",
        )

    # ------------------------------------------------------------------
    # proposal intake
    # ------------------------------------------------------------------

    def _set_proposal(self, proposal: Proposal, sig_ok: bool | None = None) -> None:
        """Reference defaultSetProposal state.go:1821."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        proposal.validate_basic()
        if not (-1 <= proposal.pol_round < proposal.round):
            raise ValueError("invalid proposal POL round")
        # verify proposer signature (state.go:1847). The pipelined
        # ingest usually proved (or disproved) it in stage 1 — sig_ok
        # is only trusted because the (height, round) equality above
        # pins the same proposer the pipeline verified against. The
        # sync fallback routes through the VerifyHub: the same proposal
        # gossiped by several peers is answered from the verdict cache
        # instead of re-verified per peer.
        if sig_ok is False:
            raise ValueError("invalid proposal signature")
        if sig_ok is not True:
            from ..crypto.verify_hub import verify_one

            proposer = rs.validators.get_proposer()
            sb = proposal.sign_bytes(self.state.chain_id)
            if not verify_one(proposer.pub_key, sb, proposal.signature):
                raise ValueError("invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(
                proposal.block_id.part_set_header
            )
        self.logger.debug("received proposal %d/%d", proposal.height, proposal.round)

    def _add_proposal_block_part(self, msg: m.BlockPartMessage, peer_id: str) -> bool:
        """Reference addProposalBlockPart state.go:1863."""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if not added:
            return False
        if rs.proposal_block_parts.is_complete():
            data = rs.proposal_block_parts.assemble()
            block = Block.decode(data)
            # integrity: the completed block must hash to the proposal's
            # block id — but only when this part set IS the proposal's.
            # After enterCommit re-arms the part set for a DECIDED block
            # (catch-up: +2/3 precommits for a round whose proposal we
            # missed), rs.proposal may still hold a later round's
            # proposal for a different block; comparing against it wedged
            # the height forever (the part set completes exactly once).
            if (
                rs.proposal is not None
                and rs.proposal.block_id.part_set_header
                == rs.proposal_block_parts.header
                and block.hash() != rs.proposal.block_id.hash
            ):
                raise ValueError("completed proposal block hash mismatch")
            rs.proposal_block = block
            self.logger.info(
                "received complete proposal block %d %s",
                block.header.height,
                block.hash().hex()[:12],
            )
            if self.event_bus is not None:
                self.event_bus.publish_complete_proposal(
                    EventDataCompleteProposal(
                        rs.height,
                        rs.round,
                        rs.step.name,
                        BlockID(block.hash(), rs.proposal_block_parts.header),
                    )
                )
            # update valid block if a polka already exists for it
            prevotes = rs.votes.prevotes(rs.round)
            maj = prevotes.two_thirds_majority() if prevotes else None
            if (
                maj is not None
                and not maj.is_nil()
                and rs.valid_round < rs.round
                and maj.hash == block.hash()
            ):
                rs.valid_round = rs.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts
            if rs.step <= RoundStep.PROPOSE and self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)
            elif rs.step == RoundStep.COMMIT:
                self._finalize_later()
        return True

    # ------------------------------------------------------------------
    # step: Prevote
    # ------------------------------------------------------------------

    def _enter_prevote(self, height: int, round_: int) -> None:
        """Reference enterPrevote state.go:1270."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE
        ):
            return
        self.logger.debug("enterPrevote %d/%d", height, round_)
        rs.round = round_
        rs.step = RoundStep.PREVOTE
        self._new_step()
        self._do_prevote(height, round_)

    def _do_prevote(self, height: int, round_: int) -> None:
        """Reference defaultDoPrevote state.go:1299."""
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(
                SignedMsgType.PREVOTE,
                BlockID(rs.locked_block.hash(), rs.locked_block_parts.header),
            )
            return
        if rs.proposal_block is None:
            self._sign_add_vote(SignedMsgType.PREVOTE, NIL_BLOCK_ID)
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except (BlockValidationError, ValueError) as e:
            self.logger.info("prevote nil: invalid proposal block: %r", e)
            self._sign_add_vote(SignedMsgType.PREVOTE, NIL_BLOCK_ID)
            return
        self._sign_add_vote(
            SignedMsgType.PREVOTE,
            BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header),
        )

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise ConsensusError("enterPrevoteWait without +2/3 prevotes")
        rs.round = round_
        rs.step = RoundStep.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.config.prevote_timeout_ns(round_),
            height,
            round_,
            RoundStep.PREVOTE_WAIT,
        )

    # ------------------------------------------------------------------
    # step: Precommit
    # ------------------------------------------------------------------

    def _enter_precommit(self, height: int, round_: int) -> None:
        """Reference enterPrecommit state.go:1366 — the locking step."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PRECOMMIT
        ):
            return
        self.logger.debug("enterPrecommit %d/%d", height, round_)
        rs.round = round_
        rs.step = RoundStep.PRECOMMIT
        self._new_step()

        prevotes = rs.votes.prevotes(round_)
        block_id = prevotes.two_thirds_majority() if prevotes else None

        if block_id is None:
            # no polka: precommit nil (but do NOT unlock)
            self._sign_add_vote(SignedMsgType.PRECOMMIT, NIL_BLOCK_ID)
            return

        if self.event_bus is not None:
            self.event_bus.publish_polka(rs.round_state_event())

        if block_id.is_nil():
            # +2/3 prevoted nil: unlock and precommit nil (state.go:1431)
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            self._sign_add_vote(SignedMsgType.PRECOMMIT, NIL_BLOCK_ID)
            return

        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            # relock (state.go:1445)
            rs.locked_round = round_
            if self.event_bus is not None:
                self.event_bus.publish_lock(rs.round_state_event())
            self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id)
            return

        if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
            # lock the proposal block (state.go:1458)
            self.block_exec.validate_block(self.state, rs.proposal_block)
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            if self.event_bus is not None:
                self.event_bus.publish_lock(rs.round_state_event())
            self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id)
            return

        # polka for a block we don't have: unlock, fetch it, precommit nil
        # (state.go:1477)
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not (
            rs.proposal_block_parts.header == block_id.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet(block_id.part_set_header)
        self._sign_add_vote(SignedMsgType.PRECOMMIT, NIL_BLOCK_ID)

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise ConsensusError("enterPrecommitWait without +2/3 precommits")
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.config.precommit_timeout_ns(round_),
            height,
            round_,
            RoundStep.PRECOMMIT_WAIT,
        )

    # ------------------------------------------------------------------
    # step: Commit
    # ------------------------------------------------------------------

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """Reference enterCommit state.go:1520."""
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        self.logger.debug("enterCommit %d/%d", height, commit_round)
        rs.step = RoundStep.COMMIT
        rs.commit_round = commit_round
        rs.commit_time_ns = self.clock.now_ns()
        self._new_step()

        precommits = rs.votes.precommits(commit_round)
        block_id = precommits.two_thirds_majority()
        if block_id is None or block_id.is_nil():
            raise ConsensusError("enterCommit without +2/3 block precommits")

        # move the locked block to proposal position if it's the one
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            if rs.proposal_block_parts is None or not (
                rs.proposal_block_parts.header == block_id.part_set_header
            ):
                # don't have the block: wait for parts
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet(block_id.part_set_header)
                return
        self._finalize_later()

    def _finalize_later(self) -> None:
        self._finalize_pending = True

    def _materialize_commit(self, precommits):
        """VoteSet -> Commit under the configured wire scheme
        ([consensus] commit_scheme / TMTPU_COMMIT_SCHEME): with
        "bls-aggregate", a BLS validator set's precommit signatures
        fold into one 96-byte aggregate (pure data transformation of
        the gossiped votes — deterministic, so same-seed chaos runs
        produce byte-identical aggregate commits). Any participating
        non-BLS signer falls back to the per-sig form, logged once."""
        import os

        from ..types.block import aggregate_commit

        commit = precommits.make_commit()
        scheme = os.environ.get("TMTPU_COMMIT_SCHEME") or self.config.commit_scheme
        if scheme != "bls-aggregate":
            return commit
        try:
            return aggregate_commit(commit, precommits.val_set)
        except ValueError as e:
            if not self._warned_aggregate_fallback:
                self._warned_aggregate_fallback = True
                self.logger.warning(
                    "commit_scheme=bls-aggregate but commit kept per-sig "
                    "form (%s)", e,
                )
            return commit

    async def _finalize_commit(self) -> None:
        """Reference finalizeCommit state.go:1611 — the only async step
        (ApplyBlock awaits the ABCI app)."""
        rs = self.rs
        if rs.step != RoundStep.COMMIT:
            return
        height = rs.height
        precommits = rs.votes.precommits(rs.commit_round)
        block_id = precommits.two_thirds_majority()
        if block_id is None or block_id.is_nil():
            return
        block, parts = rs.proposal_block, rs.proposal_block_parts
        if block is None or block.hash() != block_id.hash:
            return  # still waiting for the block
        self.block_exec.validate_block(self.state, block)

        # crash matrix points 1-3 mirror the reference's fail.Fail sites
        # around finalizeCommit (state.go:1647-1712)
        fail.fail_point(1)  # before saving the block
        if self.block_store.height() < height:
            seen_commit = self._materialize_commit(precommits)
            self.block_store.save_block(block, parts, seen_commit)
        fail.fail_point(2)  # block saved, before the WAL end-height marker
        # height is durably decided: WAL end-height marker (the blockstore
        # has the block; replay resumes from the next height)
        if self.wal is not None and not self._replay_mode:
            self.wal.write_end_height(height)
        fail.fail_point(3)  # marker written, before ApplyBlock

        state, _ = await self.block_exec.apply_block(self.state, block_id, block)

        if not self._replay_mode:
            ttc = max(0.0, self.clock.monotonic() - self._height_t0)
            self.ttc_hist.observe(ttc)
            trace.emit(
                "consensus", "height", duration_s=ttc, clock=self.clock,
                height=height, round=rs.commit_round,
            )

        # next height
        rs.commit_time_ns = self.clock.now_ns()
        self.update_to_state(state)
        self._decided.set()
        self._decided = asyncio.Event()
        self._schedule_timeout(
            max(0, rs.start_time_ns - self.clock.now_ns()),
            rs.height,
            0,
            RoundStep.NEW_HEIGHT,
        )
        self.logger.info(
            "committed block height=%d hash=%s txs=%d",
            height,
            block_id.hash.hex()[:12],
            len(block.txs),
        )

    async def wait_for_height(self, height: int, timeout: float = 30.0) -> None:
        """Test helper: block until consensus commits `height`."""
        deadline = self.clock.monotonic() + timeout
        while self.rs.height <= height:
            remaining = deadline - self.clock.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"consensus stuck at height {self.rs.height} (wanted > {height})"
                )
            ev = self._decided
            try:
                await asyncio.wait_for(ev.wait(), timeout=min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass

    # ------------------------------------------------------------------
    # votes
    # ------------------------------------------------------------------

    def _try_add_vote(
        self, vote: Vote, peer_id: str, sig_ok: bool | None = None
    ) -> bool:
        """Reference tryAddVote state.go:1961."""
        try:
            return self._add_vote(vote, peer_id, sig_ok=sig_ok)
        except ConflictingVoteError as e:
            if (
                self.priv_validator is not None
                and vote.validator_address
                == self.priv_validator.get_pub_key().address()
            ):
                self.logger.error(
                    "found conflicting vote from ourselves: %s", vote
                )
                return False
            raise

    def _add_vote(
        self, vote: Vote, peer_id: str, sig_ok: bool | None = None
    ) -> bool:
        """Reference addVote state.go:2009 — tallies the vote and drives
        the polka/lock/commit transitions."""
        rs = self.rs

        if sig_ok is False:
            # the ingest pipeline disproved the signature; surface the
            # peer to the reactor (ban/score) and drop like any other
            # invalid input
            if self.invalid_sig_hook is not None and peer_id:
                self.invalid_sig_hook(peer_id, vote)
            raise VoteSetError(
                f"invalid signature from validator {vote.validator_index} "
                f"(disproven by pipelined ingest)"
            )
        verified = sig_ok is True

        # A precommit for the previous height (LastCommit straggler)
        if (
            vote.height + 1 == rs.height
            and vote.type == SignedMsgType.PRECOMMIT
        ):
            if rs.step != RoundStep.NEW_HEIGHT or rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote, verified=verified)
            if added:
                self._publish_vote(vote)
                if self.config.skip_timeout_commit and rs.last_commit.has_all():
                    self._enter_new_round(rs.height, 0)
            return added

        if vote.height != rs.height:
            return False

        added = rs.votes.add_vote(vote, peer_id, verified=verified)
        if not added:
            return False
        self._publish_vote(vote)
        self._broadcast(
            m.HasVoteMessage(vote.height, vote.round, vote.type, vote.validator_index)
        )

        if vote.type == SignedMsgType.PREVOTE:
            self._handle_prevote_added(vote)
        elif vote.type == SignedMsgType.PRECOMMIT:
            self._handle_precommit_added(vote)
        return True

    def _publish_vote(self, vote: Vote) -> None:
        if self.event_bus is not None:
            self.event_bus.publish_vote(EventDataVote(vote))

    def _handle_prevote_added(self, vote: Vote) -> None:
        """state.go:2095-2186 (prevote section of addVote)."""
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        block_id = prevotes.two_thirds_majority()
        if block_id is not None:
            # unlock on a later polka for a different block (state.go:2112)
            if (
                rs.locked_block is not None
                and rs.locked_round < vote.round <= rs.round
                and rs.locked_block.hash() != block_id.hash
            ):
                self.logger.info("unlocking: polka for different block at round %d", vote.round)
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                if self.event_bus is not None:
                    self.event_bus.publish_unlock(rs.round_state_event())
            # valid-block update (state.go:2133)
            if (
                not block_id.is_nil()
                and rs.valid_round < vote.round
                and vote.round == rs.round
            ):
                if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    # polka for a block we don't have yet: start collecting it
                    rs.proposal_block = None
                    if rs.proposal_block_parts is None or not (
                        rs.proposal_block_parts.header == block_id.part_set_header
                    ):
                        rs.proposal_block_parts = PartSet(block_id.part_set_header)
                self._broadcast(
                    m.NewValidBlockMessage(
                        rs.height,
                        rs.round,
                        (block_id.part_set_header.total, block_id.part_set_header.hash),
                        rs.proposal_block_parts.parts_bit_array.copy(),
                        False,
                    )
                )

        # step transitions (the switch at state.go:2161)
        if rs.round < vote.round and prevotes.has_two_thirds_any():
            # round skip: +2/3 of any prevotes in a future round
            self._enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and rs.step >= RoundStep.PREVOTE:
            if block_id is not None and (
                self._is_proposal_complete() or block_id.is_nil()
            ):
                self._enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any():
                self._enter_prevote_wait(rs.height, vote.round)
        elif (
            rs.proposal is not None
            and 0 <= rs.proposal.pol_round == vote.round
        ):
            # the proposal's POL just completed: we can now prevote
            if self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)

    def _handle_precommit_added(self, vote: Vote) -> None:
        """state.go:2188-2230 (precommit section of addVote)."""
        rs = self.rs
        precommits = rs.votes.precommits(vote.round)
        block_id = precommits.two_thirds_majority()
        if block_id is not None:
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit(rs.height, vote.round)
            if not block_id.is_nil():
                self._enter_commit(rs.height, vote.round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    self._enter_new_round(rs.height, 0)
            else:
                self._enter_precommit_wait(rs.height, vote.round)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit_wait(rs.height, vote.round)

    # ------------------------------------------------------------------
    # vote signing
    # ------------------------------------------------------------------

    def _vote_time_ns(self) -> int:
        """Monotonic vote time ≥ last block time + 1ms (reference
        voteTime state.go:2237)."""
        now = self.clock.now_ns()
        minimum = 0
        if self.rs.locked_block is not None:
            minimum = self.rs.locked_block.header.time_ns + 1_000_000
        elif self.rs.proposal_block is not None:
            minimum = self.rs.proposal_block.header.time_ns + 1_000_000
        return max(now, minimum)

    def _sign_add_vote(self, type_: SignedMsgType, block_id: BlockID) -> None:
        """Reference signAddVote state.go:2262. The unsigned vote is built
        synchronously (height/round/time are snapshotted here); the privval
        signature itself is produced off-loop via the signing queue."""
        if self._replay_mode:
            return
        if self.priv_validator is None:
            return
        pub = self.priv_validator.get_pub_key()
        addr = pub.address()
        idx, val = self.rs.validators.get_by_address(addr)
        if val is None:
            return  # not a validator
        vote = Vote(
            type=type_,
            height=self.rs.height,
            round=self.rs.round,
            block_id=block_id,
            timestamp_ns=self._vote_time_ns(),
            validator_address=addr,
            validator_index=idx,
        )

        def on_signed(signed: Vote) -> None:
            self._send_internal(MsgInfo(m.VoteMessage(signed)))
            self._broadcast(m.VoteMessage(signed))

        self._queue_signing(
            lambda: self.priv_validator.sign_vote(self.state.chain_id, vote),
            on_signed,
            "vote",
        )
