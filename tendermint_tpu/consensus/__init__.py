"""Consensus layer (reference internal/consensus/)."""

from .replay import AppHashMismatchError, Handshaker, HandshakeError
from .wal import WAL, WALRecord, KIND_END_HEIGHT, KIND_MESSAGE

__all__ = [
    "AppHashMismatchError",
    "Handshaker",
    "HandshakeError",
    "WAL",
    "WALRecord",
    "KIND_END_HEIGHT",
    "KIND_MESSAGE",
]
