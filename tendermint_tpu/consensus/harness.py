"""In-process consensus test network (the analog of the reference's
internal/consensus/common_test.go fixtures): N ConsensusStates wired
directly to each other's input queues through their broadcast hooks — no
sockets, whole consensus protocol exercised in one event loop. The real
p2p reactor replaces the hook wiring in production."""

from __future__ import annotations

import asyncio
import logging
import tempfile

from ..abci.kvstore import KVStoreApp
from ..config import ConsensusConfig, MempoolConfig
from ..consensus import messages as m
from ..consensus.replay import Handshaker
from ..consensus.state import ConsensusState
from ..consensus.wal import WAL
from ..evidence.pool import EvidencePool
from ..mempool.pool import PriorityMempool
from ..privval import MockPV
from ..proxy import AppConns
from ..state.execution import BlockExecutor
from ..state.state import state_from_genesis
from ..state.store import StateStore
from ..store.blockstore import BlockStore
from ..store.db import MemDB
from ..testing import det_priv_keys
from ..types.events import EventBus
from ..types.genesis import GenesisDoc, GenesisValidator

MS = 1_000_000

# every harness genesis is stamped here; chaos runs park a frozen
# ManualClock at/behind it so the vote-time floor pins all timestamps
GENESIS_TIME_NS = 1_700_000_000_000_000_000


async def _deliver_after(delay: float, coro) -> None:
    try:
        await asyncio.sleep(delay)
    except asyncio.CancelledError:
        coro.close()  # net.stop() mid-delay: don't leak an un-awaited coro
        raise
    await coro


def fast_config() -> ConsensusConfig:
    """Short timeouts so multi-round tests finish quickly."""
    return ConsensusConfig(
        timeout_propose_ns=400 * MS,
        timeout_propose_delta_ns=200 * MS,
        timeout_prevote_ns=200 * MS,
        timeout_prevote_delta_ns=200 * MS,
        timeout_precommit_ns=200 * MS,
        timeout_precommit_delta_ns=200 * MS,
        timeout_commit_ns=80 * MS,
        skip_timeout_commit=True,
    )


def make_genesis(
    n_vals: int, chain_id: str = "test-chain", key_type: str = "ed25519"
) -> tuple[GenesisDoc, list]:
    if key_type == "ed25519":
        keys = det_priv_keys(n_vals)
    elif key_type == "bls12381":
        import hashlib

        from ..crypto.bls import BLSPrivKey

        keys = [
            BLSPrivKey(
                hashlib.sha256(
                    b"tmtpu-test" + key_type.encode() + i.to_bytes(4, "big")
                ).digest()
            )
            for i in range(n_vals)
        ]
    else:
        raise ValueError(f"unsupported harness key type {key_type}")
    gvals = [
        GenesisValidator(
            k.pub_key(),
            10,
            f"val{i}",
            pop=k.pop_prove() if key_type == "bls12381" else b"",
        )
        for i, k in enumerate(keys)
    ]
    doc = GenesisDoc(
        chain_id=chain_id,
        initial_height=1,
        genesis_time_ns=GENESIS_TIME_NS,
        validators=gvals,
    )
    return doc, keys


class Node:
    """One in-process validator: app + stores + executor + consensus SM."""

    def __init__(
        self,
        genesis: GenesisDoc,
        priv_key,
        *,
        config: ConsensusConfig | None = None,
        wal_dir: str | None = None,
        app=None,
        fs=None,  # libs/chaosfs.FS — storage fault injection for the WAL
        clock=None,  # libs/clock.Clock — injectable consensus time
        block_store=None,  # reuse across crash/restart cycles (RouterNet)
        state_store=None,
    ):
        self.genesis = genesis
        self.config = config or fast_config()
        self.app = app or KVStoreApp()
        self.app_conns = AppConns.local(self.app)
        self.block_store = block_store or BlockStore(MemDB())
        self.state_store = state_store or StateStore(MemDB())
        self.event_bus = EventBus()
        self.priv_val = MockPV(priv_key) if priv_key is not None else None
        self.clock = clock
        self.fs = fs
        self.wal_dir = wal_dir or tempfile.mkdtemp(prefix="cswal-")
        self.wal = WAL(self.wal_dir, fs=fs)
        self.mempool: PriorityMempool | None = None
        self.evidence_pool: EvidencePool | None = None
        self.cs: ConsensusState | None = None

    async def start(self, *, start_consensus: bool = True) -> None:
        """Build the stack and (by default) start the consensus SM.
        `start_consensus=False` leaves `self.cs` built but not running —
        RouterNet attaches the ConsensusReactor's hooks first, exactly
        like node.py starts the reactor before the SM, so the first
        proposal broadcast is not lost."""
        state = self.state_store.load()
        if state is None:
            state = state_from_genesis(self.genesis)
        handshaker = Handshaker(
            self.state_store, state, self.block_store, self.genesis
        )
        state = await handshaker.handshake(self.app_conns)
        self.state_store.save(state)
        self.mempool = PriorityMempool(
            MempoolConfig(), self.app_conns.mempool, height=state.last_block_height
        )
        self.evidence_pool = EvidencePool(
            MemDB(), self.state_store, self.block_store
        )
        block_exec = BlockExecutor(
            self.state_store,
            self.app_conns.consensus,
            mempool=self.mempool,
            evidence_pool=self.evidence_pool,
            block_store=self.block_store,
            event_bus=self.event_bus,
        )
        self.cs = ConsensusState(
            self.config,
            state,
            block_exec,
            self.block_store,
            priv_validator=self.priv_val,
            evidence_pool=self.evidence_pool,
            wal=self.wal,
            event_bus=self.event_bus,
            mempool=self.mempool,
            clock=self.clock,
        )
        if start_consensus:
            await self.cs.start()

    async def stop(self) -> None:
        if self.cs is not None:
            await self.cs.stop()
        if self.mempool is not None:
            self.mempool.close()  # out of the process-wide metrics fold
        await self.app_conns.stop()


class LocalNetwork:
    """N validator nodes with broadcast hooks delivering every outbound
    consensus message to every other node's peer queue.

    `chaos` (libs/chaos.ChaosNetwork) threads the fault plan under the
    hook wiring — drops, asymmetric partitions, delays, reorders, and
    duplicates apply per (sender→receiver) link; node ids are
    "node0".."nodeN-1". Corruption and bandwidth shaping are
    byte-stream faults the typed-message hooks cannot model — the
    constructor REJECTS configs that set their rates (the fault
    counters would report injections the hook never performed); run
    those classes over consensus.routernet.RouterNet, which speaks the
    real router + ChaosTransport byte path. When the chaos config carries
    `clock_skew_ms`, each validator runs on its own deterministically
    skewed clock (over `base_clock` if given — a frozen `ManualClock`
    base makes the whole run's vote/block timestamps
    bit-reproducible)."""

    def __init__(
        self,
        n_vals: int,
        *,
        config: ConsensusConfig | None = None,
        chaos=None,
        base_clock=None,
        catchup: bool = True,
        key_type: str = "ed25519",
    ):
        if chaos is not None:
            # byte-stream fault classes the typed hooks can NEVER inject:
            # accepting them here would still bump the `corrupt`/`shaped`
            # fault counters in ChaosNetwork.plan while no corruption or
            # shaping ever happens — a chaos matrix that silently lies
            # about its own coverage. Fail loud; RouterNet
            # (consensus/routernet.py) runs those classes over the real
            # router + ChaosTransport byte path.
            cfgs = [chaos.config, *chaos.config.per_channel.values()]
            bad = sorted(
                {
                    name
                    for cfg in cfgs
                    for name in ("corrupt_rate", "bandwidth_rate")
                    if getattr(cfg, name)
                }
            )
            if bad:
                raise ValueError(
                    f"LocalNetwork cannot model byte-stream faults {bad}: "
                    "the typed broadcast hooks never serialize messages, so "
                    "those injections would be counted but never performed. "
                    "Use consensus.routernet.RouterNet (real p2p.Router + "
                    "ChaosTransport) for corruption/bandwidth chaos."
                )
        self.genesis, self.keys = make_genesis(n_vals, key_type=key_type)
        self.chaos = chaos
        self.catchup = catchup
        self.catchup_rescues = 0
        clocks = [base_clock] * n_vals
        if chaos is not None:
            clocks = [
                chaos.clock_for(f"node{i}", base=base_clock)
                for i in range(n_vals)
            ]
        self.nodes = [
            Node(self.genesis, k, config=config, clock=clocks[i])
            for i, k in enumerate(self.keys)
        ]
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        for node in self.nodes:
            await node.start()
        for i, node in enumerate(self.nodes):
            node.cs.broadcast_hook = self._make_hook(i)
        if self.catchup:
            self._tasks.append(
                asyncio.get_running_loop().create_task(
                    self._catchup_relay(), name="harness.catchup"
                )
            )

    def _make_hook(self, sender: int):
        def hook(msg):
            for j, other in enumerate(self.nodes):
                if j == sender or other.cs is None:
                    continue
                mi = self._to_input(msg)
                if mi is None:
                    continue
                kind, args = mi
                delay, copies = 0.0, 1
                if self.chaos is not None:
                    plan = self.chaos.plan(f"node{sender}", f"node{j}", 0)
                    if plan.drop:
                        continue
                    # reorder = extra delay pushing past successors, as
                    # in ChaosConnection.send_message
                    delay = plan.delay_s + (0.05 if plan.reorder else 0.0)
                    copies = 2 if plan.duplicate else 1
                for _ in range(copies):
                    coro = getattr(other.cs, kind)(*args, f"node{sender}")
                    if delay > 0:
                        coro = _deliver_after(delay, coro)
                    self._tasks.append(
                        asyncio.get_running_loop().create_task(coro)
                    )

        return hook

    @staticmethod
    def _to_input(msg):
        if isinstance(msg, m.ProposalMessage):
            return "add_proposal", (msg.proposal,)
        if isinstance(msg, m.BlockPartMessage):
            return "add_block_part", (msg.height, msg.round, msg.part)
        if isinstance(msg, m.VoteMessage):
            return "add_vote", (msg.vote,)
        return None  # HasVote / NewValidBlock are gossip hints; no-op here

    async def _catchup_relay(self) -> None:
        """Minimal stand-in for the consensus reactor's catch-up gossip /
        block-sync rescue (ROADMAP gap): a receiver that missed a
        decided height's proposal — e.g. the victim of a one-way
        partition whose only proposer view was the cut link — gets the
        stored commit's precommits and the block parts replayed from any
        node that already committed that height. Production nodes get
        this from `_send_catchup_commit_vote` + part gossip over real
        routers; without it the direct-hook harness can wedge forever.
        The relay deliberately ignores the chaos fault plan: it models
        the out-of-band block-sync path, not the vote-gossip links the
        chaos layer is partitioning."""
        from ..types.keys import SignedMsgType
        from ..types.vote import Vote

        while True:
            await asyncio.sleep(0.25)
            for node in self.nodes:
                cs = node.cs
                if cs is None or not cs.is_running:
                    continue
                h = cs.rs.height
                donor = next(
                    (
                        d
                        for d in self.nodes
                        if d is not node
                        and d.cs is not None
                        and d.block_store.height() >= h
                    ),
                    None,
                )
                if donor is None:
                    continue  # nobody has committed this height yet
                # canonical commit (from block h+1) when the chain moved
                # on, else the donor's own seen commit for its tip
                commit = donor.block_store.load_block_commit(
                    h
                ) or donor.block_store.load_seen_commit(h)
                meta = donor.block_store.load_block_meta(h)
                if commit is None or meta is None:
                    continue
                self.catchup_rescues += 1
                # open the commit round (the real VoteSetMaj23 exchange
                # does this) so precommits beyond round+1 are admitted
                if cs.rs.height == h and cs.rs.votes is not None:
                    cs.rs.votes.set_peer_maj23(
                        commit.round,
                        SignedMsgType.PRECOMMIT,
                        "catchup-relay",
                        commit.block_id,
                    )
                # precommits first: +2/3 moves the receiver to COMMIT and
                # arms a PartSet for the decided block id …
                for idx, cs_sig in enumerate(commit.signatures):
                    if cs_sig.is_absent():
                        continue
                    vote = Vote(
                        type=SignedMsgType.PRECOMMIT,
                        height=commit.height,
                        round=commit.round,
                        block_id=cs_sig.block_id(commit.block_id),
                        timestamp_ns=cs_sig.timestamp_ns,
                        validator_address=cs_sig.validator_address,
                        validator_index=idx,
                        signature=cs_sig.signature,
                    )
                    await cs.add_vote(vote, "catchup-relay")
                # … then the parts complete the block and finalize fires
                for idx in range(meta.block_id.part_set_header.total):
                    part = donor.block_store.load_block_part(h, idx)
                    if part is not None:
                        await cs.add_block_part(
                            h, commit.round, part, "catchup-relay"
                        )

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for node in self.nodes:
            await node.stop()

    async def wait_for_height(self, height: int, timeout: float = 30.0) -> None:
        await asyncio.gather(
            *(n.cs.wait_for_height(height, timeout) for n in self.nodes)
        )
