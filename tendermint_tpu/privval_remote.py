"""Remote signer: a validator's key isolated in its own process
(reference privval/signer_listener_endpoint.go:30 + signer_client.go:17).

`SignerServer` runs beside the key (wrapping a FilePV) and serves signing
requests over TCP; `SignerClient` implements the PrivValidator interface
inside the node. The consensus state machine signs synchronously, so the
client keeps a blocking socket guarded by a lock with a per-request
deadline, and transparently reconnects with retries (the analog of
RetrySignerClient, privval/retry_signer_client.go).

Wire: 4-byte BE length + protoenc body.
  1 PubKeyRequest    {}                    → 2 PubKeyResponse {pub_key}
  3 SignVoteRequest  {chain_id, vote}      → 4 SignedVoteResponse {vote | err}
  5 SignProposalReq  {chain_id, proposal}  → 6 SignedProposalResponse {…}
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
import threading
import time

from .crypto import ed25519
from .libs import protoenc as pe
from .privval import DoubleSignError, PrivValidator
from .types.vote import Proposal, Vote

_LEN = struct.Struct(">I")

T_PUBKEY_REQ = 1
T_PUBKEY_RES = 2
T_SIGN_VOTE_REQ = 3
T_SIGN_VOTE_RES = 4
T_SIGN_PROPOSAL_REQ = 5
T_SIGN_PROPOSAL_RES = 6


def _encode(tag: int, body: bytes) -> bytes:
    payload = pe.message_field(tag, body)
    return _LEN.pack(len(payload)) + payload


def _decode(payload: bytes) -> tuple[int, bytes]:
    r = pe.Reader(payload)
    tag, _wt = r.read_tag()
    return tag, r.read_bytes()


class RemoteSignerError(RuntimeError):
    pass


class SignerServer:
    """Serves a PrivValidator over TCP (reference
    privval/signer_server.go / signer_dialer_endpoint)."""

    def __init__(self, pv: PrivValidator, *, logger: logging.Logger | None = None):
        self.pv = pv
        self.logger = logger or logging.getLogger("signer.server")
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None
        self._writers: set[asyncio.StreamWriter] = set()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._server = await asyncio.start_server(self._serve, host, port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._writers.add(writer)
        try:
            while True:
                hdr = await reader.readexactly(_LEN.size)
                (n,) = _LEN.unpack(hdr)
                tag, body = _decode(await reader.readexactly(n))
                writer.write(self._handle(tag, body))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    def _handle(self, tag: int, body: bytes) -> bytes:
        return _encode(*handle_signer_request(self.pv, tag, body))


def handle_signer_request(
    pv: PrivValidator, tag: int, body: bytes
) -> tuple[int, bytes]:
    """Transport-independent signer dispatch: (request tag, body) →
    (response tag, body). Shared by the socket and gRPC servers so the
    two attachment modes answer identically."""
    if tag == T_PUBKEY_REQ:
        # typed PublicKey proto, not raw bytes: remote signers may hold
        # non-ed25519 keys (reference privval proto carries the oneof)
        from .crypto import pubkey_to_proto

        return T_PUBKEY_RES, pe.bytes_field(1, pubkey_to_proto(pv.get_pub_key()))
    if tag in (T_SIGN_VOTE_REQ, T_SIGN_PROPOSAL_REQ):
        r = pe.Reader(body)
        chain_id, raw = "", b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                chain_id = r.read_string()
            elif f == 2:
                raw = r.read_bytes()
            else:
                r.skip(wt)
        res_tag = T_SIGN_VOTE_RES if tag == T_SIGN_VOTE_REQ else T_SIGN_PROPOSAL_RES
        try:
            if tag == T_SIGN_VOTE_REQ:
                signed = pv.sign_vote(chain_id, Vote.decode(raw))
            else:
                signed = pv.sign_proposal(chain_id, Proposal.decode(raw))
            return res_tag, pe.bytes_field(1, signed.encode())
        except DoubleSignError as e:
            return res_tag, pe.string_field(2, str(e))
    return tag + 1, pe.string_field(2, f"unknown request {tag}")


class ThreadedSignerServer:
    """Run a SignerServer on its own thread + event loop. The production
    deployment is a separate process; in-process embedding (tests, the
    CLI's one-machine mode) must NOT share the node's loop because the
    consensus-side SignerClient blocks its calling thread while waiting
    for the signature (matching the reference's synchronous signing
    path)."""

    def __init__(self, pv: PrivValidator):
        self.pv = pv
        self.port: int | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server: SignerServer | None = None
        self._thread: threading.Thread | None = None
        self._ready = threading.Event()

    def start(self) -> int:
        self._thread = threading.Thread(target=self._run, daemon=True, name="signer")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RemoteSignerError("signer server failed to start")
        return self.port

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def main():
            self._server = SignerServer(self.pv)
            await self._server.start()
            self.port = self._server.port
            self._ready.set()
            await asyncio.Event().wait()  # run until loop is stopped

        try:
            self._loop.run_until_complete(main())
        except RuntimeError:
            pass  # loop stopped

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)


class SignerClient(PrivValidator):
    """PrivValidator backed by a remote signer (reference
    signer_client.go:17 with retry_signer_client.go semantics)."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        timeout: float = 3.0,
        retries: int = 3,
        logger: logging.Logger | None = None,
    ):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.retries = retries
        self.logger = logger or logging.getLogger("signer.client")
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()
        self._pub_key: ed25519.Ed25519PubKey | None = None

    # -- transport -------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        s = socket.create_connection((self.host, self.port), timeout=self.timeout)
        s.settimeout(self.timeout)
        self._sock = s
        return s

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip(self, tag: int, body: bytes) -> tuple[int, bytes]:
        last: Exception | None = None
        for attempt in range(self.retries):
            try:
                with self._lock:
                    s = self._connect()
                    s.sendall(_encode(tag, body))
                    hdr = self._recv_exact(s, _LEN.size)
                    (n,) = _LEN.unpack(hdr)
                    return _decode(self._recv_exact(s, n))
            except (OSError, ConnectionError) as e:
                last = e
                self._drop()
                time.sleep(min(0.1 * (2**attempt), 1.0))
        raise RemoteSignerError(f"remote signer unreachable: {last!r}")

    def _recv_exact(self, s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("signer closed connection")
            buf += chunk
        return buf

    @staticmethod
    def _parse_signed(body: bytes) -> tuple[bytes, str]:
        r = pe.Reader(body)
        raw, err = b"", ""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                raw = r.read_bytes()
            elif f == 2:
                err = r.read_string()
            else:
                r.skip(wt)
        return raw, err

    # -- PrivValidator ---------------------------------------------------

    def get_pub_key(self):
        if self._pub_key is None:
            from .crypto import pubkey_from_proto

            tag, body = self._roundtrip(T_PUBKEY_REQ, b"")
            raw, _err = self._parse_signed(body)
            self._pub_key = pubkey_from_proto(raw)
        return self._pub_key

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        body = pe.string_field(1, chain_id) + pe.bytes_field(2, vote.encode())
        _tag, res = self._roundtrip(T_SIGN_VOTE_REQ, body)
        raw, err = self._parse_signed(res)
        if err:
            raise DoubleSignError(err)
        return Vote.decode(raw)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        body = pe.string_field(1, chain_id) + pe.bytes_field(2, proposal.encode())
        _tag, res = self._roundtrip(T_SIGN_PROPOSAL_REQ, body)
        raw, err = self._parse_signed(res)
        if err:
            raise DoubleSignError(err)
        return Proposal.decode(raw)


# -- gRPC attachment mode (reference privval/grpc/{server,client}.go) -------

GRPC_SIGNER_SERVICE = "tendermint.privval.PrivValidatorAPI"
_GRPC_METHOD_TAGS = {
    "GetPubKey": T_PUBKEY_REQ,
    "SignVote": T_SIGN_VOTE_REQ,
    "SignProposal": T_SIGN_PROPOSAL_REQ,
}


class GrpcSignerServer:
    """Serves a PrivValidator over gRPC (reference privval/grpc/server.go:1).
    Payload bodies are the same protoenc encodings as the socket protocol
    (handle_signer_request), so the two modes answer identically; gRPC
    provides the framing, deadlines, and connection management."""

    def __init__(self, pv: PrivValidator):
        self.pv = pv
        self._server = None
        self.port: int | None = None

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        from concurrent import futures

        import grpc

        # one worker: the reference serializes signing (the double-sign
        # guard mutates last-sign state; concurrent signs must not race)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=1))

        def make_handler(tag: int):
            def handle(request: bytes, context) -> bytes:
                res_tag, body = handle_signer_request(self.pv, tag, request)
                return pe.message_field(res_tag, body)

            return handle

        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                make_handler(tag),
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
            for name, tag in _GRPC_METHOD_TAGS.items()
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(GRPC_SIGNER_SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)


class GrpcSignerClient(PrivValidator):
    """PrivValidator over a blocking gRPC channel (reference
    privval/grpc/client.go:1). Consensus signs synchronously, so the
    sync API is the right shape — no event-loop involvement."""

    def __init__(self, host: str, port: int, *, timeout: float = 3.0):
        import grpc

        self.timeout = timeout
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._stubs = {
            name: self._channel.unary_unary(
                f"/{GRPC_SIGNER_SERVICE}/{name}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            for name in _GRPC_METHOD_TAGS
        }
        self._pub_key: ed25519.Ed25519PubKey | None = None

    def close(self) -> None:
        self._channel.close()

    def _roundtrip(self, method: str, body: bytes) -> bytes:
        payload = self._stubs[method](body, timeout=self.timeout)
        _tag, res = _decode(payload)
        return res

    def get_pub_key(self):
        if self._pub_key is None:
            from .crypto import pubkey_from_proto

            raw, _err = SignerClient._parse_signed(
                self._roundtrip("GetPubKey", b"")
            )
            self._pub_key = pubkey_from_proto(raw)
        return self._pub_key

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        body = pe.string_field(1, chain_id) + pe.bytes_field(2, vote.encode())
        raw, err = SignerClient._parse_signed(self._roundtrip("SignVote", body))
        if err:
            raise DoubleSignError(err)
        return Vote.decode(raw)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        body = pe.string_field(1, chain_id) + pe.bytes_field(2, proposal.encode())
        raw, err = SignerClient._parse_signed(
            self._roundtrip("SignProposal", body)
        )
        if err:
            raise DoubleSignError(err)
        return Proposal.decode(raw)
