"""Block sync — fast replay of committed blocks from peers (reference
internal/blocksync/v0/; channel 0x40).

Restructured for the TPU: the reference's one-block-at-a-time
poolRoutine (reactor.go:439) becomes a fetch → sign-bytes → range-batch
verify → apply pipeline, where a whole window of commits is verified in
one batched kernel call (types/validation.verify_commit_range)."""

BLOCKSYNC_CHANNEL = 0x40
