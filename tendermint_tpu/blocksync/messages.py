"""Blocksync wire messages (reference proto/tendermint/blocksync)."""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protoenc as pe
from ..types.block import Block

T_BLOCK_REQUEST = 1
T_NO_BLOCK_RESPONSE = 2
T_BLOCK_RESPONSE = 3
T_STATUS_REQUEST = 4
T_STATUS_RESPONSE = 5


@dataclass(frozen=True)
class BlockRequest:
    height: int


@dataclass(frozen=True)
class NoBlockResponse:
    height: int


@dataclass(frozen=True)
class BlockResponse:
    block: Block


@dataclass(frozen=True)
class StatusRequest:
    pass


@dataclass(frozen=True)
class StatusResponse:
    height: int
    base: int


Message = BlockRequest | NoBlockResponse | BlockResponse | StatusRequest | StatusResponse


def encode_message(msg: Message) -> bytes:
    if isinstance(msg, BlockRequest):
        return pe.message_field(T_BLOCK_REQUEST, pe.varint_field(1, msg.height))
    if isinstance(msg, NoBlockResponse):
        return pe.message_field(T_NO_BLOCK_RESPONSE, pe.varint_field(1, msg.height))
    if isinstance(msg, BlockResponse):
        return pe.message_field(T_BLOCK_RESPONSE, msg.block.encode())
    if isinstance(msg, StatusRequest):
        return pe.message_field(T_STATUS_REQUEST, b"")
    if isinstance(msg, StatusResponse):
        return pe.message_field(
            T_STATUS_RESPONSE,
            pe.varint_field(1, msg.height) + pe.varint_field(2, msg.base),
        )
    raise TypeError(f"unknown blocksync message {type(msg)}")


def decode_message(data: bytes) -> Message:
    r = pe.Reader(data)
    f, _wt = r.read_tag()
    body = r.read_bytes()
    if f == T_BLOCK_REQUEST or f == T_NO_BLOCK_RESPONSE:
        br = pe.Reader(body)
        height = 0
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                height = br.read_uvarint()
            else:
                br.skip(bwt)
        return BlockRequest(height) if f == T_BLOCK_REQUEST else NoBlockResponse(height)
    if f == T_BLOCK_RESPONSE:
        return BlockResponse(Block.decode(body))
    if f == T_STATUS_REQUEST:
        return StatusRequest()
    if f == T_STATUS_RESPONSE:
        br = pe.Reader(body)
        height = base = 0
        while not br.eof():
            bf, bwt = br.read_tag()
            if bf == 1:
                height = br.read_uvarint()
            elif bf == 2:
                base = br.read_uvarint()
            else:
                br.skip(bwt)
        return StatusResponse(height, base)
    raise ValueError(f"unknown blocksync tag {f}")
