"""Block pool — schedules concurrent block requests across peers and
hands back blocks in height order (reference internal/blocksync/v0/pool.go:69:
up to 600 in-flight requesters, ≤20 per peer).

`next_requests()` yields (height, peer) assignments; the reactor sends
BlockRequests and feeds responses back via `add_block`. `peek_range`
returns the contiguous run of downloaded blocks starting at `height` —
the unit the reactor feeds to the range-batched verifier.

Resilience: request timeouts are ADAPTIVE per peer — a Jacobson/Karels
RTO (srtt + 4·rttvar, clamped) learned from observed block-response
RTTs, so a fast in-memory peer is re-tried in milliseconds while a slow
WAN peer isn't spuriously timed out. Repeated consecutive timeouts ban
the peer (the reactor drains `take_banned()` and reports a fatal
PeerError) instead of the old single-counter bookkeeping that never
acted on anything."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..libs.clock import SYSTEM, Clock
from ..types.block import Block

REQUEST_WINDOW = 128  # in-flight heights (reference: 600)
PER_PEER_LIMIT = 16  # reference maxPendingRequestsPerPeer=20
REQUEST_TIMEOUT = 15.0  # RTO ceiling (the old fixed timeout)
INITIAL_REQUEST_TIMEOUT = 2.0  # cold-start RTO before any RTT sample (à la TCP)
MIN_REQUEST_TIMEOUT = 0.25  # RTO floor: don't hammer sub-ms in-memory links
BAN_AFTER_TIMEOUTS = 5  # consecutive timeouts before a peer is banned
BAN_COOLDOWN = 30.0  # quarantine; after this the peer may re-register


@dataclass
class _Peer:
    peer_id: str
    base: int = 0
    height: int = 0
    pending: set[int] = field(default_factory=set)
    timeouts: int = 0  # consecutive request timeouts (reset by any block)
    total_timeouts: int = 0
    blocks_served: int = 0
    srtt: float = 0.0  # smoothed RTT, 0 = no sample yet
    rttvar: float = 0.0

    def observe_rtt(self, rtt: float) -> None:
        """Jacobson/Karels (RFC 6298 §2) smoothing."""
        if self.srtt == 0.0:
            self.srtt = rtt
            self.rttvar = rtt / 2
        else:
            self.rttvar = 0.75 * self.rttvar + 0.25 * abs(self.srtt - rtt)
            self.srtt = 0.875 * self.srtt + 0.125 * rtt
        self.timeouts = 0
        self.blocks_served += 1

    def request_timeout(self) -> float:
        """Adaptive RTO; doubles per consecutive timeout (classic RTO
        backoff) so a degraded peer is probed, not flooded."""
        if self.srtt == 0.0:
            rto = INITIAL_REQUEST_TIMEOUT
        else:
            rto = min(
                max(self.srtt + 4 * self.rttvar, MIN_REQUEST_TIMEOUT),
                REQUEST_TIMEOUT,
            )
        return min(rto * (2**self.timeouts), REQUEST_TIMEOUT)

    def health(self) -> float:
        """Scheduling score, lower = better: load + timeout penalty +
        latency. Drives _pick_peer away from degraded peers before the
        ban threshold is reached."""
        return len(self.pending) + 4.0 * self.timeouts + self.srtt


@dataclass
class _Request:
    height: int
    peer_id: str
    time: float


class BlockPool:
    def __init__(
        self,
        start_height: int,
        *,
        clock: Clock | None = None,
        logger: logging.Logger | None = None,
    ):
        self.height = start_height  # next height to hand to the verifier
        self.logger = logger or logging.getLogger("blockpool")
        # duration domain only (RTO samples, ban cooldowns, grace
        # windows) — never stamped into protocol output; injectable so
        # chaos clock drift skews this node's timeout bookkeeping too
        self._clock = clock or SYSTEM
        self.peers: dict[str, _Peer] = {}
        self.requests: dict[int, _Request] = {}  # height -> outstanding req
        self.blocks: dict[int, tuple[Block, str]] = {}  # height -> (block, provider)
        self.started_at = self._clock.monotonic()
        self._last_advance = self._clock.monotonic()
        # when the peer set last BECAME empty — the zero-peer caught-up
        # grace measures from here, not from pool start, so a transient
        # total peer loss mid-sync doesn't instantly report caught-up
        self._no_peers_since = self._clock.monotonic()
        self._banned: list[str] = []  # drained by the reactor (take_banned)
        # quarantine expiry per banned peer: a TIMED ban, not a permanent
        # one — transient total-loss events (a partition) must not strand
        # the node with an empty peer set after the net heals
        self._ban_until: dict[str, float] = {}

    # -- peers -----------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        if self._clock.monotonic() < self._ban_until.get(peer_id, 0.0):
            return
        p = self.peers.setdefault(peer_id, _Peer(peer_id))
        p.base, p.height = base, height

    def remove_peer(self, peer_id: str) -> list[int]:
        """Returns heights that must be re-requested."""
        p = self.peers.pop(peer_id, None)
        if p is None:
            return []
        if not self.peers:
            self._no_peers_since = self._clock.monotonic()
        redo = []
        for h in list(p.pending):
            self.requests.pop(h, None)
            if h not in self.blocks:
                redo.append(h)
        return redo

    def take_banned(self) -> list[str]:
        """Peers banned since the last call (for the reactor to report)."""
        out, self._banned = self._banned, []
        return out

    def _ban(self, peer: _Peer) -> None:
        self.logger.info(
            "banning peer %s after %d consecutive request timeouts",
            peer.peer_id[:12],
            peer.timeouts,
        )
        self._ban_until[peer.peer_id] = self._clock.monotonic() + BAN_COOLDOWN
        self._banned.append(peer.peer_id)
        self.remove_peer(peer.peer_id)

    def max_peer_height(self) -> int:
        return max((p.height for p in self.peers.values()), default=0)

    # -- request scheduling ---------------------------------------------

    def next_requests(self) -> list[tuple[int, str]]:
        """Assign un-requested heights within the window to peers with
        capacity (reference makeNextRequests pool.go:394)."""
        out = []
        now = self._clock.monotonic()
        # retry timed-out requests first (per-peer adaptive RTO)
        for h, req in list(self.requests.items()):
            if h in self.blocks:
                continue
            p = self.peers.get(req.peer_id)
            timeout = p.request_timeout() if p is not None else REQUEST_TIMEOUT
            if now - req.time > timeout:
                if p is not None:
                    p.pending.discard(h)
                    p.timeouts += 1
                    p.total_timeouts += 1
                    if p.timeouts >= BAN_AFTER_TIMEOUTS:
                        self._ban(p)  # also clears the peer's requests
                self.requests.pop(h, None)
        for h in range(self.height, self.height + REQUEST_WINDOW):
            if h in self.blocks or h in self.requests:
                continue
            peer = self._pick_peer(h)
            if peer is None:
                continue
            peer.pending.add(h)
            self.requests[h] = _Request(h, peer.peer_id, now)
            out.append((h, peer.peer_id))
        return out

    def _pick_peer(self, height: int) -> _Peer | None:
        best = None
        for p in self.peers.values():
            if not (p.base <= height <= p.height):
                continue
            if len(p.pending) >= PER_PEER_LIMIT:
                continue
            if best is None or p.health() < best.health():
                best = p
        return best

    # -- block intake ----------------------------------------------------

    def add_block(self, peer_id: str, block: Block) -> bool:
        h = block.header.height
        req = self.requests.get(h)
        if h < self.height or h in self.blocks:
            return False
        # accept unsolicited blocks too (reference logs; we take them)
        self.blocks[h] = (block, peer_id)
        p = self.peers.get(peer_id)
        if p is not None:
            p.pending.discard(h)
        if req is not None:
            # free the slot of whichever peer currently holds the
            # assignment (may differ from the sender after a timeout
            # re-assignment)
            assigned = self.peers.get(req.peer_id)
            if assigned is not None:
                assigned.pending.discard(h)
                if req.peer_id == peer_id:
                    assigned.observe_rtt(self._clock.monotonic() - req.time)
            del self.requests[h]
        return True

    def no_block(self, peer_id: str, height: int) -> None:
        req = self.requests.get(height)
        if req is not None and req.peer_id == peer_id:
            del self.requests[height]
            p = self.peers.get(peer_id)
            if p is not None:
                p.pending.discard(height)

    # -- consumption -----------------------------------------------------

    def peek_range(self, max_len: int) -> list[tuple[Block, str]]:
        """Contiguous downloaded blocks starting at self.height. Block-
        sync verification of height h needs h+1's LastCommit, so the
        last block of the run is returned only as the verifier for its
        predecessor (the caller applies [0:-1])."""
        out = []
        h = self.height
        while len(out) < max_len and h in self.blocks:
            out.append(self.blocks[h])
            h += 1
        return out

    def pop(self, height: int) -> None:
        """Block applied; advance."""
        self.blocks.pop(height, None)
        if height >= self.height:
            self.height = height + 1
            self._last_advance = self._clock.monotonic()

    def redo(self, height: int, *bad_peers: str) -> None:
        """Verification failed: drop blocks from the offending providers
        and re-request (reference RedoRequest)."""
        for h in list(self.blocks):
            if h >= height and self.blocks[h][1] in bad_peers:
                del self.blocks[h]
        for pid in bad_peers:
            self.remove_peer(pid)

    def is_caught_up(self) -> bool:
        """Within one block of the best peer (reference pool.go IsCaughtUp):
        caught up once we've waited a startup grace for peers to report AND
        our chain is the longest we know of. No peer-count gate — a solo
        validator (or an isolated node at the tip) must still hand over to
        consensus after the grace period."""
        if self.peers:
            return self.height >= self.max_peer_height()
        # nobody reported a height: give discovery a grace window (from
        # the moment we LAST had no peers, not pool start), then hand
        # over — consensus lag triggers a switch-back if a taller peer
        # shows up later (reactor.resume)
        return self._clock.monotonic() - self._no_peers_since > 5.0
