"""Blocksync reactor (reference internal/blocksync/v0/reactor.go:78,
channel 0x40) — restructured as the TPU pipeline:

  fetch (network, BlockPool) → sign-bytes construction (host) →
  RANGE-batched commit verification (one TPU call per window,
  verify_commit_range) → ApplyBlock (ABCI)

The reference verifies and applies one block per poolRoutine tick
(reactor.go:439-568); here a contiguous window of up to `window` blocks
is verified in a single batched call, then applied in order. Validator-
set changes inside a window are handled safely: each block's assumed
validator hash is checked just before apply, and a mismatch triggers
individual re-verification with the true set."""

from __future__ import annotations

import asyncio
import logging

from ..libs.clock import SYSTEM, Clock
from ..libs.service import Service
from ..p2p.peermanager import PeerStatus
from ..p2p.router import Channel
from ..p2p.types import Envelope, PeerError
from ..state.execution import BlockExecutor
from ..types.block import BlockID
from ..types.validation import InvalidCommitError, verify_commit_light, verify_commit_range
from . import BLOCKSYNC_CHANNEL
from . import messages as m
from .pool import BlockPool

STATUS_INTERVAL = 2.0
REQUEST_INTERVAL = 0.02
SWITCH_CHECK_INTERVAL = 0.2
DEFAULT_WINDOW = 64


class BlockSyncReactor(Service):
    def __init__(
        self,
        state,
        block_exec: BlockExecutor,
        block_store,
        channel: Channel,
        peer_updates: asyncio.Queue,
        *,
        window: int = DEFAULT_WINDOW,
        active: bool = True,
        clock: Clock | None = None,
        logger: logging.Logger | None = None,
    ):
        super().__init__("bs-reactor", logger)
        # duration domain (range-verify latency, pool RTO/ban clocks);
        # injected so chaos clock drift reaches sync bookkeeping too
        self.clock = clock or SYSTEM
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.channel = channel
        self.peer_updates = peer_updates
        self.window = window
        # active=False serves blocks/status to peers but never fetches or
        # applies (a validator started without block-sync must not race
        # live consensus for the same heights)
        self.active = active
        self.pool = BlockPool(state.last_block_height + 1, clock=self.clock)
        self.synced = asyncio.Event()  # set on caught-up (switch to consensus)
        self.metrics = {
            "blocks_applied": 0,
            "sigs_verified": 0,
            "ranges": 0,
            "peer_bans": 0,
        }
        # Commits for heights in [_commit_verified_from, _commit_verified_upto]
        # are signature-proven by a range batch (or the sequential fallback)
        # against the validator set whose hash is recorded alongside; lets
        # apply_block skip the redundant host re-verification of each block's
        # LastCommit. NOTE the lower bound: a range starting at height h
        # proves the commits FOR h..upto (block h+1's LastCommit is the
        # commit for h) — it proves nothing about the commit for h-1, so the
        # first block applied after startup/resume must be full-verified
        # (commit_verified=False). Reset on redo(): a re-fetched block can
        # carry a different commit; reset on resume(): the proof interval is
        # stale after a consensus interlude.
        self._commit_verified_from = None  # no proof interval yet
        self._commit_verified_upto = 0
        self._commit_verified_vals = b""

    async def on_start(self) -> None:
        self.spawn(self._process_peer_updates(), name="bsr.peers")
        self.spawn(self._process_inbound(), name="bsr.in")
        self.spawn(self._status_routine(), name="bsr.status")
        if self.active:
            self.spawn(self._request_routine(), name="bsr.req")
            self.spawn(self._sync_routine(), name="bsr.sync")
        else:
            self.synced.set()

    def resume(self, state) -> None:
        """Re-activate the fetch/verify/apply pipeline after consensus
        fell too far behind (the reference 0.37 'switch back to
        block-sync'). Caller must have paused consensus first."""
        self.state = state
        self.pool.height = state.last_block_height + 1
        self.pool.blocks = {
            h: b for h, b in self.pool.blocks.items() if h > state.last_block_height
        }
        self._commit_verified_from = None
        self._commit_verified_upto = 0
        self._commit_verified_vals = b""
        self.synced = asyncio.Event()
        self.spawn(self._request_routine(), name="bsr.req")
        self.spawn(self._sync_routine(), name="bsr.sync")

    # -- peers -----------------------------------------------------------

    async def _process_peer_updates(self) -> None:
        while True:
            upd = await self.peer_updates.get()
            if upd.status == PeerStatus.UP:
                self._send(m.StatusRequest(), to=upd.node_id)
                # advertise our own range so the peer can sync from us
                self._send(
                    m.StatusResponse(self.block_store.height(), self.block_store.base()),
                    to=upd.node_id,
                )
            else:
                self.pool.remove_peer(upd.node_id)

    def _send(self, msg, *, to: str = "", broadcast: bool = False) -> None:
        try:
            self.channel.out_q.put_nowait(
                Envelope(BLOCKSYNC_CHANNEL, msg, to=to, broadcast=broadcast)
            )
        except asyncio.QueueFull:
            self.logger.warning("blocksync outbound queue full")

    # -- inbound ---------------------------------------------------------

    async def _process_inbound(self) -> None:
        async for env in self.channel:
            msg = env.message
            if isinstance(msg, m.StatusRequest):
                self._send(
                    m.StatusResponse(self.block_store.height(), self.block_store.base()),
                    to=env.from_,
                )
            elif isinstance(msg, m.StatusResponse):
                self.pool.set_peer_range(env.from_, msg.base, msg.height)
            elif isinstance(msg, m.BlockRequest):
                block = self.block_store.load_block(msg.height)
                if block is not None:
                    self._send(m.BlockResponse(block), to=env.from_)
                else:
                    self._send(m.NoBlockResponse(msg.height), to=env.from_)
            elif isinstance(msg, m.BlockResponse):
                self.pool.add_block(env.from_, msg.block)
            elif isinstance(msg, m.NoBlockResponse):
                self.pool.no_block(env.from_, msg.height)

    # -- outbound request/status loops ----------------------------------

    async def _request_routine(self) -> None:
        while not self.synced.is_set():
            for height, peer_id in self.pool.next_requests():
                self._send(m.BlockRequest(height), to=peer_id)
            # peers the pool banned for repeated consecutive timeouts are
            # evicted for real (fatal PeerError -> router disconnect) AND
            # promoted into the peer manager's dial quarantine (ban=True:
            # escalating cooldown, no redial) — the pool-local timed ban
            # alone let a bad peer bounce back every BAN_COOLDOWN
            for pid in self.pool.take_banned():
                self.metrics["peer_bans"] += 1
                await self.channel.error(
                    PeerError(pid, "blocksync: repeated request timeouts", ban=True)
                )
            await asyncio.sleep(REQUEST_INTERVAL)

    async def _status_routine(self) -> None:
        while True:
            self._send(m.StatusRequest(), broadcast=True)
            await asyncio.sleep(STATUS_INTERVAL)

    # -- the pipeline ----------------------------------------------------

    async def _sync_routine(self) -> None:
        """fetch → verify (range-batched) → apply (reference poolRoutine
        reactor.go:439, restructured)."""
        while not self.synced.is_set():
            run = self.pool.peek_range(self.window + 1)
            if len(run) < 2:
                if self.pool.is_caught_up():
                    # hand over to consensus (reference SwitchToConsensus);
                    # we keep serving BlockRequests/status to other peers
                    self.synced.set()
                    return
                await asyncio.sleep(SWITCH_CHECK_INTERVAL)
                continue
            await self._verify_and_apply(run)

    async def _verify_and_apply(self, run) -> None:
        """Verify blocks run[0..-2] using each successor's LastCommit in
        ONE batched call, then apply them in order."""
        chain_id = self.state.chain_id
        # Stage 1 (host): build verification entries. Block i is verified
        # by run[i+1].last_commit against the CURRENT validator set —
        # valid while the set doesn't change mid-range; the apply loop
        # re-checks per block and re-verifies individually on rotation.
        entries = []
        parts_list = []
        assumed_vals = self.state.validators
        for i in range(len(run) - 1):
            block, _provider = run[i]
            next_block, _ = run[i + 1]
            parts = block.make_part_set()
            parts_list.append(parts)
            block_id = BlockID(block.hash(), parts.header)
            entries.append((assumed_vals, block_id, block.header.height, next_block.last_commit))
        first_height = run[0][0].header.height

        # Stage 2 (TPU): one batched verification for the whole range
        try:
            n_sigs = sum(
                sum(1 for s in e[3].signatures if s.is_commit()) for e in entries
            )
            t0 = self.clock.monotonic()
            await asyncio.to_thread(
                verify_commit_range, chain_id, entries, lane="backfill"
            )
            dt = self.clock.monotonic() - t0
            self.metrics["ranges"] += 1
            self.metrics["sigs_verified"] += n_sigs
            # the batch proved the commits FOR first_height..first+len-1
            # (each block's successor LastCommit), all against assumed_vals
            self._record_commit_proof(
                first_height, first_height + len(entries) - 1, assumed_vals.hash()
            )
            self.logger.debug(
                "verified range h=%d..%d (%d sigs) in %.1fms",
                first_height,
                first_height + len(entries) - 1,
                n_sigs,
                dt * 1e3,
            )
        except InvalidCommitError as e:
            # NOT necessarily byzantine: the whole range was verified
            # against today's validator set, so a legitimate mid-range
            # validator rotation also lands here. Re-process the run
            # sequentially against the true (evolving) state; only a
            # block that fails against its CORRECT set evicts peers.
            self.logger.debug(
                "range verify failed at h=%d (%s); falling back to sequential",
                first_height + getattr(e, "failed_index", 0),
                e,
            )
            await self._apply_sequential(run, parts_list)
            return

        # Stage 3: apply in order (ABCI)
        for i in range(len(run) - 1):
            block, provider = run[i]
            height = block.header.height
            parts = parts_list[i]
            block_id = BlockID(block.hash(), parts.header)
            next_block, next_provider = run[i + 1]
            # validator rotation guard: if the set changed mid-range, the
            # batch's assumption is stale from here on — re-verify this
            # block against the true set before applying
            if self.state.validators.hash() != assumed_vals.hash():
                try:
                    await asyncio.to_thread(
                        verify_commit_light,
                        chain_id,
                        self.state.validators,
                        block_id,
                        height,
                        next_block.last_commit,
                        lane="backfill",
                    )
                except InvalidCommitError as e:
                    await self._punish(height, provider, next_provider, e)
                    return
                # record the re-proof so the NEXT block's apply doesn't
                # redo this commit on the host (same bookkeeping as the
                # sequential fallback)
                self._record_commit_proof(
                    height, height, self.state.validators.hash()
                )
            if not await self._apply_one(block, block_id, parts, next_block, provider):
                return
        return

    async def _apply_sequential(self, run, parts_list) -> None:
        """Per-block verify (against the true evolving validator set) +
        apply — the fallback when a range batch fails, and the semantic
        twin of the reference's one-at-a-time poolRoutine."""
        chain_id = self.state.chain_id
        for i in range(len(run) - 1):
            block, provider = run[i]
            height = block.header.height
            if height < self.pool.height:
                continue  # already applied
            parts = parts_list[i]
            block_id = BlockID(block.hash(), parts.header)
            next_block, next_provider = run[i + 1]
            try:
                await asyncio.to_thread(
                    verify_commit_light,
                    chain_id,
                    self.state.validators,
                    block_id,
                    height,
                    next_block.last_commit,
                    lane="backfill",
                )
            except InvalidCommitError as e:
                await self._punish(height, provider, next_provider, e)
                return
            # commit for `height` proven against the TRUE set for that
            # height (state.validators now == state.last_validators when
            # block height+1 is applied next iteration)
            self._record_commit_proof(height, height, self.state.validators.hash())
            if not await self._apply_one(block, block_id, parts, next_block, provider):
                return

    async def _punish(self, height, provider, next_provider, err) -> None:
        """Bad block/commit confirmed against the correct validator set:
        both the block provider and the commit provider are suspect
        (reference reactor.go:556-568)."""
        self.logger.info(
            "invalid commit at height %d from %s: %s", height, provider[:12], err
        )
        await self.channel.error(PeerError(provider, f"bad block: {err}"))
        if next_provider != provider:
            await self.channel.error(PeerError(next_provider, f"bad commit: {err}"))
        self.pool.redo(height, provider, next_provider)
        self._commit_verified_upto = min(self._commit_verified_upto, height - 1)

    def _record_commit_proof(self, a: int, b: int, vals_hash: bytes) -> None:
        """Merge a freshly proven commit interval [a, b] (commits FOR
        those heights, proven against vals_hash). A proof under a
        different validator-set hash, or one not contiguous with the
        recorded interval, REPLACES it — extending across a gap or a set
        change would claim proofs that were never computed."""
        lo, hi = self._commit_verified_from, self._commit_verified_upto
        if (
            lo is None
            or vals_hash != self._commit_verified_vals
            or hi < lo  # emptied by a redo/punish rollback
            or a > hi + 1  # gap above
            or b < lo - 1  # gap below
        ):
            self._commit_verified_from, self._commit_verified_upto = a, b
            self._commit_verified_vals = vals_hash
        else:
            self._commit_verified_from = min(lo, a)
            self._commit_verified_upto = max(hi, b)

    def _commit_preverified(self, height: int) -> bool:
        """True when block `height`'s LastCommit (the commit for
        height-1) was already signature-proven by a batch/sequential
        verification against exactly the set validate_block will check
        it with (state.last_validators).

        The lower bound matters: the first range proves commits from its
        OWN first height onward, never the commit for first_height-1, so
        the first block applied after startup/resume always takes the
        full apply-time verification path (commit_verified=False)."""
        return (
            self._commit_verified_from is not None
            and self._commit_verified_from <= height - 1 <= self._commit_verified_upto
            and self.state.last_validators.hash() == self._commit_verified_vals
        )

    async def _apply_one(self, block, block_id, parts, next_block, provider) -> bool:
        height = block.header.height
        try:
            if self.block_store.height() < height:
                self.block_store.save_block(block, parts, next_block.last_commit)
            self.state, _ = await self.block_exec.apply_block(
                self.state,
                block_id,
                block,
                commit_verified=self._commit_preverified(height),
            )
            self.metrics["blocks_applied"] += 1
        except Exception as e:
            self.logger.error("apply failed at height %d: %r", height, e)
            await self.channel.error(PeerError(provider, f"apply: {e!r}"))
            self.pool.redo(height, provider)
            self._commit_verified_upto = min(self._commit_verified_upto, height - 1)
            return False
        self.pool.pop(height)
        return True
