"""Mempool interface (reference internal/mempool/mempool.go:30).

The concrete priority mempool lives in mempool/pool.py; `NopMempool` keeps
the block executor testable without one."""

from __future__ import annotations

MEMPOOL_CHANNEL = 0x30


class Mempool:
    async def check_tx(self, tx: bytes, sender: str = "", trace_ctx=None) -> None:
        """Validate a tx against the app and admit it. Raises on rejection.
        `trace_ctx` is an optional libs/trace TraceCtx handed through by
        TxIngress so the admission path tiles end to end."""
        raise NotImplementedError

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        raise NotImplementedError

    def reap_max_txs(self, max_txs: int) -> list[bytes]:
        raise NotImplementedError

    def lock(self):
        """Async context manager held across Commit (reference
        Mempool.Lock/Unlock around app commit, execution.go:245)."""
        raise NotImplementedError

    async def update(
        self,
        height: int,
        txs: list[bytes],
        results: list,
        *,
        recheck: bool = True,
    ) -> None:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    async def flush(self) -> None:
        raise NotImplementedError


class _NullLock:
    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False


class NopMempool(Mempool):
    async def check_tx(self, tx, sender="", trace_ctx=None):
        pass

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return []

    def reap_max_txs(self, max_txs):
        return []

    def lock(self):
        return _NullLock()

    async def update(self, height, txs, results, *, recheck=True):
        pass

    def size(self):
        return 0

    def size_bytes(self):
        return 0

    async def flush(self):
        pass
