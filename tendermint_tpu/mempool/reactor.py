"""Mempool gossip reactor (reference internal/mempool/v1/reactor.go,
channel 0x30).

Each peer gets a broadcast task walking the mempool in priority order and
sending txs that peer hasn't been seen to have (either from us earlier or
because the peer itself sent it to us — tracked in WrappedTx.peers).
Per-tx fan-out is capped (`MempoolConfig.gossip_fanout`): once a tx has
been pushed to that many peers the rest rely on transitive gossip, so a
flood costs each node O(fanout) sends per tx, not O(peers).

Inbound txs route through TxIngress when the node runs one: dedup +
signature pre-verification happen BEFORE the ABCI CheckTx round-trip,
and a busy pipeline sheds (the peer re-offers later) instead of
buffering unboundedly."""

from __future__ import annotations

import asyncio
import logging

from ..libs import protoenc as pe
from ..libs.service import Service
from ..p2p.peermanager import PeerStatus
from ..p2p.router import Channel
from ..p2p.types import Envelope, PeerError
from . import MEMPOOL_CHANNEL
from .ingress import TxIngress
from .pool import PriorityMempool, TxInCacheError, TxRejectedError

BROADCAST_SLEEP = 0.05

# Wire-side sanity bound: a gossip frame is sent one-tx-at-a-time by
# honest peers (see _broadcast_routine), so a frame repeating thousands
# of tx fields is malformed by construction — raise at decode, never
# build an unbounded list (tmtlint wire-bounds; the decoded txs also
# each pass through the ingress size/occupancy checks afterwards).
MAX_WIRE_TXS = 1024


def encode_txs(txs: list[bytes]) -> bytes:
    return b"".join(pe.bytes_field(1, tx) for tx in txs)


def decode_txs(data: bytes) -> list[bytes]:
    r = pe.Reader(data)
    out = []
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            out.append(r.read_bytes())
            if len(out) > MAX_WIRE_TXS:
                raise ValueError(f"tx gossip frame exceeds {MAX_WIRE_TXS} txs")
        else:
            r.skip(wt)
    return out


class MempoolReactor(Service):
    def __init__(
        self,
        mempool: PriorityMempool,
        channel: Channel,
        peer_updates: asyncio.Queue,
        *,
        ingress: TxIngress | None = None,
        broadcast: bool = True,
        logger: logging.Logger | None = None,
    ):
        super().__init__("mp-reactor", logger)
        self.mempool = mempool
        self.ingress = ingress
        self.channel = channel
        self.peer_updates = peer_updates
        self.broadcast = broadcast
        self._peer_tasks: dict[str, asyncio.Task] = {}
        self._sent: dict[str, set[bytes]] = {}  # peer -> tx hashes sent

    async def on_start(self) -> None:
        self.spawn(self._process_peer_updates(), name="mpr.peers")
        self.spawn(self._process_inbound(), name="mpr.in")

    async def on_stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()

    async def _process_peer_updates(self) -> None:
        while True:
            upd = await self.peer_updates.get()
            if upd.status == PeerStatus.UP and self.broadcast:
                if upd.node_id not in self._peer_tasks:
                    self._sent[upd.node_id] = set()
                    self._peer_tasks[upd.node_id] = self.spawn(
                        self._broadcast_to(upd.node_id),
                        name=f"mpr.bcast.{upd.node_id[:8]}",
                    )
            elif upd.status == PeerStatus.DOWN:
                t = self._peer_tasks.pop(upd.node_id, None)
                if t is not None:
                    t.cancel()
                self._sent.pop(upd.node_id, None)

    async def _process_inbound(self) -> None:
        async for env in self.channel:
            if self.ingress is not None:
                # staged admission, fire-and-forget: dedup + signature
                # pre-verify happen before any tx costs an ABCI
                # round-trip, a full pipeline sheds (the peer re-offers),
                # and a parked nonce-gap tx never stalls this loop. The
                # ingress pre-retrieves every rejection future's
                # exception, so dropping the handle leaks nothing.
                for tx in env.message:
                    self.ingress.submit_nowait(tx, source=env.from_)
                continue
            for tx in env.message:
                try:
                    await self.mempool.check_tx(tx, sender=env.from_)
                except TxInCacheError:
                    pass
                except TxRejectedError:
                    pass  # invalid per app: not the peer's fault per se
                except Exception as e:
                    await self.channel.error(PeerError(env.from_, f"tx: {e!r}"))

    async def _broadcast_to(self, peer_id: str) -> None:
        """Reference broadcastTxRoutine: walk resident txs, skip ones the
        peer already has (sent by us earlier, or the peer was a gossip
        source — WrappedTx.peers — so it is never echoed its own tx) and
        ones already pushed to `gossip_fanout` peers."""
        sent = self._sent[peer_id]
        fanout = self.mempool.config.gossip_fanout
        while True:
            batch, picked = [], []
            for wtx in self.mempool.all_entries():
                if wtx.hash in sent or peer_id in wtx.peers:
                    continue
                if fanout > 0 and wtx.gossiped >= fanout:
                    continue  # fan-out cap: transitive gossip covers the rest
                # claim the fan-out slot at selection (before any await):
                # concurrent per-peer tasks must not all pick the same tx
                wtx.gossiped += 1
                sent.add(wtx.hash)
                batch.append(wtx.tx)
                picked.append(wtx)
                if len(batch) >= 100:
                    break
            if batch:
                try:
                    # awaited put: backpressure instead of silent tx loss
                    await self.channel.out_q.put(
                        Envelope(MEMPOOL_CHANNEL, batch, to=peer_id)
                    )
                except asyncio.CancelledError:
                    # peer went DOWN mid-send: give the claimed fan-out
                    # slots back, or churn could exhaust a tx's budget
                    # with zero deliveries
                    for wtx in picked:
                        wtx.gossiped -= 1
                        sent.discard(wtx.hash)
                    raise
            else:
                await asyncio.sleep(BROADCAST_SLEEP)
