"""Mempool gossip reactor (reference internal/mempool/v1/reactor.go,
channel 0x30).

Each peer gets a broadcast task walking the mempool in priority order and
sending txs that peer hasn't been seen to have (either from us earlier or
because the peer itself sent it to us — tracked in WrappedTx.peers)."""

from __future__ import annotations

import asyncio
import logging

from ..libs import protoenc as pe
from ..libs.service import Service
from ..p2p.peermanager import PeerStatus
from ..p2p.router import Channel
from ..p2p.types import Envelope, PeerError
from . import MEMPOOL_CHANNEL
from .pool import PriorityMempool, TxInCacheError, TxRejectedError

BROADCAST_SLEEP = 0.05


def encode_txs(txs: list[bytes]) -> bytes:
    return b"".join(pe.bytes_field(1, tx) for tx in txs)


def decode_txs(data: bytes) -> list[bytes]:
    r = pe.Reader(data)
    out = []
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            out.append(r.read_bytes())
        else:
            r.skip(wt)
    return out


class MempoolReactor(Service):
    def __init__(
        self,
        mempool: PriorityMempool,
        channel: Channel,
        peer_updates: asyncio.Queue,
        *,
        broadcast: bool = True,
        logger: logging.Logger | None = None,
    ):
        super().__init__("mp-reactor", logger)
        self.mempool = mempool
        self.channel = channel
        self.peer_updates = peer_updates
        self.broadcast = broadcast
        self._peer_tasks: dict[str, asyncio.Task] = {}
        self._sent: dict[str, set[bytes]] = {}  # peer -> tx hashes sent

    async def on_start(self) -> None:
        self.spawn(self._process_peer_updates(), name="mpr.peers")
        self.spawn(self._process_inbound(), name="mpr.in")

    async def on_stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()

    async def _process_peer_updates(self) -> None:
        while True:
            upd = await self.peer_updates.get()
            if upd.status == PeerStatus.UP and self.broadcast:
                if upd.node_id not in self._peer_tasks:
                    self._sent[upd.node_id] = set()
                    self._peer_tasks[upd.node_id] = self.spawn(
                        self._broadcast_to(upd.node_id),
                        name=f"mpr.bcast.{upd.node_id[:8]}",
                    )
            elif upd.status == PeerStatus.DOWN:
                t = self._peer_tasks.pop(upd.node_id, None)
                if t is not None:
                    t.cancel()
                self._sent.pop(upd.node_id, None)

    async def _process_inbound(self) -> None:
        async for env in self.channel:
            for tx in env.message:
                try:
                    await self.mempool.check_tx(tx, sender=env.from_)
                except TxInCacheError:
                    pass
                except TxRejectedError:
                    pass  # invalid per app: not the peer's fault per se
                except Exception as e:
                    await self.channel.error(PeerError(env.from_, f"tx: {e!r}"))

    async def _broadcast_to(self, peer_id: str) -> None:
        """Reference broadcastTxRoutine: walk resident txs, skip ones the
        peer already has."""
        sent = self._sent[peer_id]
        while True:
            batch, hashes = [], []
            for wtx in self.mempool.all_entries():
                if wtx.hash in sent or peer_id in wtx.peers:
                    continue
                batch.append(wtx.tx)
                hashes.append(wtx.hash)
                if len(batch) >= 100:
                    break
            if batch:
                # awaited put: backpressure instead of silent tx loss
                await self.channel.out_q.put(
                    Envelope(MEMPOOL_CHANNEL, batch, to=peer_id)
                )
                sent.update(hashes)
            else:
                await asyncio.sleep(BROADCAST_SLEEP)
