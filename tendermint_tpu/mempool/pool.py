"""Priority mempool (reference internal/mempool/v1/mempool.go:30 — the
default mempool version, config/config.go:852) plus the LRU tx cache
(reference internal/mempool/cache.go).

Transactions are admitted via ABCI CheckTx on the mempool connection and
held in (priority DESC, arrival ASC) order; `reap_max_bytes_max_gas`
takes the highest-priority prefix that fits the block budget, and
`update` removes committed txs and optionally re-CheckTxs the remainder
(reference v1/mempool.go Update/recheckTxs). When full, the lowest-
priority resident tx is evicted if the newcomer outranks it
(v1/mempool.go:232 canAddTx / eviction)."""

from __future__ import annotations

import asyncio
import itertools
import logging
from collections import OrderedDict
from dataclasses import dataclass, field

from ..abci import types as abci
from ..abci.client import Client
from ..config import MempoolConfig
from ..crypto.hashes import sha256
from . import Mempool


class TxCache:
    """Fixed-size LRU of tx hashes (reference mempool/cache.go LRUTxCache)."""

    def __init__(self, size: int):
        self.size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()

    def push(self, tx: bytes) -> bool:
        """Returns False if already present (and refreshes recency)."""
        key = sha256(tx)
        if key in self._map:
            self._map.move_to_end(key)
            return False
        self._map[key] = None
        if len(self._map) > self.size:
            self._map.popitem(last=False)
        return True

    def remove(self, tx: bytes) -> None:
        self._map.pop(sha256(tx), None)

    def has(self, tx: bytes) -> bool:
        return sha256(tx) in self._map

    def reset(self) -> None:
        self._map.clear()


class TxRejectedError(ValueError):
    def __init__(self, code: int, log: str):
        super().__init__(f"tx rejected: code={code} log={log!r}")
        self.code = code
        self.log = log


class TxInCacheError(ValueError):
    pass


class MempoolFullError(ValueError):
    pass


@dataclass
class WrappedTx:
    tx: bytes
    hash: bytes
    height: int  # height at admission
    priority: int
    gas_wanted: int
    sender: str
    seq: int  # arrival order (FIFO tie-break)
    time_ns: int = 0
    peers: set[str] = field(default_factory=set)

    def sort_key(self):
        return (-self.priority, self.seq)


class PriorityMempool(Mempool):
    def __init__(
        self,
        config: MempoolConfig,
        app: Client,
        *,
        height: int = 0,
        logger: logging.Logger | None = None,
    ):
        self.config = config
        self.app = app
        self.height = height
        self.logger = logger or logging.getLogger("mempool")
        self.cache = TxCache(config.cache_size)
        self._txs: dict[bytes, WrappedTx] = {}  # hash -> wtx
        self._bytes = 0
        self._seq = itertools.count()
        self._lock = asyncio.Lock()
        # set when txs are available; consensus wait-for-txs hook
        self._txs_available: asyncio.Event = asyncio.Event()
        self.notified_txs_available = False
        # pulsed by update() when it resets notified_txs_available, so the
        # consensus txs-available waiter sleeps instead of polling
        self._notified_reset: asyncio.Event = asyncio.Event()

    # -- admission -------------------------------------------------------

    async def check_tx(self, tx: bytes, sender: str = "") -> None:
        if len(tx) > self.config.max_tx_bytes:
            raise TxRejectedError(0, f"tx too large ({len(tx)} bytes)")
        if not self.cache.push(tx):
            # seen before: record the extra gossip sender, reject
            wtx = self._txs.get(sha256(tx))
            if wtx is not None and sender:
                wtx.peers.add(sender)
            raise TxInCacheError("tx already in cache")
        res = await self.app.check_tx(abci.RequestCheckTx(tx))
        if not res.is_ok():
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            raise TxRejectedError(res.code, res.log)
        wtx = WrappedTx(
            tx=tx,
            hash=sha256(tx),
            height=self.height,
            priority=res.priority,
            gas_wanted=res.gas_wanted,
            sender=res.sender or sender,
            seq=next(self._seq),
        )
        self._insert(wtx)

    def _insert(self, wtx: WrappedTx) -> None:
        if wtx.hash in self._txs:
            return
        while (
            len(self._txs) >= self.config.size
            or self._bytes + len(wtx.tx) > self.config.max_txs_bytes
        ):
            victim = max(self._txs.values(), key=lambda w: w.sort_key())
            if victim.sort_key() <= wtx.sort_key():
                # newcomer doesn't outrank the worst resident: reject
                self.cache.remove(wtx.tx)
                raise MempoolFullError(
                    f"mempool full ({len(self._txs)} txs, {self._bytes} bytes)"
                )
            self._remove(victim.hash, remove_from_cache=True)
            self.logger.debug("evicted tx %s", victim.hash.hex()[:12])
        self._txs[wtx.hash] = wtx
        self._bytes += len(wtx.tx)
        if not self._txs_available.is_set():
            self._txs_available.set()

    def _remove(self, hash_: bytes, *, remove_from_cache: bool) -> None:
        wtx = self._txs.pop(hash_, None)
        if wtx is None:
            return
        self._bytes -= len(wtx.tx)
        if remove_from_cache:
            self.cache.remove(wtx.tx)

    # -- reaping ---------------------------------------------------------

    def _ordered(self) -> list[WrappedTx]:
        return sorted(self._txs.values(), key=lambda w: w.sort_key())

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        out: list[bytes] = []
        total_bytes = total_gas = 0
        for wtx in self._ordered():
            nb = total_bytes + len(wtx.tx)
            if max_bytes > -1 and nb > max_bytes:
                break
            ng = total_gas + wtx.gas_wanted
            if max_gas > -1 and ng > max_gas:
                break
            total_bytes, total_gas = nb, ng
            out.append(wtx.tx)
        return out

    def reap_max_txs(self, max_txs: int) -> list[bytes]:
        txs = [w.tx for w in self._ordered()]
        return txs if max_txs < 0 else txs[:max_txs]

    # -- lifecycle -------------------------------------------------------

    def lock(self):
        return self._lock

    async def update(
        self, height: int, txs: list[bytes], results: list, *, recheck: bool = True
    ) -> None:
        """Remove committed txs; re-CheckTx what remains (reference
        v1/mempool.go Update). Caller holds lock() (the executor commits
        under it)."""
        self.height = height
        for i, tx in enumerate(txs):
            committed_ok = i < len(results) and results[i].is_ok()
            if committed_ok:
                self.cache.push(tx)  # keep committed txs in cache
            else:
                self.cache.remove(tx)
            self._remove(sha256(tx), remove_from_cache=False)
        if recheck and self.config.recheck and self._txs:
            await self._recheck()
        if self.size() > 0:
            self._txs_available.set()
        else:
            self._txs_available.clear()
        self.notified_txs_available = False
        self._notified_reset.set()

    async def _recheck(self) -> None:
        """Re-run CheckTx(RECHECK) on all resident txs after a block
        changed app state (reference recheckTxs v1/mempool.go:540)."""
        for wtx in self._ordered():
            res = await self.app.check_tx(
                abci.RequestCheckTx(wtx.tx, abci.CheckTxType.RECHECK)
            )
            if not res.is_ok():
                self._remove(
                    wtx.hash,
                    remove_from_cache=not self.config.keep_invalid_txs_in_cache,
                )
            else:
                wtx.priority = res.priority

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._bytes

    async def flush(self) -> None:
        self._txs.clear()
        self._bytes = 0
        self.cache.reset()

    # -- gossip support --------------------------------------------------

    def all_entries(self) -> list[WrappedTx]:
        return self._ordered()

    def has_tx(self, hash_: bytes) -> bool:
        return hash_ in self._txs

    async def wait_for_txs(self) -> None:
        await self._txs_available.wait()

    async def wait_notified_reset(self) -> None:
        """Block until the next post-commit reset of the once-per-height
        txs-available notification latch."""
        self._notified_reset.clear()
        await self._notified_reset.wait()
