"""Priority mempool (reference internal/mempool/v1/mempool.go:30 — the
default mempool version, config/config.go:852) plus the LRU tx cache
(reference internal/mempool/cache.go).

Transactions are admitted via ABCI CheckTx on the mempool connection and
held in (priority DESC, arrival ASC) order; `reap_max_bytes_max_gas`
takes the highest-priority prefix that fits the block budget, and
`update` removes committed txs and optionally re-CheckTxs the remainder
(reference v1/mempool.go Update/recheckTxs). When full, the lowest-
priority resident tx is evicted if the newcomer outranks it
(v1/mempool.go:232 canAddTx / eviction)."""

from __future__ import annotations

import asyncio
import itertools
import logging
import weakref
from collections import OrderedDict
from dataclasses import dataclass, field

from ..abci import types as abci
from ..abci.client import Client
from ..config import MempoolConfig
from ..crypto.hash_hub import sha256_one
from ..libs import trace
from . import Mempool

#: process-wide registry of live pools; NodeMetrics sums their stats at
#: render time (the verifyhub/ingest fold pattern — multi-node
#: in-process tests run several pools, one /metrics shows the funnel)
_pools: "weakref.WeakSet[PriorityMempool]" = weakref.WeakSet()


def aggregate_pools():
    """Summed (stats, size, bytes) across every live pool, or None."""
    pools = list(_pools)
    if not pools:
        return None
    keys = pools[0].stats.keys()
    s = {k: sum(p.stats[k] for p in pools) for k in keys}
    return s, sum(p.size() for p in pools), sum(p.size_bytes() for p in pools)


class TxCache:
    """Fixed-size LRU of tx hashes (reference mempool/cache.go LRUTxCache)."""

    def __init__(self, size: int):
        self.size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()

    def push(self, tx: bytes) -> bool:
        """Returns False if already present (and refreshes recency)."""
        key = sha256_one(tx)
        if key in self._map:
            self._map.move_to_end(key)
            return False
        self._map[key] = None
        if len(self._map) > self.size:
            self._map.popitem(last=False)
        return True

    def remove(self, tx: bytes) -> None:
        self._map.pop(sha256_one(tx), None)

    def has(self, tx: bytes) -> bool:
        return sha256_one(tx) in self._map

    def reset(self) -> None:
        self._map.clear()


class TxRejectedError(ValueError):
    def __init__(self, code: int, log: str):
        super().__init__(f"tx rejected: code={code} log={log!r}")
        self.code = code
        self.log = log


class TxInCacheError(ValueError):
    pass


class MempoolFullError(ValueError):
    pass


@dataclass
class WrappedTx:
    tx: bytes
    hash: bytes
    height: int  # height at admission
    priority: int
    gas_wanted: int
    sender: str
    seq: int  # arrival order (FIFO tie-break)
    time_ns: int = 0
    peers: set[str] = field(default_factory=set)
    gossiped: int = 0  # peers this tx was sent to (fan-out cap)

    def sort_key(self):
        return (-self.priority, self.seq)


class PriorityMempool(Mempool):
    def __init__(
        self,
        config: MempoolConfig,
        app: Client,
        *,
        height: int = 0,
        logger: logging.Logger | None = None,
    ):
        self.config = config
        self.app = app
        self.height = height
        self.logger = logger or logging.getLogger("mempool")
        self.cache = TxCache(config.cache_size)
        # hashes of txs committed in a block: an admission whose CheckTx
        # was in flight across that commit must NOT resurrect them (the
        # update/check_tx interleaving class — see check_tx)
        self._committed = TxCache(config.cache_size)
        self._txs: dict[bytes, WrappedTx] = {}  # hash -> wtx
        self._bytes = 0
        self._seq = itertools.count()
        self._lock = asyncio.Lock()
        # flood observability, folded into /metrics at render time
        self.stats: dict[str, float] = {
            "admitted": 0.0,   # txs inserted into the resident set
            "rejected": 0.0,   # CheckTx/size rejections (full pool incl.)
            "evicted": 0.0,    # residents displaced by higher priority
            "recheck_failed": 0.0,  # residents dropped by post-commit recheck
        }
        _pools.add(self)
        # set when txs are available; consensus wait-for-txs hook
        self._txs_available: asyncio.Event = asyncio.Event()
        self.notified_txs_available = False
        # pulsed by update() when it resets notified_txs_available, so the
        # consensus txs-available waiter sleeps instead of polling
        self._notified_reset: asyncio.Event = asyncio.Event()

    # -- admission -------------------------------------------------------

    async def precheck(self, tx: bytes):
        """Bare ABCI CheckTx round-trip with NO cache/insert side
        effects — the tx-ingress stage-B slice prefetch (release-order
        micro-batching) issues these concurrently and hands the
        responses back through check_tx(pre=...). Kept on the pool so
        the app connection stays encapsulated."""
        return await self.app.check_tx(abci.RequestCheckTx(tx))

    async def check_tx(
        self, tx: bytes, sender: str = "", trace_ctx=None, pre=None
    ) -> None:
        if len(tx) > self.config.max_tx_bytes:
            self.stats["rejected"] += 1
            raise TxRejectedError(0, f"tx too large ({len(tx)} bytes)")
        if self._committed.has(tx):
            raise TxInCacheError("tx already committed")
        if not self.cache.push(tx):
            # seen before: record the extra gossip sender, reject
            wtx = self._txs.get(sha256_one(tx))
            if wtx is not None and sender:
                wtx.peers.add(sender)
            raise TxInCacheError("tx already in cache")
        # checktx/insert trace stages (TxIngress hands its trace ctx
        # through so the admission path tiles end to end): the checktx
        # span starts at the nonce-lane boundary mark the ingress left,
        # so stage durations share boundaries and sum exactly
        t_ck0 = (
            trace_ctx.marks.pop("checktx_start", trace_ctx.clock.monotonic())
            if trace_ctx is not None
            else 0.0
        )
        # `pre` is a slice-prefetched response (ingress stage-B micro-
        # batching): consume it instead of paying another ABCI RTT
        res = pre if pre is not None else await self.app.check_tx(
            abci.RequestCheckTx(tx)
        )
        if trace_ctx is not None:
            t_ck1 = trace_ctx.clock.monotonic()
            trace.record(trace_ctx, "mempool.ingress", "checktx", t_ck0, t_ck1)
        if not res.is_ok():
            self.stats["rejected"] += 1
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            raise TxRejectedError(res.code, res.log)
        # insert + eviction are one atomic section against update(): the
        # executor commits holding lock(), so an admission whose CheckTx
        # straddled that commit can neither double-count _bytes against a
        # concurrent eviction nor resurrect a tx the commit just removed
        async with self._lock:
            if self._committed.has(tx):
                # committed while our CheckTx round-trip was in flight
                raise TxInCacheError("tx committed during admission")
            wtx = WrappedTx(
                tx=tx,
                hash=sha256_one(tx),
                height=self.height,
                priority=res.priority,
                gas_wanted=res.gas_wanted,
                sender=res.sender or sender,
                seq=next(self._seq),
                # the gossip source already has this tx: never echo it back
                peers={sender} if sender else set(),
            )
            try:
                self._insert(wtx)
            except MempoolFullError:
                self.stats["rejected"] += 1
                raise
            self.stats["admitted"] += 1
            if trace_ctx is not None:
                t_ins = trace_ctx.clock.monotonic()
                trace.record(trace_ctx, "mempool.ingress", "insert", t_ck1, t_ins)
                trace_ctx.marks["insert_end"] = t_ins

    def _insert(self, wtx: WrappedTx) -> None:
        if wtx.hash in self._txs:
            return
        while (
            len(self._txs) >= self.config.size
            or self._bytes + len(wtx.tx) > self.config.max_txs_bytes
        ):
            victim = max(self._txs.values(), key=lambda w: w.sort_key())
            if victim.sort_key() <= wtx.sort_key():
                # newcomer doesn't outrank the worst resident: reject
                self.cache.remove(wtx.tx)
                raise MempoolFullError(
                    f"mempool full ({len(self._txs)} txs, {self._bytes} bytes)"
                )
            self._remove(victim.hash, remove_from_cache=True)
            self.stats["evicted"] += 1
            self.logger.debug("evicted tx %s", victim.hash.hex()[:12])
        self._txs[wtx.hash] = wtx
        self._bytes += len(wtx.tx)
        if not self._txs_available.is_set():
            self._txs_available.set()

    def _remove(self, hash_: bytes, *, remove_from_cache: bool) -> None:
        wtx = self._txs.pop(hash_, None)
        if wtx is None:
            return
        self._bytes -= len(wtx.tx)
        if remove_from_cache:
            self.cache.remove(wtx.tx)

    # -- reaping ---------------------------------------------------------

    def _ordered(self) -> list[WrappedTx]:
        return sorted(self._txs.values(), key=lambda w: w.sort_key())

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        out: list[bytes] = []
        total_bytes = total_gas = 0
        for wtx in self._ordered():
            nb = total_bytes + len(wtx.tx)
            if max_bytes > -1 and nb > max_bytes:
                break
            ng = total_gas + wtx.gas_wanted
            if max_gas > -1 and ng > max_gas:
                break
            total_bytes, total_gas = nb, ng
            out.append(wtx.tx)
        return out

    def reap_max_txs(self, max_txs: int) -> list[bytes]:
        txs = [w.tx for w in self._ordered()]
        return txs if max_txs < 0 else txs[:max_txs]

    # -- lifecycle -------------------------------------------------------

    def lock(self):
        return self._lock

    async def update(
        self, height: int, txs: list[bytes], results: list, *, recheck: bool = True
    ) -> None:
        """Remove committed txs; re-CheckTx what remains (reference
        v1/mempool.go Update). Caller holds lock() (the executor commits
        under it)."""
        self.height = height
        for i, tx in enumerate(txs):
            committed_ok = i < len(results) and results[i].is_ok()
            if committed_ok:
                self.cache.push(tx)  # keep committed txs in cache
                # remember the commit: an admission in flight across this
                # update must not re-insert the tx (check_tx re-checks
                # this under lock after its ABCI round-trip)
                self._committed.push(tx)
            else:
                self.cache.remove(tx)
            self._remove(sha256_one(tx), remove_from_cache=False)
        if recheck and self.config.recheck and self._txs:
            await self._recheck()
        if self.size() > 0:
            self._txs_available.set()
        else:
            self._txs_available.clear()
        self.notified_txs_available = False
        self._notified_reset.set()

    async def _recheck(self) -> None:
        """Re-run CheckTx(RECHECK) on all resident txs after a block
        changed app state (reference recheckTxs v1/mempool.go:540).

        Micro-batched: the resident set is re-checked in concurrent
        slices of `recheck_batch` ABCI calls instead of N sequential
        round-trips, so post-commit recheck latency scales with the
        slowest call per slice, not the sum. Results are applied in
        priority order regardless of completion order (gather preserves
        submission order), so the surviving set is deterministic."""
        entries = self._ordered()
        width = max(1, self.config.recheck_batch)
        for i in range(0, len(entries), width):
            chunk = entries[i : i + width]
            results = await asyncio.gather(
                *(
                    self.app.check_tx(
                        abci.RequestCheckTx(w.tx, abci.CheckTxType.RECHECK)
                    )
                    for w in chunk
                )
            )
            for wtx, res in zip(chunk, results):
                if wtx.hash not in self._txs:
                    continue  # displaced while the slice was in flight
                if not res.is_ok():
                    self._remove(
                        wtx.hash,
                        remove_from_cache=not self.config.keep_invalid_txs_in_cache,
                    )
                    self.stats["recheck_failed"] += 1
                else:
                    wtx.priority = res.priority

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._bytes

    async def flush(self) -> None:
        self._txs.clear()
        self._bytes = 0
        self.cache.reset()

    # -- gossip support --------------------------------------------------

    def all_entries(self) -> list[WrappedTx]:
        return self._ordered()

    def has_tx(self, hash_: bytes) -> bool:
        return hash_ in self._txs

    def close(self) -> None:
        """Deregister from the process-wide metrics fold: a stopped
        node's pool must not keep contributing residents to /metrics
        (the ingress registry filters on is_running; pools are not
        Services, so owners call this from their stop path)."""
        _pools.discard(self)

    def is_committed(self, tx: bytes) -> bool:
        """True when `tx` was committed in a recent block (bounded LRU):
        admission layers reject these before any verify/ABCI work."""
        return self._committed.has(tx)

    def note_peer(self, hash_: bytes, peer: str) -> None:
        """Record that `peer` already has this tx (gossip duplicate):
        the broadcast loop will never echo it back there."""
        wtx = self._txs.get(hash_)
        if wtx is not None and peer:
            wtx.peers.add(peer)

    async def wait_for_txs(self) -> None:
        await self._txs_available.wait()

    async def wait_notified_reset(self) -> None:
        """Block until the next post-commit reset of the once-per-height
        txs-available notification latch."""
        self._notified_reset.clear()
        await self._notified_reset.wait()
