"""TxIngress — the production front door for transactions.

Raw tx bytes (RPC ``broadcast_tx_*`` and p2p gossip alike) become
admitted mempool entries through a staged pipeline, so a tx flood from
millions of users degrades into *measured shedding* instead of
unbounded buffering or per-tx event-loop stalls:

  stage 0 (``submit_nowait``, synchronous): cheap guards — size cap,
      dedup against the mempool tx cache, the committed-tx LRU, and the
      ingress's own in-flight set (a gossip re-submission records the
      extra source peer and costs nothing) — then a bounded occupancy
      check. A full pipeline REJECTS WITH BUSY (``IngressBusyError``,
      counted as shed) — explicit backpressure, never an unbounded
      queue.

  stage A (``verify_workers`` concurrent tasks): envelope parse +
      signature pre-verification. A *signed tx envelope*
      (``TxEnvelope``, prefix ``stx1``) carries (key type, pubkey,
      nonce, payload, signature); its signature is awaited through the
      VerifyHub's **backfill lane** (``crypto.verify_hub.averify_one``)
      so a tx flood fills device-sized micro-batches without ever
      displacing consensus votes from the live lane, and the hub's
      verdict cache answers gossip re-submissions before they cost a
      dispatch. Bare (non-envelope) txs skip straight through.

  stage B (single releaser, strictly ordered): verdicts flow through a
      sequence-numbered REORDER BUFFER and are admitted in arrival
      order — same-seed flood runs produce bit-identical admitted-tx
      order no matter how the hub's threads interleave. Envelope txs
      then pass their per-sender **nonce lane**: in-order admission per
      sender; an out-of-order nonce PARKS (bounded lane depth, rejected
      busy beyond it) until the gap fills or the park times out on the
      injected clock's wall domain (deterministic under a frozen
      ``ManualClock``); a nonce below the lane watermark is rejected
      stale. Finally the existing ``PriorityMempool.check_tx`` runs the
      ABCI round-trip and fee/priority insert-or-evict under the pool
      lock.

Tracing: each submission opens a trace on the injected clock; the five
stages — ``intake`` → ``verify`` → ``nonce_lane`` → ``checktx`` →
``insert`` — share boundary marks and TILE the root ``admit`` span
exactly (subsystem ``mempool.ingress``, on the PR 6 flight recorder).

Config: ``[mempool.ingress]`` (config.MempoolIngressConfig); env
mirrors TMTPU_INGRESS_DISABLE / _DEPTH / _WORKERS / _LANE_DEPTH /
_PARK_MS win over TOML, the VerifyHub contract.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import weakref
from collections import OrderedDict
from dataclasses import dataclass

from ..config import MempoolIngressConfig
from ..crypto import pubkey_from_type_and_bytes
from ..crypto import verify_hub as vh
from ..crypto.hash_hub import sha256_one
from ..libs import protoenc as pe
from ..libs import trace
from ..libs.clock import SYSTEM, Clock
from ..libs.metrics import Histogram
from ..libs.service import Service
from .pool import PriorityMempool, TxInCacheError, TxRejectedError

#: admission-latency buckets: sub-ms through flood-saturation tails
ADMIT_BUCKETS = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

#: per-sender nonce lanes kept; the least-recently-touched lane (and
#: anything still parked in it) is evicted beyond this
MAX_LANES = 8192

#: process-wide registry of live ingresses; NodeMetrics sums their
#: stats at render time (the verifyhub/ingest fold pattern)
_ingresses: "weakref.WeakSet[TxIngress]" = weakref.WeakSet()


def aggregate():
    """(summed stats, admit hist, verify hist) across live ingresses,
    or (None, None, None) when none is running."""
    ins = [i for i in _ingresses if i.is_running]
    if not ins:
        return None, None, None
    keys = ins[0].stats.keys()
    s = {k: sum(i.stats[k] for i in ins) for k in keys}
    s["depth"] = float(sum(i.occupancy for i in ins))
    # "parked" in stats is the cumulative park counter; this gauge is
    # how many txs sit parked right now
    s["parked_now"] = float(sum(i.parked_count() for i in ins))

    def fold(hists):
        counts = [0] * (len(ADMIT_BUCKETS) + 1)
        total_sum, total_count = 0.0, 0
        for h in hists:
            for j, c in enumerate(h._counts):
                counts[j] += c
            total_sum += h._sum
            total_count += h._count
        return counts, total_sum, total_count

    return (
        s,
        fold([i.admit_latency for i in ins]),
        fold([i.verify_latency for i in ins]),
    )


class IngressBusyError(ValueError):
    """Explicit backpressure: the intake pipeline (or a nonce lane) is
    full — resubmit later. RPC maps this to a busy response; gossip
    just drops (the peer will re-offer)."""


def _fail(fut: asyncio.Future, err: Exception) -> asyncio.Future:
    """Resolve a fresh future with a rejection, pre-retrieving the
    exception so fire-and-forget callers (gossip) never leak an
    'exception was never retrieved' warning."""
    fut.set_exception(err)
    fut.exception()
    return fut


# -- signed tx envelope -----------------------------------------------------

ENVELOPE_PREFIX = b"stx1"
#: domain separator for envelope signatures — an envelope signature can
#: never double as a vote/proposal/handshake signature
SIGN_DOMAIN = b"tmtpu/tx/v1\x00"


@dataclass(frozen=True)
class TxEnvelope:
    """Parsed signed tx envelope: (key_type, pubkey, nonce, payload,
    signature over SIGN_DOMAIN + nonce + payload)."""

    key_type: str
    pub_key_bytes: bytes
    nonce: int
    payload: bytes
    signature: bytes

    def sign_bytes(self) -> bytes:
        return SIGN_DOMAIN + pe.uvarint(self.nonce) + self.payload

    def pub_key(self):
        return pubkey_from_type_and_bytes(self.key_type, self.pub_key_bytes)

    @property
    def sender(self) -> bytes:
        return self.pub_key_bytes


def encode_envelope(env: TxEnvelope) -> bytes:
    return (
        ENVELOPE_PREFIX
        + pe.string_field(1, env.key_type)
        + pe.bytes_field(2, env.pub_key_bytes)
        + pe.varint_field(3, env.nonce)
        + pe.bytes_field(4, env.payload)
        + pe.bytes_field(5, env.signature)
    )


def make_signed_tx(priv_key, nonce: int, payload: bytes) -> bytes:
    """Build one signed envelope tx (tests / bench / client SDKs)."""
    env = TxEnvelope(
        key_type=priv_key.TYPE,
        pub_key_bytes=priv_key.pub_key().bytes(),
        nonce=nonce,
        payload=payload,
        signature=b"",
    )
    sig = priv_key.sign(env.sign_bytes())
    return encode_envelope(
        TxEnvelope(env.key_type, env.pub_key_bytes, nonce, payload, sig)
    )


def decode_envelope(tx: bytes) -> TxEnvelope | None:
    """Parse a signed envelope; None for bare txs (no prefix); raises
    ValueError when the prefix is present but the body is malformed."""
    if not tx.startswith(ENVELOPE_PREFIX):
        return None
    r = pe.Reader(tx[len(ENVELOPE_PREFIX):])
    # proto3 semantics: an absent varint field means 0 (nonce 0 is the
    # first nonce of a fresh sender, not a malformed envelope)
    key_type, pub, nonce, payload, sig = "", b"", 0, b"", b""
    try:
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                key_type = r.read_string()
            elif f == 2:
                pub = r.read_bytes()
            elif f == 3:
                nonce = r.read_uvarint()
            elif f == 4:
                payload = r.read_bytes()
            elif f == 5:
                sig = r.read_bytes()
            else:
                r.skip(wt)
    except Exception as e:  # noqa: BLE001 — truncated/garbage body
        raise ValueError(f"malformed tx envelope: {e!r}") from None
    if not key_type or not pub or not sig:
        raise ValueError("malformed tx envelope: missing fields")
    return TxEnvelope(key_type, pub, nonce, payload, sig)


# -- pipeline entries -------------------------------------------------------


class _TxEntry:
    __slots__ = (
        "seq", "tx", "hash", "source", "fut", "ctx", "envelope", "error",
        "t_submit", "t_pickup", "t_verified", "extra_sources", "precheck",
    )

    def __init__(self, seq, tx, hash_, source, fut, ctx, t_submit):
        self.seq = seq
        self.tx = tx
        self.hash = hash_
        self.source = source
        self.fut = fut
        self.ctx = ctx  # TraceCtx | None
        self.envelope: TxEnvelope | None = None
        self.error: Exception | None = None  # stage-A verdict
        self.t_submit = t_submit
        self.t_pickup = 0.0
        self.t_verified = 0.0
        self.extra_sources: list[str] = []
        # prefetched ABCI CheckTx response (stage-B slice micro-batch);
        # consumed by PriorityMempool.check_tx in release order
        self.precheck = None


class _NonceLane:
    """Per-sender admission lane: `next` is the watermark (None until
    the first admitted nonce); `parked` holds out-of-order arrivals
    keyed by nonce with their park deadlines (clock wall domain)."""

    __slots__ = ("next", "parked")

    def __init__(self):
        self.next: int | None = None
        self.parked: OrderedDict[int, tuple[_TxEntry, int]] = OrderedDict()


class TxIngress(Service):
    """Staged tx-admission pipeline in front of one PriorityMempool
    (see module docstring)."""

    def __init__(
        self,
        config: MempoolIngressConfig,
        mempool: PriorityMempool,
        *,
        clock: Clock | None = None,
        logger: logging.Logger | None = None,
    ):
        super().__init__("tx-ingress", logger or logging.getLogger("mempool.ingress"))

        def _knob(env_name, explicit, cast):
            v = os.environ.get(env_name)
            return cast(v) if v else explicit

        self.config = config
        self.depth = max(1, _knob("TMTPU_INGRESS_DEPTH", config.depth, int))
        self.verify_workers = max(
            1, _knob("TMTPU_INGRESS_WORKERS", config.verify_workers, int)
        )
        self.lane_depth = max(
            1, _knob("TMTPU_INGRESS_LANE_DEPTH", config.nonce_lane_depth, int)
        )
        self.park_timeout_ns = int(
            max(0.0, _knob("TMTPU_INGRESS_PARK_MS", config.nonce_park_timeout_ms, float))
            * 1e6
        )
        self.checktx_batch = max(
            1, _knob("TMTPU_INGRESS_CHECKTX_BATCH", config.checktx_batch, int)
        )
        self.mempool = mempool
        self.clock = clock or SYSTEM

        self._seq = itertools.count()
        self._intake: asyncio.Queue[_TxEntry] = asyncio.Queue(self.depth)
        self.occupancy = 0  # accepted-submit → resolved-or-parked
        self._pending: dict[bytes, _TxEntry] = {}  # hash → in-pipeline entry
        self._reorder: dict[int, _TxEntry] = {}
        self._next_release = 0
        self._release_ev = asyncio.Event()
        self._lanes: OrderedDict[bytes, _NonceLane] = OrderedDict()
        # senders whose lane currently holds parked entries: expiry runs
        # per release and must be O(parked lanes), not O(all lanes)
        self._parked_lanes: set[bytes] = set()
        # global parked-tx count: parked entries leave the occupancy
        # bound (they must not block live admission), so without this
        # cap an attacker minting fresh senders could hold up to
        # MAX_LANES * lane_depth full txs — the total parked set is
        # bounded by `depth` too (pipeline holds <= depth in flight
        # PLUS <= depth parked)
        self._parked_total = 0
        # serializes lane mutation between the releaser (_admit) and the
        # periodic sweeper (_expire_parked): both await CheckTx mid-
        # lane-update, and an interleaving could regress a watermark and
        # re-admit a nonce — the one property lanes exist to rule out
        self._lane_lock = asyncio.Lock()

        self.admit_latency = Histogram(
            "ingress_admit_latency_seconds",
            "submit-to-insert latency per admitted tx",
            buckets=ADMIT_BUCKETS,
        )
        self.verify_latency = Histogram(
            "ingress_verify_latency_seconds",
            "stage-A parse + signature pre-verify latency per tx",
            buckets=ADMIT_BUCKETS,
        )
        self.stats: dict[str, float] = {
            "submitted": 0.0,     # accepted into the pipeline
            "shed": 0.0,          # rejected busy at intake (backpressure)
            "dedup_drops": 0.0,   # duplicates dropped before any work
            "rejected": 0.0,      # size/malformed/bad-sig/stale/expired
            "sig_failed": 0.0,    # envelope signature pre-verify failures
            "parked": 0.0,        # nonce-gap arrivals parked in a lane
            "park_expired": 0.0,  # parked txs evicted on gap timeout
            "park_adopted": 0.0,  # fresh-lane parks adopted as lane start
            "stale_nonce": 0.0,   # nonce below the lane watermark
            "lane_full": 0.0,     # rejected busy: lane park depth reached
        }
        _ingresses.add(self)

    # -- lifecycle -------------------------------------------------------

    async def on_start(self) -> None:
        for i in range(self.verify_workers):
            self.spawn(self._verify_worker(), name=f"ingress.verify.{i}")
        self.spawn(self._releaser(), name="ingress.release")
        self.spawn(self._park_sweeper(), name="ingress.sweep")

    async def on_stop(self) -> None:
        # resolve everything still pending so no submitter hangs; the
        # pipeline tasks are cancelled by Service.stop after this
        err = IngressBusyError("tx ingress shutting down")
        for entry in list(self._pending.values()):
            self._resolve(entry, err, count=None)
        self._reorder.clear()
        self._lanes.clear()
        self._parked_lanes.clear()
        self._parked_total = 0

    # -- submission ------------------------------------------------------

    def submit_nowait(self, tx: bytes, source: str = "") -> asyncio.Future:
        """Enqueue one tx; the returned future resolves (None) when the
        tx is inserted into the mempool, or raises the rejection
        (awaiting the future IS the synchronous-submit API). A full
        pipeline fails fast with IngressBusyError — the backpressure
        edge — instead of buffering unboundedly."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if not self.is_running:
            # a submission racing shutdown must fail fast: entries
            # accepted with no workers would hang their futures forever
            return _fail(fut, IngressBusyError("tx ingress not running"))
        if len(tx) > self.mempool.config.max_tx_bytes:
            self.stats["rejected"] += 1
            return _fail(fut, TxRejectedError(0, f"tx too large ({len(tx)} bytes)"))
        h = sha256_one(tx)
        pending = self._pending.get(h)
        if pending is not None:
            # already in the pipeline: remember the extra source so the
            # reactor never echoes the tx back to it, drop the duplicate
            if source and source != pending.source:
                pending.extra_sources.append(source)
            self.stats["dedup_drops"] += 1
            return _fail(fut, TxInCacheError("tx already in ingress pipeline"))
        if self.mempool.cache.has(tx) or self.mempool.has_tx(h):
            if source:
                self.mempool.note_peer(h, source)
            self.stats["dedup_drops"] += 1
            return _fail(fut, TxInCacheError("tx already in cache"))
        if self.mempool.is_committed(tx):
            # the committed LRU outlives tx-cache churn under flood: a
            # gossip echo of a committed tx must not cost a pipeline
            # slot + signature verify just to die at the ABCI boundary
            self.stats["dedup_drops"] += 1
            return _fail(fut, TxInCacheError("tx already committed"))
        if self.occupancy >= self.depth:
            self.stats["shed"] += 1
            return _fail(
                fut,
                IngressBusyError(
                    f"ingress busy: {self.occupancy}/{self.depth} in flight"
                ),
            )
        ctx = trace.start(self.clock)
        # t_submit IS the root span's t0 when tracing: the five stage
        # spans share boundary marks and must tile `admit` exactly
        entry = _TxEntry(
            next(self._seq), tx, h, source, fut,
            ctx, ctx.t0 if ctx is not None else self.clock.monotonic(),
        )
        self.occupancy += 1
        self.stats["submitted"] += 1
        self._pending[h] = entry
        # cannot overflow: occupancy ≤ depth bounds queue residency too
        self._intake.put_nowait(entry)
        return fut

    # -- stage A: parse + signature pre-verify ---------------------------

    async def _verify_worker(self) -> None:
        while True:
            entry = await self._intake.get()
            entry.t_pickup = self.clock.monotonic()
            trace.record(
                entry.ctx, "mempool.ingress", "intake",
                entry.t_submit, entry.t_pickup,
            )
            try:
                env = decode_envelope(entry.tx)
                if env is not None:
                    entry.envelope = env
                    ok = await vh.averify_one(
                        env.pub_key(), env.sign_bytes(), env.signature,
                        lane=vh.LANE_BACKFILL, trace_ctx=entry.ctx,
                    )
                    if not ok:
                        entry.error = TxRejectedError(1, "invalid envelope signature")
                        self.stats["sig_failed"] += 1
            except asyncio.CancelledError:
                raise
            except ValueError as e:
                entry.error = TxRejectedError(1, str(e))
            except Exception as e:  # noqa: BLE001 — unknown key type etc.
                entry.error = TxRejectedError(1, f"envelope verify failed: {e!r}")
            entry.t_verified = self.clock.monotonic()
            self.verify_latency.observe(entry.t_verified - entry.t_pickup)
            trace.record(
                entry.ctx, "mempool.ingress", "verify",
                entry.t_pickup, entry.t_verified,
                signed=entry.envelope is not None,
            )
            self._reorder[entry.seq] = entry
            self._release_ev.set()

    # -- stage B: in-order release → nonce lane → checktx/insert ---------

    async def _releaser(self) -> None:
        """Single releaser: admissions happen strictly in release order.
        With checktx_batch > 1, consecutive ready entries form a SLICE
        whose ABCI CheckTx calls are prefetched concurrently (the
        mempool `_recheck` shape) before the serial in-order admission
        consumes them — the per-tx ABCI round-trip cost collapses to
        one RTT per slice on remote-socket apps, while insert order,
        nonce-lane semantics, and same-seed bit-reproducibility are
        untouched (width 1 is byte-for-byte today's serial path,
        asserted in tests)."""
        while True:
            while self._next_release not in self._reorder:
                self._release_ev.clear()
                await self._release_ev.wait()
            entries = [self._reorder.pop(self._next_release)]
            self._next_release += 1
            while (
                len(entries) < self.checktx_batch
                and self._next_release in self._reorder
            ):
                entries.append(self._reorder.pop(self._next_release))
                self._next_release += 1
            if len(entries) > 1:
                await self._prefetch_checktx(entries)
            for entry in entries:
                await self._expire_parked()
                await self._admit(entry)

    async def _prefetch_checktx(self, entries: list[_TxEntry]) -> None:
        """Issue the slice's ABCI CheckTx calls concurrently and stash
        the responses on the entries. Only entries the serial path will
        plausibly admit prefetch: errored stage-A entries never reach
        CheckTx, and `_would_skip_checktx` filters the doomed/parking
        cases (stale nonce, out-of-order park, cache duplicate) so a
        flood of rejects doesn't translate into wasted app round-trips
        — the filter is a HEURISTIC (lane state can shift while the
        slice admits); a wrong skip just means one inline RTT later,
        never a wrong verdict. A prefetch failure likewise leaves
        `precheck` unset and the serial path re-issues inline.
        Staleness note: a commit landing mid-slice can make a
        prefetched verdict stale, the exact window `_recheck` already
        accepts; the committed-tx re-check under the pool lock still
        prevents resurrection."""

        async def fetch(entry: _TxEntry):
            try:
                entry.precheck = await self.mempool.precheck(entry.tx)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — serial path re-issues
                entry.precheck = None

        await asyncio.gather(
            *(
                fetch(e)
                for e in entries
                if e.error is None and not self._would_skip_checktx(e)
            )
        )

    def _would_skip_checktx(self, entry: _TxEntry) -> bool:
        """Best-effort predictor of 'this entry never reaches CheckTx in
        serial admission': cache/committed duplicates reject at the
        pool, nonce-laned entries that are stale or out-of-order reject
        or park (and parked entries drop their prefetch anyway)."""
        if self.mempool.is_committed(entry.tx) or self.mempool.cache.has(entry.tx):
            return True
        env = entry.envelope
        if env is None:
            return False
        lane = self._lanes.get(env.sender)
        nxt = lane.next if lane is not None else None
        if nxt is None:
            return env.nonce != 0  # fresh lane parks any nonzero nonce
        return env.nonce != nxt  # stale (reject) or gap (park) alike

    async def _admit(self, entry: _TxEntry) -> None:
        if entry.error is not None:
            self.stats["rejected"] += 1
            self._finish_trace(entry, outcome="rejected")
            self._resolve(entry, entry.error)
            return
        env = entry.envelope
        if env is None:
            await self._check_and_insert(entry)
            return
        async with self._lane_lock:
            await self._admit_laned(entry, env)

    async def _admit_laned(self, entry: _TxEntry, env: TxEnvelope) -> None:
        lane = self._lanes.get(env.sender)
        if lane is None:
            lane = self._lanes[env.sender] = _NonceLane()
            self._evict_excess_lanes()
        else:
            self._lanes.move_to_end(env.sender)
        if lane.next is not None and env.nonce < lane.next:
            self.stats["stale_nonce"] += 1
            self.stats["rejected"] += 1
            self._finish_trace(entry, outcome="stale_nonce")
            self._resolve(
                entry,
                TxRejectedError(
                    1, f"stale nonce {env.nonce} (lane watermark {lane.next})"
                ),
            )
            return
        if (lane.next is None and env.nonce != 0) or (
            lane.next is not None and env.nonce > lane.next
        ):
            # gap: park (bounded) until the missing nonce admits or the
            # park times out on the injected clock's wall domain. A
            # FRESH lane (no watermark yet) parks any nonzero nonce —
            # gossip may deliver a sender's txs out of order, and
            # admitting nonce k first would reject 0..k-1 as stale
            # forever; on park timeout the lane ADOPTS its lowest parked
            # nonce as the start instead (see _expire_parked).
            if env.nonce in lane.parked:
                self.stats["dedup_drops"] += 1
                self._finish_trace(entry, outcome="dup_nonce")
                self._resolve(
                    entry, TxRejectedError(1, f"nonce {env.nonce} already parked")
                )
                return
            if len(lane.parked) >= self.lane_depth:
                self.stats["lane_full"] += 1
                self._finish_trace(entry, outcome="lane_full")
                self._resolve(
                    entry,
                    IngressBusyError(
                        f"nonce lane full ({len(lane.parked)} parked)"
                    ),
                )
                return
            if self._parked_total >= self.depth:
                # global park capacity: fresh-sender floods must not
                # sidestep the depth bound through the parked set
                self.stats["shed"] += 1
                self._finish_trace(entry, outcome="park_capacity")
                self._resolve(
                    entry,
                    IngressBusyError(
                        f"park capacity exhausted ({self._parked_total} parked)"
                    ),
                )
                return
            # a parked entry admits at an arbitrarily later release:
            # its slice-prefetched CheckTx verdict would be stale by
            # whole blocks — drop it, the drain path re-issues
            entry.precheck = None
            lane.parked[env.nonce] = (
                entry, self.clock.now_ns() + self.park_timeout_ns
            )
            self._parked_lanes.add(env.sender)
            self._parked_total += 1
            self.stats["parked"] += 1
            # the parked tx leaves the bounded pipeline (its own lane
            # depth bounds it now); the future stays pending
            self.occupancy -= 1
            return
        # in order (or the lane's first tx): admit, then drain any
        # parked successors the admission just unblocked
        admitted = await self._check_and_insert(entry)
        if admitted:
            lane.next = env.nonce + 1
            await self._drain_parked(env.sender, lane)

    async def _drain_parked(self, sender: bytes, lane: _NonceLane) -> None:
        while lane.next in lane.parked:
            entry, _deadline = lane.parked.pop(lane.next)
            self._parked_total -= 1
            # a parked entry released its occupancy slot when it parked
            if await self._check_and_insert(entry, holds_slot=False):
                lane.next += 1
            else:
                break  # failed nonce does not advance the watermark
        if not lane.parked:
            self._parked_lanes.discard(sender)

    async def _check_and_insert(
        self, entry: _TxEntry, *, holds_slot: bool = True
    ) -> bool:
        slot = True if holds_slot else None
        t_lane_end = self.clock.monotonic()
        trace.record(
            entry.ctx, "mempool.ingress", "nonce_lane",
            entry.t_verified, t_lane_end,
        )
        if entry.ctx is not None:
            entry.ctx.marks["checktx_start"] = t_lane_end
        pre, entry.precheck = entry.precheck, None  # consume-once
        try:
            await self.mempool.check_tx(
                entry.tx, sender=entry.source, trace_ctx=entry.ctx, pre=pre
            )
        except asyncio.CancelledError:
            raise
        except TxInCacheError as e:
            self.stats["dedup_drops"] += 1
            self._finish_trace(entry, outcome="dup")
            self._resolve(entry, e, count=slot)
            return False
        except ValueError as e:  # TxRejectedError, MempoolFullError
            self._finish_trace(entry, outcome="rejected")
            self._resolve(entry, e, count=slot)
            return False
        except Exception as e:  # noqa: BLE001 — app-conn failures etc.
            # anything else (ABCI socket drop, app crash) must reject
            # THIS tx, never kill the single releaser task — a dead
            # releaser wedges all admission until node restart
            self.logger.warning(
                "checktx errored (%r); rejecting tx %s",
                e, entry.hash.hex()[:12],
            )
            self.stats["rejected"] += 1
            self._finish_trace(entry, outcome="error")
            self._resolve(entry, TxRejectedError(1, f"checktx error: {e!r}"), count=slot)
            return False
        for s in entry.extra_sources:
            self.mempool.note_peer(entry.hash, s)
        end = (
            entry.ctx.marks.get("insert_end") if entry.ctx is not None else None
        )
        self._finish_trace(entry, outcome="admitted", end=end)
        self.admit_latency.observe(self.clock.monotonic() - entry.t_submit)
        self._resolve(entry, None, count=slot)
        return True

    # -- nonce-lane maintenance ------------------------------------------

    async def _expire_parked(self) -> None:
        """Resolve parked txs whose gap never filled inside the park
        timeout: an ESTABLISHED lane evicts them (the missing nonce is
        the sender's problem — all successors are unusable), a FRESH
        lane (watermark never known) instead ADOPTS its lowest parked
        nonce as the lane start and admits from there. Runs at every
        in-order release and from the periodic sweeper; deadlines live
        on the injected clock's wall domain, so a frozen ManualClock
        never expires anything mid-flood and same-seed runs stay
        bit-identical. Scans only lanes that actually hold parked txs
        (the _parked_lanes index), so the per-release call stays O(1)
        for the overwhelmingly common no-gaps flood. Holds the lane
        lock: the sweeper and the releaser must never interleave their
        mid-await lane updates."""
        async with self._lane_lock:
            await self._expire_parked_locked()

    async def _expire_parked_locked(self) -> None:
        now = self.clock.now_ns()
        for sender in list(self._parked_lanes):
            lane = self._lanes.get(sender)
            if lane is None or not lane.parked:
                self._parked_lanes.discard(sender)
                continue
            while lane.parked:
                # arrival order == deadline order (constant park timeout)
                nonce, (entry, deadline) = next(iter(lane.parked.items()))
                if deadline > now:
                    break
                if lane.next is None:
                    # fresh lane timed out waiting for nonce 0: adopt the
                    # lowest parked nonce as the start and drain upward
                    low = min(lane.parked)
                    entry, _deadline = lane.parked.pop(low)
                    self._parked_total -= 1
                    self.stats["park_adopted"] += 1
                    if await self._check_and_insert(entry, holds_slot=False):
                        lane.next = low + 1
                        await self._drain_parked(sender, lane)
                    # loop again: leftovers past a remaining gap now sit
                    # in an established lane and expire by eviction
                    continue
                del lane.parked[nonce]
                self._parked_total -= 1
                self.stats["park_expired"] += 1
                self.stats["rejected"] += 1
                self._finish_trace(entry, outcome="park_expired")
                self._resolve(
                    entry,
                    TxRejectedError(1, f"nonce gap timed out (parked {nonce})"),
                    count=None,  # occupancy was released at park time
                )
            if not lane.parked:
                self._parked_lanes.discard(sender)

    def _evict_excess_lanes(self) -> None:
        while len(self._lanes) > MAX_LANES:
            sender, lane = self._lanes.popitem(last=False)
            self._parked_lanes.discard(sender)
            self._parked_total -= len(lane.parked)
            for entry, _deadline in lane.parked.values():
                # same accounting as a gap timeout: counted rejected and
                # the admit trace closed, so floods of many senders never
                # lose spans or undercount mempool_tx_rejected
                self.stats["park_expired"] += 1
                self.stats["rejected"] += 1
                self._finish_trace(entry, outcome="lane_evicted")
                self._resolve(
                    entry, IngressBusyError("nonce lane evicted"), count=None
                )

    async def _park_sweeper(self) -> None:
        interval = max(0.05, self.park_timeout_ns / 4e9)
        while True:
            await asyncio.sleep(interval)
            await self._expire_parked()

    # -- bookkeeping -----------------------------------------------------

    def _resolve(self, entry: _TxEntry, err, count: bool | None = True) -> None:
        """Terminal outcome for an entry: resolve its future, drop it
        from the pending map, and (unless `count is None`) release its
        occupancy slot."""
        if count is not None:
            self.occupancy = max(0, self.occupancy - 1)
        self._pending.pop(entry.hash, None)
        if entry.fut.done():
            return  # stop() raced a normal resolution
        if err is None:
            entry.fut.set_result(None)
        else:
            entry.fut.set_exception(err)
            # a gossip caller may never await rejection futures; mark
            # the exception retrieved so the loop doesn't log leaks
            entry.fut.exception()

    def _finish_trace(self, entry: _TxEntry, *, outcome: str, end=None) -> None:
        if entry.ctx is None:
            return
        trace.record(
            entry.ctx, "mempool.ingress", "admit",
            entry.ctx.t0,
            end if end is not None else entry.ctx.clock.monotonic(),
            outcome=outcome,
        )

    def parked_count(self) -> int:
        return self._parked_total
