"""Private validator — signs votes and proposals, guards against
double-signing (reference privval/file.go).

`PrivValidator` is the signing interface consumed by the consensus state
machine (reference types/priv_validator.go:28). `FilePV` persists the key
and the last-sign-state to disk; the last-sign-state file is written
*before* a signature is released so a crashed-and-restarted validator can
never sign conflicting votes for the same (height, round, step)
(reference privval/file.go:152, signVote/signProposal guards).

The remote-signer endpoints (socket protocol, the analog of
privval/signer_listener_endpoint.go) live in privval_remote.py.
"""

from __future__ import annotations

import json
import os
import tempfile

from .crypto import ed25519, pubkey_from_type_and_bytes
from .types.keys import SignedMsgType
from .types.vote import Proposal, Vote

# sign-state steps (reference privval/file.go:33-37)
STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_TO_STEP = {
    SignedMsgType.PREVOTE: STEP_PREVOTE,
    SignedMsgType.PRECOMMIT: STEP_PRECOMMIT,
}


class DoubleSignError(RuntimeError):
    pass


class PrivValidator:
    """Signing interface (reference types/priv_validator.go:28)."""

    def get_pub_key(self):
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """Sign and return the vote with its signature (and possibly the
        timestamp of a previously-signed identical vote) filled in."""
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        raise NotImplementedError


class MockPV(PrivValidator):
    """In-memory signer without persistence — the test double (reference
    types/priv_validator.go MockPV). No double-sign protection unless
    `guard` is set."""

    def __init__(self, priv_key=None, *, guard: bool = False):
        self.priv_key = priv_key or ed25519.Ed25519PrivKey.generate()
        self._guard = _SignState() if guard else None

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        sb = vote.sign_bytes(chain_id)
        if self._guard is not None:
            reuse = self._guard.check_vote(vote, sb)
            if reuse is not None:
                sig, ts = reuse
                return Vote(
                    **{**vote.__dict__, "signature": sig, "timestamp_ns": ts}
                )
        sig = self.priv_key.sign(sb)
        if self._guard is not None:
            self._guard.record(
                vote.height, vote.round, _VOTE_TO_STEP[vote.type], sb, sig
            )
        return Vote(**{**vote.__dict__, "signature": sig})

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        sb = proposal.sign_bytes(chain_id)
        sig = self.priv_key.sign(sb)
        return Proposal(**{**proposal.__dict__, "signature": sig})


class _SignState:
    """Last-sign-state with the three-way outcome of the reference's
    CheckHRS (privval/file.go:86): new HRS → sign; same HRS + same
    sign-bytes → return the old signature (idempotent re-sign after a
    crash); same HRS + different sign-bytes → double-sign panic."""

    def __init__(self):
        self.height = 0
        self.round = 0
        self.step = STEP_NONE
        self.sign_bytes: bytes = b""
        self.signature: bytes = b""

    def _cmp(self, height: int, round_: int, step: int) -> int:
        mine = (self.height, self.round, self.step)
        theirs = (height, round_, step)
        return (theirs > mine) - (theirs < mine)

    def check_vote(self, vote: Vote, sb: bytes) -> tuple[bytes, int] | None:
        """Returns (signature, timestamp_ns) of a previous signing to
        reuse — the caller must emit the vote with THAT timestamp, since
        the signature covers it — or None to sign fresh. Raises
        DoubleSignError on a conflicting regression."""
        step = _VOTE_TO_STEP[vote.type]
        c = self._cmp(vote.height, vote.round, step)
        if c > 0:
            return None
        if c == 0:
            if sb == self.sign_bytes and self.signature:
                return self.signature, vote.timestamp_ns
            # same HRS, differing only in timestamp is also a legal
            # re-sign: reuse the old signature AND its timestamp
            if self.signature:
                old_ts = _timestamp_only_diff(self.sign_bytes, sb, field=5)
                if old_ts is not None:
                    return self.signature, old_ts
            raise DoubleSignError(
                f"conflicting vote at height/round/step "
                f"{vote.height}/{vote.round}/{step}"
            )
        raise DoubleSignError(
            f"sign-state regression: have {self.height}/{self.round}/{self.step}, "
            f"asked to sign {vote.height}/{vote.round}/{step}"
        )

    def check_proposal(self, proposal: Proposal, sb: bytes) -> tuple[bytes, int] | None:
        c = self._cmp(proposal.height, proposal.round, STEP_PROPOSE)
        if c > 0:
            return None
        if c == 0:
            if sb == self.sign_bytes and self.signature:
                return self.signature, proposal.timestamp_ns
            if self.signature:
                old_ts = _timestamp_only_diff(self.sign_bytes, sb, field=6)
                if old_ts is not None:
                    return self.signature, old_ts
            raise DoubleSignError(
                f"conflicting proposal at {proposal.height}/{proposal.round}"
            )
        raise DoubleSignError("proposal sign-state regression")

    def record(self, height: int, round_: int, step: int, sb: bytes, sig: bytes):
        self.height, self.round, self.step = height, round_, step
        self.sign_bytes, self.signature = sb, sig


def _timestamp_only_diff(old_sb: bytes, new_sb: bytes, *, field: int) -> int | None:
    """If the two canonical sign-bytes differ only in their timestamp
    field, return the OLD timestamp (whose signature is reusable), else
    None (reference privval/file.go checkVotesOnlyDifferByTimestamp /
    checkProposalsOnlyDifferByTimestamp)."""
    from .types import canonical

    try:
        a, old_ts = canonical.strip_timestamp(old_sb, field=field)
        b, _ = canonical.strip_timestamp(new_sb, field=field)
    except Exception:
        return None
    return old_ts if a == b else None


class FilePV(PrivValidator):
    """File-backed validator key + last-sign-state (reference
    privval/file.go:152). Two JSON files, like the reference's
    priv_validator_key.json / priv_validator_state.json."""

    def __init__(self, priv_key, key_path: str, state_path: str):
        self.priv_key = priv_key
        self.key_path = key_path
        self.state_path = state_path
        self.last_sign_state = _SignState()

    # -- construction ----------------------------------------------------

    @classmethod
    def generate(
        cls, key_path: str, state_path: str, *, key_type: str = "ed25519"
    ) -> "FilePV":
        if key_type == "ed25519":
            priv = ed25519.Ed25519PrivKey.generate()
        elif key_type == "secp256k1":
            from .crypto import secp256k1

            priv = secp256k1.Secp256k1PrivKey.generate()
        else:
            raise ValueError(f"unsupported validator key type {key_type!r}")
        pv = cls(priv, key_path, state_path)
        pv.save()
        return pv

    @classmethod
    def load(cls, key_path: str, state_path: str) -> "FilePV":
        with open(key_path) as f:
            kd = json.load(f)
        key_type = kd.get("type", "ed25519")
        if key_type == "ed25519":
            priv = ed25519.Ed25519PrivKey(bytes.fromhex(kd["priv_key"])[:32])
        else:
            from .crypto import secp256k1

            priv = secp256k1.Secp256k1PrivKey(bytes.fromhex(kd["priv_key"]))
        pv = cls(priv, key_path, state_path)
        if os.path.exists(state_path):
            with open(state_path) as f:
                sd = json.load(f)
            ss = pv.last_sign_state
            ss.height = sd.get("height", 0)
            ss.round = sd.get("round", 0)
            ss.step = sd.get("step", STEP_NONE)
            ss.sign_bytes = bytes.fromhex(sd.get("sign_bytes", ""))
            ss.signature = bytes.fromhex(sd.get("signature", ""))
        return pv

    @classmethod
    def load_or_generate(cls, key_path: str, state_path: str) -> "FilePV":
        if os.path.exists(key_path):
            return cls.load(key_path, state_path)
        return cls.generate(key_path, state_path)

    # -- persistence -----------------------------------------------------

    def save(self) -> None:
        self._atomic_write(
            self.key_path,
            {
                "address": self.priv_key.pub_key().address().hex(),
                "pub_key": self.priv_key.pub_key().bytes().hex(),
                "priv_key": self.priv_key.bytes().hex(),
                "type": "ed25519"
                if isinstance(self.priv_key, ed25519.Ed25519PrivKey)
                else "secp256k1",
            },
        )
        self._save_state()

    def _save_state(self) -> None:
        ss = self.last_sign_state
        self._atomic_write(
            self.state_path,
            {
                "height": ss.height,
                "round": ss.round,
                "step": ss.step,
                "sign_bytes": ss.sign_bytes.hex(),
                "signature": ss.signature.hex(),
            },
        )

    @staticmethod
    def _atomic_write(path: str, obj: dict) -> None:
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".pv-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(obj, f, indent=2)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    # -- signing ---------------------------------------------------------

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        sb = vote.sign_bytes(chain_id)
        reuse = self.last_sign_state.check_vote(vote, sb)
        if reuse is not None:
            sig, ts = reuse
            return Vote(**{**vote.__dict__, "signature": sig, "timestamp_ns": ts})
        sig = self.priv_key.sign(sb)
        # persist the sign-state BEFORE releasing the signature
        self.last_sign_state.record(
            vote.height, vote.round, _VOTE_TO_STEP[vote.type], sb, sig
        )
        self._save_state()
        return Vote(**{**vote.__dict__, "signature": sig})

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        sb = proposal.sign_bytes(chain_id)
        reuse = self.last_sign_state.check_proposal(proposal, sb)
        if reuse is not None:
            sig, ts = reuse
            return Proposal(
                **{**proposal.__dict__, "signature": sig, "timestamp_ns": ts}
            )
        sig = self.priv_key.sign(sb)
        self.last_sign_state.record(
            proposal.height, proposal.round, STEP_PROPOSE, sb, sig
        )
        self._save_state()
        return Proposal(**{**proposal.__dict__, "signature": sig})
