"""LightFleet — LightD, the mass light-client serving layer.

``light/`` has had a correct client (bisection verifier, witness
cross-check, divergence detector) since the seed; what it never had is a
SERVING layer. A full node asked the same "prove the chain up to height
H" question by a million light clients answers it a million times — each
answer a skipping-verification hop of ~150 signatures. LightD closes
that gap in the verifyd/ingress mold: one in-process service that owns a

  * **verified-hop cache**: skipping-verification checkpoints are
    verified ONCE (through the VerifyHub's backfill lane, so fleet
    traffic can never displace live consensus votes) and then served to
    every client. N clients syncing to tip share one verification of
    each hop instead of N x 150 signatures. Same-height concurrent
    syncs COALESCE onto one in-flight verification (the hub's
    coalescing shape, one level up);

  * **aggregate hop proofs**: when the committee signs with BLS, the
    hop target's commit is folded via the existing
    ``types.block.aggregate_commit`` machinery into ONE 96-byte G2
    aggregate plus the flag bitmap the per-validator entries already
    carry — verified through the ``crypto.verify_hub.verify_aggregate``
    chokepoint (one pairing product instead of 150 signature checks,
    the arXiv:2302.00418 committee-scale light-verification win), with
    a per-signature fallback for non-BLS committees. The folded commit
    IS the wire format a re-verifying client consumes (``HopProof``);

  * **bounded concurrency with explicit busy-shed**: at most
    ``max_sessions`` verification sessions run at once; an arrival
    beyond that is REJECTED WITH BUSY (``LightDBusyError``, counted as
    shed) — the ingress backpressure contract: never an unbounded
    queue. Cache hits and coalesced joins are not sessions and never
    shed;

  * ``lightd_*`` metrics (process-wide registry folded into /metrics at
    render time, the ingress pattern) and ``light.sync`` trace spans on
    the flight recorder.

Deployment shape: one LightD per serving point (gateway/POP), its
primary pointed at a full node it need not trust, witnesses pointed at
independent nodes. Clients either trust their LightD (it runs the full
divergence detector on their behalf — a detected light-client attack
raises ``Divergence`` and forms ``LightClientAttackEvidence`` exactly
like the embedded client) or re-verify the served ``HopProof`` chain
themselves at one pairing per hop.

Env knobs (override config, the VerifyHub contract):
TMTPU_LIGHTD_SESSIONS, TMTPU_LIGHTD_PROOF_CACHE,
TMTPU_LIGHTD_AGG_HOPS=0 (serve per-sig hops even for BLS committees).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import weakref
from dataclasses import dataclass

from ..libs import protoenc as pe
from ..libs import trace
from ..libs.metrics import Histogram
from ..libs.service import Service
from ..types.block import aggregate_commit
from . import verifier
from .client import Divergence, LightClient, TrustedStore, TrustOptions
from .provider import Provider
from .types import LightBlock, SignedHeader

logger = logging.getLogger("light.fleet")

#: hop-proof schemes (per-scheme attribution on rejection)
SCHEME_AGGREGATE = "bls-aggregate"
SCHEME_PER_SIG = "per-sig"

#: sync-latency buckets: a warm hop-cache hit is sub-ms; a cold
#: committee-scale hop on the CPU fallback runs seconds
SYNC_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: process-wide registry of live LightDs; NodeMetrics folds their stats
#: at render time (the ingress/verifyhub pattern)
_lightds: "weakref.WeakSet[LightD]" = weakref.WeakSet()


def aggregate():
    """(summed stats, folded sync-latency hist) across live LightDs, or
    (None, None) when none is running."""
    ds = [d for d in _lightds if d.is_running]
    if not ds:
        return None, None
    keys = ds[0].stats.keys()
    s = {k: sum(d.stats[k] for d in ds) for k in keys}
    s["sessions_now"] = float(sum(d.active_sessions for d in ds))
    counts = [0] * (len(SYNC_BUCKETS) + 1)
    total_sum, total_count = 0.0, 0
    for d in ds:
        h = d.sync_latency
        for j, c in enumerate(h._counts):
            counts[j] += c
        total_sum += h._sum
        total_count += h._count
    return s, (counts, total_sum, total_count)


class LightDBusyError(Exception):
    """Explicit backpressure: every verification session slot is taken —
    back off and resubmit. The RPC proxy maps this to the busy contract
    (`light.proxy.LIGHT_BUSY_CODE`, the MEMPOOL_BUSY_CODE pattern);
    nothing was queued."""


class HopProofError(ValueError):
    """A hop proof failed verification. The message leads with the
    scheme tag (``[bls-aggregate]`` / ``[per-sig]``) so a rejection is
    attributable to the pairing path vs the per-signature path from the
    error alone."""

    def __init__(self, scheme: str, detail: str):
        super().__init__(f"[{scheme}] {detail}")
        self.scheme = scheme


@dataclass(frozen=True)
class HopProof:
    """One trusted-header hop, self-contained: the target light block
    (validators + signed header) whose commit is either the BLS
    aggregate wire variant (`agg_sig` set: one 96-byte aggregate, the
    CommitSig flags as the signer bitmap, per-entry signatures empty)
    or the plain per-signature form. A client holding any trusted block
    the hop's skipping rules accept re-verifies it at one pairing (or
    one signature batch) via `verify_hop_proof`."""

    block: LightBlock
    scheme: str

    @property
    def height(self) -> int:
        return self.block.height

    def wire_bytes(self) -> int:
        return len(self.encode())

    def encode(self) -> bytes:
        # memoized (the evidence pattern — safe on a frozen dataclass):
        # a cached proof is served encode()d on every RPC hit, and the
        # encoding covers a committee-scale validator set + commit
        enc = self.__dict__.get("_enc")
        if enc is None:
            enc = pe.message_field(1, self.block.encode()) + pe.string_field(
                2, self.scheme
            )
            object.__setattr__(self, "_enc", enc)
        return enc

    @classmethod
    def decode(cls, data: bytes) -> "HopProof":
        r = pe.Reader(data)
        block = None
        scheme = ""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                block = LightBlock.decode(r.read_bytes())
            elif f == 2:
                scheme = r.read_bytes().decode()
            else:
                r.skip(wt)
        return cls(block, scheme)

    def validate_basic(self, chain_id: str) -> None:
        if self.block is None:
            raise HopProofError(self.scheme or "?", "missing light block")
        if self.scheme not in (SCHEME_AGGREGATE, SCHEME_PER_SIG):
            raise HopProofError(self.scheme or "?", "unknown hop-proof scheme")
        is_agg = self.block.signed_header.commit.is_aggregate()
        if is_agg != (self.scheme == SCHEME_AGGREGATE):
            # a proof lying about its own scheme must die before any
            # crypto: the claimed scheme drives attribution AND the
            # expected wire shape
            raise HopProofError(
                self.scheme,
                "scheme tag does not match the commit wire form "
                f"(agg_sig {'present' if is_agg else 'absent'})",
            )
        self.block.validate_basic(chain_id)


def make_hop_proof(block: LightBlock, *, aggregate_hops: bool = True) -> HopProof:
    """Fold one verified hop target into its wire proof: the BLS
    aggregate variant when every participating signer is BLS (pure data
    transformation — `types.block.aggregate_commit` sums the very
    signatures the validators gossiped), the per-signature form
    otherwise (mixed/Edwards committees — the fallback)."""
    from ..crypto import hash_hub

    with hash_hub.lane_ctx(hash_hub.LANE_LIGHT):
        commit = block.signed_header.commit
        if aggregate_hops:
            try:
                agg = aggregate_commit(commit, block.validators)
                if agg is not commit:
                    block = LightBlock(
                        SignedHeader(block.header, agg), block.validators
                    )
                return HopProof(block, SCHEME_AGGREGATE)
            except ValueError:
                pass  # non-BLS committee: per-sig fallback below
        if commit.is_aggregate():
            return HopProof(block, SCHEME_AGGREGATE)
        return HopProof(block, SCHEME_PER_SIG)


def verify_hop_proof(
    chain_id: str,
    trusted: LightBlock,
    proof: HopProof,
    trusting_period_ns: int,
    now_ns: int | None = None,
    *,
    trust_level=verifier.DEFAULT_TRUST_LEVEL,
) -> LightBlock:
    """Client-side re-verification of one served hop against a trusted
    block: the standard skipping/adjacent rules (light/verifier.py),
    whose commit check routes through `verify_hub.verify_aggregate` for
    aggregate proofs (one pairing product + the shared verdict cache)
    and the batched per-sig path otherwise. Raises `HopProofError`
    carrying the scheme tag, so a tampered aggregate is attributable to
    the pairing path and a tampered signature to the per-sig path."""
    from ..crypto import hash_hub

    with hash_hub.lane_ctx(hash_hub.LANE_LIGHT):
        proof.validate_basic(chain_id)
        try:
            verifier.verify(
                chain_id,
                trusted,
                proof.block,
                trusting_period_ns,
                now_ns,
                trust_level=trust_level,
            )
        except verifier.VerificationError as e:
            raise HopProofError(proof.scheme, str(e)) from e
        return proof.block


class _HopProvider(Provider):
    """LightD's view of its primary: light blocks pass through
    `make_hop_proof` folding BEFORE verification, so a BLS committee's
    hop is verified as ONE aggregate (through the verify_aggregate
    chokepoint the validation funnel routes aggregate commits to) and
    the verified-hop cache persists exactly the bytes `hop_proof`
    serves. Per-sig committees pass through untouched."""

    def __init__(self, inner: Provider, owner: "LightD"):
        self.inner = inner
        self.owner = owner

    def __repr__(self) -> str:
        return f"_HopProvider({self.inner!r})"

    def chain_id(self) -> str:
        return self.inner.chain_id()

    async def light_block(self, height: int) -> LightBlock:
        lb = await self.inner.light_block(height)
        if not self.owner.aggregate_hops:
            return lb
        proof = make_hop_proof(lb, aggregate_hops=True)
        if proof.scheme == SCHEME_AGGREGATE:
            self.owner.stats["agg_hops"] += 1
            return proof.block
        self.owner.stats["per_sig_hops"] += 1
        return lb

    async def report_evidence(self, evidence) -> None:
        await self.inner.report_evidence(evidence)


class _CountingStore(TrustedStore):
    """The verified-hop cache: every save is one checkpoint verified by
    THIS LightD (never by a client). Hit/miss accounting lives at the
    `sync` entry point — the embedded client's own store reads during a
    session must not double-count."""

    def __init__(self, owner: "LightD", db=None):
        super().__init__(db)
        self._owner = owner

    def save(self, lb) -> None:
        from .client import _LB_PREFIX

        # re-saves don't count (the client persists the sync target both
        # via its pending buffer and as the verified head)
        if not self.db.has(_LB_PREFIX + lb.height.to_bytes(8, "big")):
            self._owner.stats["hops_verified"] += 1
        super().save(lb)


class LightD(Service):
    """The light-client serving daemon (module docstring). Owns one
    embedded LightClient whose trusted store is the verified-hop cache;
    every public entry point is async and safe to call concurrently."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: list[Provider] | None = None,
        *,
        config=None,
        store_db=None,
        logger_: logging.Logger | None = None,
    ):
        super().__init__("lightd", logger_ or logger)
        from ..config import LightDConfig

        cfg = config or LightDConfig()

        def _knob(env_name, default, cast):
            v = os.environ.get(env_name)
            return cast(v) if v else default

        self.max_sessions = max(
            1, _knob("TMTPU_LIGHTD_SESSIONS", cfg.max_sessions, int)
        )
        self.proof_cache_size = max(
            0, _knob("TMTPU_LIGHTD_PROOF_CACHE", cfg.proof_cache, int)
        )
        self.aggregate_hops = _knob(
            "TMTPU_LIGHTD_AGG_HOPS",
            cfg.aggregate_hops,
            lambda v: v.lower() not in ("0", "false", "no"),
        )
        self.chain_id = chain_id
        self.store = _CountingStore(self, store_db)
        self.client = LightClient(
            chain_id,
            trust_options,
            _HopProvider(primary, self),
            witnesses,
            store=self.store,
            sequential=cfg.sequential,
            logger=self.logger,
        )
        self.active_sessions = 0
        #: height -> future of an in-flight verification: concurrent
        #: same-height syncs coalesce onto one session
        self._inflight: dict[int, asyncio.Future] = {}
        #: height -> HopProof with its encoding memoized (bounded,
        #: insertion-evicted)
        self._proofs: dict[int, HopProof] = {}
        self.sync_latency = Histogram(
            "lightd_sync_latency_seconds",
            "request-to-verified-verdict latency per sync",
            buckets=SYNC_BUCKETS,
        )
        self.stats = {
            "syncs": 0.0,            # sync requests received (incl. shed)
            "sheds": 0.0,            # rejected-with-busy at the session bound
            "coalesced": 0.0,        # joined an in-flight same-height session
            "hop_cache_hits": 0.0,   # store gets answered without verification
            "hop_cache_misses": 0.0,
            "hops_verified": 0.0,    # checkpoints verified + persisted by LightD
            "agg_hops": 0.0,         # hops folded to the BLS aggregate form
            "per_sig_hops": 0.0,     # hops served per-sig (fallback)
            "proofs_served": 0.0,
            "proof_cache_hits": 0.0,
            "divergences": 0.0,      # witness cross-check caught an attack
        }
        _lightds.add(self)

    async def on_start(self) -> None:
        pass

    async def on_stop(self) -> None:
        for fut in self._inflight.values():
            if not fut.done():
                fut.cancel()
        self._inflight.clear()

    # -- serving surface -------------------------------------------------

    async def sync(self, height: int = 0, now_ns: int | None = None) -> LightBlock:
        """Verified light block at `height` (0 = primary tip): the hop
        cache answers warm heights with zero verification; a cold height
        coalesces onto any in-flight same-height session or claims a
        bounded session slot (busy-shed beyond `max_sessions`)."""
        self.stats["syncs"] += 1
        t0 = time.monotonic()
        with trace.span("light", "sync", height=height) as sp:
            if height:
                hit = self.store.get(height)
                if hit is not None:
                    self.stats["hop_cache_hits"] += 1
                    sp.set(outcome="cache_hit")
                    self.sync_latency.observe(time.monotonic() - t0)
                    return hit
            fut = self._inflight.get(height)
            if fut is not None:
                self.stats["coalesced"] += 1
                sp.set(outcome="coalesced")
                lb = await asyncio.shield(fut)
                self.sync_latency.observe(time.monotonic() - t0)
                return lb
            if self.active_sessions >= self.max_sessions:
                self.stats["sheds"] += 1
                sp.set(outcome="shed")
                raise LightDBusyError(
                    f"lightd busy: {self.active_sessions} sessions in flight "
                    f"(max {self.max_sessions}); back off and resubmit"
                )
            # a miss is a request that actually entered a verification
            # session — sheds are counted separately, so the hit rate
            # reflects what was SERVED, not load that bounced
            self.stats["hop_cache_misses"] += 1
            fut = asyncio.get_running_loop().create_future()
            self._inflight[height] = fut
            self.active_sessions += 1
            try:
                lb = await self.client.verify_light_block_at_height(
                    height, now_ns
                )
            except BaseException as e:
                if isinstance(e, Divergence):
                    self.stats["divergences"] += 1
                    sp.set(outcome="divergence")
                if not fut.done():
                    # coalesced waiters share the failure; shield() above
                    # keeps a cancelled WAITER from killing the session
                    fut.set_exception(
                        e if not isinstance(e, asyncio.CancelledError)
                        else LightDBusyError("lightd sync cancelled")
                    )
                fut.exception()  # consumed here; never "never retrieved"
                raise
            else:
                if not fut.done():
                    fut.set_result(lb)
            finally:
                self.active_sessions -= 1
                if self._inflight.get(height) is fut:
                    del self._inflight[height]
            sp.set(outcome="verified", verified_height=lb.height)
            self.sync_latency.observe(time.monotonic() - t0)
            return lb

    async def light_block(self, height: int = 0) -> LightBlock:
        """Provider-shaped alias: every served block is verified."""
        return await self.sync(height)

    async def hop_proof(self, height: int) -> HopProof:
        """The aggregate hop proof for `height`: the verified light
        block (through `sync`, so hop cache + coalescing + busy-shed all
        apply) folded to the committee's best wire form and cached."""
        if height:
            proof = self._proofs.get(height)
            if proof is not None:
                self.stats["proof_cache_hits"] += 1
                self.stats["proofs_served"] += 1
                return proof
        lb = await self.sync(height)
        proof = make_hop_proof(lb, aggregate_hops=self.aggregate_hops)
        if self.proof_cache_size:
            while len(self._proofs) >= self.proof_cache_size:
                self._proofs.pop(next(iter(self._proofs)))
            # keyed by the VERIFIED height — a tip request (height 0)
            # caches under the height it resolved to, never under 0
            self._proofs[lb.height] = proof
        self.stats["proofs_served"] += 1
        return proof

    # -- introspection ---------------------------------------------------

    def latency_snapshot(self) -> tuple[list[int], float, int]:
        h = self.sync_latency
        return list(h._counts), h._sum, h._count

    def hop_cache_hit_rate(self) -> float:
        hits = self.stats["hop_cache_hits"]
        total = hits + self.stats["hop_cache_misses"]
        return hits / total if total else 0.0
