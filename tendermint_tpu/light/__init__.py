"""Light client (reference light/): verify headers against a trusted
header using validator-set overlap instead of replaying the chain. The
stateless core verifier is light/verifier.py; the stateful client with
bisection, a pluggable trusted store, and witness cross-checking is
light/client.py."""

from .types import LightBlock, SignedHeader

__all__ = ["LightBlock", "SignedHeader"]
