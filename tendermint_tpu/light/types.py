"""Light-client types (reference types/light.go): SignedHeader =
header + the commit that signed it; LightBlock adds the validator set."""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protoenc as pe
from ..types.block import Commit, Header
from ..types.validator_set import ValidatorSet


@dataclass(frozen=True)
class SignedHeader:
    header: Header
    commit: Commit

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None or self.commit is None:
            raise ValueError("signed header missing header or commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header chain id {self.header.chain_id!r} != {chain_id!r}"
            )
        if self.commit.height != self.header.height:
            raise ValueError("commit height != header height")
        if self.commit.block_id.hash != self.header.hash():
            raise ValueError("commit signs a different header")

    @property
    def height(self) -> int:
        return self.header.height

    def encode(self) -> bytes:
        return pe.message_field(1, self.header.encode()) + pe.message_field(
            2, self.commit.encode()
        )

    @classmethod
    def decode(cls, data: bytes) -> "SignedHeader":
        r = pe.Reader(data)
        header = commit = None
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                header = Header.decode(r.read_bytes())
            elif f == 2:
                commit = Commit.decode(r.read_bytes())
            else:
                r.skip(wt)
        return cls(header, commit)


@dataclass(frozen=True)
class LightBlock:
    signed_header: SignedHeader
    validators: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.height

    @property
    def header(self) -> Header:
        return self.signed_header.header

    def validate_basic(self, chain_id: str) -> None:
        if self.validators is None:
            raise ValueError("light block missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validators.validate_basic()
        if self.header.validators_hash != self.validators.hash():
            raise ValueError("validators hash does not match header")

    def encode(self) -> bytes:
        return pe.message_field(1, self.signed_header.encode()) + pe.message_field(
            2, self.validators.encode()
        )

    @classmethod
    def decode(cls, data: bytes) -> "LightBlock":
        r = pe.Reader(data)
        sh = vals = None
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                sh = SignedHeader.decode(r.read_bytes())
            elif f == 2:
                vals = ValidatorSet.decode(r.read_bytes())
            else:
                r.skip(wt)
        return cls(sh, vals)
