"""Light-client attack injection — the lunatic provider strategy.

`consensus/byzantine.py` made validators lie on the consensus wire;
this module makes a *provider* lie to light clients: a `LunaticProvider`
wraps an honest provider and, at seeded attack heights, serves a FORGED
light block — a header whose state-derived fields (app_hash, and the
claimed validator set) are fabricated, signed for real by a colluding
subset of the actual committee (the classic lunatic light-client
attack: the attackers reuse their genuine keys out of band, so the
forged commit passes every signature check and only the witness
cross-check can catch it).

Like the consensus strategy layer, every decision is a pure function of
(seed, height) — never arrival order or wall time — so two same-seed
attack runs serve bit-identical forged blocks and the formed
`LightClientAttackEvidence` bytes are reproducible. And like it, the
module is QUARANTINED: the tmtlint ``byz-containment`` rule pins the
import graph so only the scenario harness (consensus/scenarios.py) and
tests may name it — production wiring holding validator keys must be
structurally unable to sign a forged header.

The construction (what honest verification sees):

  * the forged header copies the real header at the attack height
    (time, chain id, last_block_id) but fabricates app_hash — a
    state-derived field, so `conflicting_header_is_invalid` classifies
    the attack as lunatic and attribution lands on every common-set
    validator that signed it;
  * it claims a validator set consisting of exactly the colluding
    subset, whose members all sign — so the conflicting block verifies
    +2/3 of its OWN claimed set (`verify_commit_light`), and the subset
    is chosen to hold > trust-level power of the real common-height set
    (`verify_commit_light_trusting`) — both checks the evidence pool
    reruns before pooling;
  * attack heights must be NON-adjacent to the client's trust anchor:
    adjacent verification pins the exact next validator set by hash and
    rejects the forgery before the witness cross-check even runs (a
    useful negative test, not an attack).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..types.block import BlockID, Commit, CommitSig, Header, PartSetHeader
from ..types.canonical import vote_sign_bytes
from ..types.keys import SignedMsgType
from ..types.validator_set import Validator, ValidatorSet
from .provider import Provider
from .types import LightBlock, SignedHeader


@dataclass(frozen=True)
class LunaticConfig:
    """One lunatic attack plan: which heights to forge at and how many
    committee members collude. Deterministic in `seed`."""

    attack_heights: tuple[int, ...]
    seed: int = 0
    #: colluding validators (must hold > 1/3 of the common-height power
    #: for the forged block to survive the evidence pool's trusting
    #: check; the scenario harness sizes this for the committee)
    n_traitors: int = 2


def _seeded_hash(seed: int, tag: str, *coords) -> bytes:
    return hashlib.sha256(
        f"tmtpu-lunatic:{seed}:{tag}:{coords!r}".encode()
    ).digest()


def traitor_indices(cfg: LunaticConfig, n_vals: int) -> tuple[int, ...]:
    """The colluding subset, a pure function of (seed, n_vals): a seeded
    starting offset and stride walk over the validator indices."""
    n = min(cfg.n_traitors, n_vals)
    start = int.from_bytes(_seeded_hash(cfg.seed, "subset", n_vals)[:4], "big")
    return tuple(sorted((start + i) % n_vals for i in range(n)))


def forge_light_block(
    cfg: LunaticConfig,
    real: LightBlock,
    vals: ValidatorSet,
    keys_by_addr: dict,
    chain_id: str,
) -> LightBlock:
    """The lunatic forgery for one height: a header copied from the real
    block with a seeded app_hash and the colluding subset as the claimed
    validator set, committed by every colluder at the real header's
    timestamp (deterministic under same-seed runs)."""
    idxs = traitor_indices(cfg, len(vals.validators))
    subset = [vals.validators[i] for i in idxs]
    claimed = ValidatorSet([Validator(v.pub_key, v.voting_power) for v in subset])
    header = Header(
        chain_id=real.header.chain_id,
        height=real.height,
        time_ns=real.header.time_ns,
        last_block_id=real.header.last_block_id,
        last_commit_hash=real.header.last_commit_hash,
        data_hash=real.header.data_hash,
        validators_hash=claimed.hash(),
        next_validators_hash=claimed.hash(),
        consensus_hash=real.header.consensus_hash,
        app_hash=_seeded_hash(cfg.seed, "app", real.height),
        last_results_hash=real.header.last_results_hash,
        evidence_hash=real.header.evidence_hash,
        proposer_address=claimed.validators[0].address,
        version=real.header.version,
    )
    bid = BlockID(
        header.hash(),
        PartSetHeader(1, _seeded_hash(cfg.seed, "parts", real.height)),
    )
    sigs = []
    for val in claimed.validators:
        ts = real.header.time_ns
        sb = vote_sign_bytes(
            chain_id, SignedMsgType.PRECOMMIT, real.height, 0, bid, ts
        )
        sigs.append(
            CommitSig.for_block(val.address, ts, keys_by_addr[val.address].sign(sb))
        )
    commit = Commit(real.height, 0, bid, tuple(sigs))
    return LightBlock(SignedHeader(header, commit), claimed)


class LunaticProvider(Provider):
    """A traitor primary: honest pass-through everywhere except the
    seeded attack heights, where the forged block is served instead.
    Forgeries are built once per height and cached, so every client
    (and every same-seed run) sees byte-identical lies."""

    def __init__(
        self,
        inner: Provider,
        cfg: LunaticConfig,
        vals: ValidatorSet,
        keys_by_addr: dict,
    ):
        self.inner = inner
        self.cfg = cfg
        self.vals = vals
        self.keys_by_addr = keys_by_addr
        self._forged: dict[int, LightBlock] = {}
        #: observation log for the scenario auditor (heights served
        #: forged, in request order — bounded by the attack plan)
        self.served_forged: list[int] = []

    def __repr__(self) -> str:
        return f"LunaticProvider({self.inner!r}, heights={self.cfg.attack_heights})"

    def chain_id(self) -> str:
        return self.inner.chain_id()

    def traitor_addresses(self) -> tuple[bytes, ...]:
        return tuple(
            self.vals.validators[i].address
            for i in traitor_indices(self.cfg, len(self.vals.validators))
        )

    async def light_block(self, height: int) -> LightBlock:
        real = await self.inner.light_block(height)
        if real.height not in self.cfg.attack_heights:
            return real
        forged = self._forged.get(real.height)
        if forged is None:
            forged = forge_light_block(
                self.cfg, real, self.vals, self.keys_by_addr, self.chain_id()
            )
            self._forged[real.height] = forged
        self.served_forged.append(real.height)
        return forged

    async def report_evidence(self, evidence) -> None:
        # a real attacker drops evidence against itself on the floor
        pass
