"""Stateless light-client core verifier (reference light/verifier.go).

Two modes:
  verify_adjacent (verifier.go:103) — heights h, h+1: the trusted header
    pins the EXACT next validator set (next_validators_hash), so only
    VerifyCommitLight against that set is needed.
  verify_non_adjacent (verifier.go:33) — skipping/bisection: the trusted
    set must still hold `trust_level` (default 1/3) of the new commit's
    power (VerifyCommitLightTrusting), then the new set verifies its own
    commit (VerifyCommitLight).

Both funnel into the same batched TPU verification path
(types/validation.py) — and through the VerifyHub when one is running,
so light-client commits share kernel launches (and the gossip dedup
cache) with live consensus and block-sync."""

from __future__ import annotations

import time
from fractions import Fraction

from ..crypto import hash_hub
from ..types.validation import (
    InvalidCommitError,
    verify_commit_light,
    verify_commit_light_trusting,
    verify_commit_range,
)
from .types import LightBlock

DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class VerificationError(ValueError):
    pass


class ErrNewValSetCantBeTrusted(VerificationError):
    """Trusting-period overlap check failed — caller should bisect
    (reference ErrNewValSetCantBeTrusted)."""


def _validate_untrusted(
    chain_id: str,
    trusted: LightBlock,
    untrusted: LightBlock,
    now_ns: int,
    max_clock_drift_ns: int,
) -> None:
    """Shared sanity checks (reference verifier.go
    checkRequiredHeaderFields + verifyNewHeaderAndVals)."""
    untrusted.validate_basic(chain_id)
    if untrusted.height <= trusted.height:
        raise VerificationError(
            f"untrusted height {untrusted.height} <= trusted {trusted.height}"
        )
    if untrusted.header.time_ns <= trusted.header.time_ns:
        raise VerificationError("untrusted header time is not after trusted")
    if untrusted.header.time_ns >= now_ns + max_clock_drift_ns:
        raise VerificationError("untrusted header time is from the future")


def _expired(trusted: LightBlock, trusting_period_ns: int, now_ns: int) -> bool:
    return trusted.header.time_ns + trusting_period_ns <= now_ns


def _check_adjacent_link(
    chain_id: str,
    trusted: LightBlock,
    untrusted: LightBlock,
    trusting_period_ns: int,
    now_ns: int,
    max_clock_drift_ns: int,
) -> None:
    """Every non-signature check of one adjacent step — shared verbatim
    by verify_adjacent and verify_adjacent_chain so the two paths cannot
    drift."""
    if untrusted.height != trusted.height + 1:
        raise VerificationError(
            f"headers must be adjacent in height "
            f"({trusted.height} -> {untrusted.height})"
        )
    if _expired(trusted, trusting_period_ns, now_ns):
        raise VerificationError(f"trusted header {trusted.height} has expired")
    _validate_untrusted(chain_id, trusted, untrusted, now_ns, max_clock_drift_ns)
    if untrusted.header.validators_hash != trusted.header.next_validators_hash:
        raise VerificationError(
            "untrusted validators hash != trusted next_validators_hash"
        )


def verify_adjacent(
    chain_id: str,
    trusted: LightBlock,
    untrusted: LightBlock,
    trusting_period_ns: int,
    now_ns: int | None = None,
    max_clock_drift_ns: int = 10 * 1_000_000_000,
) -> None:
    """Reference VerifyAdjacent verifier.go:103."""
    now_ns = time.time_ns() if now_ns is None else now_ns
    with hash_hub.lane_ctx(hash_hub.LANE_LIGHT):
        _check_adjacent_link(
            chain_id, trusted, untrusted, trusting_period_ns, now_ns, max_clock_drift_ns
        )
        try:
            verify_commit_light(
                chain_id,
                untrusted.validators,
                untrusted.signed_header.commit.block_id,
                untrusted.height,
                untrusted.signed_header.commit,
                lane="backfill",
            )
        except InvalidCommitError as e:
            raise VerificationError(f"invalid commit: {e}") from e


def verify_adjacent_chain(
    chain_id: str,
    trusted: LightBlock,
    chain: list[LightBlock],
    trusting_period_ns: int,
    now_ns: int | None = None,
    max_clock_drift_ns: int = 10 * 1_000_000_000,
) -> LightBlock:
    """Bulk sequential verification — the TPU-first shape of the
    reference's header-by-header VerifyAdjacent loop
    (light/client_benchmark_test.go drives exactly this workload).

    All structural and trust-linkage checks (adjacency, expiry, times,
    next_validators_hash pinning) run sequentially on the host — they are
    cheap and order-dependent — and then every header's commit signatures
    are proven in ONE range-batched verifier call
    (types/validation.py:verify_commit_range), so a 1 000-header catch-up
    is a handful of MSM kernel launches instead of 1 000. Since each
    header's validator set is pinned by its predecessor's
    next_validators_hash BEFORE any signature is checked, deferring the
    signature proof to the end does not weaken the trust chain: a forged
    commit anywhere fails the batch and nothing is returned.

    Returns the new trusted head (the last block of `chain`). Raises
    VerificationError naming the offending height otherwise."""
    now_ns = time.time_ns() if now_ns is None else now_ns
    with hash_hub.lane_ctx(hash_hub.LANE_LIGHT):
        entries = []
        prev = trusted
        for lb in chain:
            _check_adjacent_link(
                chain_id, prev, lb, trusting_period_ns, now_ns, max_clock_drift_ns
            )
            entries.append(
                (
                    lb.validators,
                    lb.signed_header.commit.block_id,
                    lb.height,
                    lb.signed_header.commit,
                )
            )
            prev = lb
        try:
            verify_commit_range(chain_id, entries, lane="backfill")
        except InvalidCommitError as e:
            idx = getattr(e, "failed_index", None)
            at = f" at height {chain[idx].height}" if idx is not None else ""
            raise VerificationError(f"invalid commit{at}: {e}") from e
        return prev


def verify_non_adjacent(
    chain_id: str,
    trusted: LightBlock,
    untrusted: LightBlock,
    trusting_period_ns: int,
    now_ns: int | None = None,
    max_clock_drift_ns: int = 10 * 1_000_000_000,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """Reference VerifyNonAdjacent verifier.go:33."""
    now_ns = time.time_ns() if now_ns is None else now_ns
    if untrusted.height == trusted.height + 1:
        return verify_adjacent(
            chain_id, trusted, untrusted, trusting_period_ns, now_ns, max_clock_drift_ns
        )
    if _expired(trusted, trusting_period_ns, now_ns):
        raise VerificationError("trusted header has expired")
    with hash_hub.lane_ctx(hash_hub.LANE_LIGHT):
        _validate_untrusted(chain_id, trusted, untrusted, now_ns, max_clock_drift_ns)
        # the trusted validator set must still control trust_level of the new
        # commit (verifier.go:67)
        try:
            verify_commit_light_trusting(
                chain_id,
                trusted.validators,
                untrusted.signed_header.commit,
                trust_level,
                lane="backfill",
            )
        except InvalidCommitError as e:
            raise ErrNewValSetCantBeTrusted(str(e)) from e
        # and the new set must verify its own commit (verifier.go:82)
        try:
            verify_commit_light(
                chain_id,
                untrusted.validators,
                untrusted.signed_header.commit.block_id,
                untrusted.height,
                untrusted.signed_header.commit,
                lane="backfill",
            )
        except InvalidCommitError as e:
            raise VerificationError(f"invalid commit: {e}") from e


def verify(
    chain_id: str,
    trusted: LightBlock,
    untrusted: LightBlock,
    trusting_period_ns: int,
    now_ns: int | None = None,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    max_clock_drift_ns: int = 10 * 1_000_000_000,
) -> None:
    """Dispatch on adjacency (reference Verify verifier.go:151)."""
    if untrusted.height == trusted.height + 1:
        verify_adjacent(
            chain_id, trusted, untrusted, trusting_period_ns, now_ns,
            max_clock_drift_ns,
        )
    else:
        verify_non_adjacent(
            chain_id, trusted, untrusted, trusting_period_ns, now_ns,
            max_clock_drift_ns, trust_level=trust_level,
        )
