"""Light-block providers (reference light/provider/provider.go).

A provider serves LightBlocks for heights and accepts evidence reports.
`BlockStoreProvider` is the in-process implementation over a node's
stores (the analog of the reference's local provider used by tests and
the statesync backfill); the RPC-backed provider lives with the RPC
client (task: rpc layer). `RetryingProvider` wraps any provider with the
shared backoff + circuit-breaker policy (libs/retry) so flaky transports
degrade gracefully instead of surfacing every transient error to the
verification strategies."""

from __future__ import annotations

import random

from ..libs.retry import BackoffPolicy, CircuitBreaker, RetriesExhaustedError, retry
from ..types.block import Commit
from .types import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    pass


class Provider:
    def chain_id(self) -> str:
        raise NotImplementedError

    async def light_block(self, height: int) -> LightBlock:
        """Height 0 = latest. Raises LightBlockNotFoundError."""
        raise NotImplementedError

    async def report_evidence(self, evidence) -> None:
        raise NotImplementedError


class RetryingProvider(Provider):
    """Backoff + circuit breaker around any provider.

    * transient `ProviderError`s are retried under an exponential
      full-jitter policy;
    * `LightBlockNotFoundError` is a DEFINITIVE answer (the peer simply
      lacks the height) — it propagates immediately and does not count
      against the breaker;
    * repeated failures open the breaker and subsequent calls fail fast
      with ProviderError until the half-open probe succeeds."""

    def __init__(
        self,
        inner: Provider,
        *,
        policy: BackoffPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        rng: random.Random | None = None,
    ):
        self.inner = inner
        self.policy = policy or BackoffPolicy(
            base=0.05, cap=2.0, max_attempts=4, deadline=10.0
        )
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout=5.0, name="light-provider"
        )
        self.rng = rng

    def __repr__(self) -> str:
        return f"RetryingProvider({self.inner!r})"

    def chain_id(self) -> str:
        return self.inner.chain_id()

    async def light_block(self, height: int) -> LightBlock:
        if not self.breaker.allow():
            raise ProviderError(
                f"provider {self.inner!r} circuit open (failing fast)"
            )

        async def attempt() -> LightBlock:
            try:
                return await self.inner.light_block(height)
            except LightBlockNotFoundError:
                raise  # definitive: do not retry, do not trip the breaker
            except ProviderError:
                raise
            except Exception as e:  # transport-level surprise: retryable
                raise ProviderError(f"provider failure: {e!r}") from e

        # EVERY exit path below must record an outcome on the breaker: a
        # claimed half-open probe slot is only released by record_success/
        # record_failure, so a silent exit would wedge the breaker open.
        try:
            lb = await retry(
                attempt,
                self.policy,
                retry_on=(ProviderError,),
                give_up_on=(LightBlockNotFoundError,),
                rng=self.rng,
            )
        except LightBlockNotFoundError:
            # definitive answer from a RESPONSIVE provider: the transport
            # is healthy, only the height is absent
            self.breaker.record_success()
            raise
        except RetriesExhaustedError as e:
            self.breaker.record_failure()
            last = e.last if isinstance(e.last, ProviderError) else ProviderError(str(e))
            raise last
        except BaseException:
            # cancellation / unexpected error mid-probe: release the slot
            # pessimistically so a later call can half-open again
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return lb

    async def report_evidence(self, evidence) -> None:
        await self.inner.report_evidence(evidence)


class BlockStoreProvider(Provider):
    """Serve light blocks straight from a block store + state store."""

    def __init__(self, chain_id: str, block_store, state_store, evidence_pool=None):
        self._chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.evidence_pool = evidence_pool
        self.reported: list = []

    def chain_id(self) -> str:
        return self._chain_id

    def _light_block_sync(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height()
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)  # commit FOR height
        if commit is None:
            commit = self.block_store.load_seen_commit(height)  # tip block
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            raise LightBlockNotFoundError(f"no light block at height {height}")
        return LightBlock(SignedHeader(meta.header, commit), vals)

    async def light_block(self, height: int) -> LightBlock:
        return self._light_block_sync(height)

    async def report_evidence(self, evidence) -> None:
        """Hand reported evidence to the backing node's pool (the
        in-process analog of the RPC provider's broadcast_evidence)."""
        self.reported.append(evidence)
        if self.evidence_pool is not None:
            self.evidence_pool.add_evidence(evidence)
