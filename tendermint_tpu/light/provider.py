"""Light-block providers (reference light/provider/provider.go).

A provider serves LightBlocks for heights and accepts evidence reports.
`BlockStoreProvider` is the in-process implementation over a node's
stores (the analog of the reference's local provider used by tests and
the statesync backfill); the RPC-backed provider lives with the RPC
client (task: rpc layer)."""

from __future__ import annotations

from ..types.block import Commit
from .types import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    pass


class Provider:
    def chain_id(self) -> str:
        raise NotImplementedError

    async def light_block(self, height: int) -> LightBlock:
        """Height 0 = latest. Raises LightBlockNotFoundError."""
        raise NotImplementedError

    async def report_evidence(self, evidence) -> None:
        raise NotImplementedError


class BlockStoreProvider(Provider):
    """Serve light blocks straight from a block store + state store."""

    def __init__(self, chain_id: str, block_store, state_store, evidence_pool=None):
        self._chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.evidence_pool = evidence_pool
        self.reported: list = []

    def chain_id(self) -> str:
        return self._chain_id

    def _light_block_sync(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height()
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)  # commit FOR height
        if commit is None:
            commit = self.block_store.load_seen_commit(height)  # tip block
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            raise LightBlockNotFoundError(f"no light block at height {height}")
        return LightBlock(SignedHeader(meta.header, commit), vals)

    async def light_block(self, height: int) -> LightBlock:
        return self._light_block_sync(height)

    async def report_evidence(self, evidence) -> None:
        """Hand reported evidence to the backing node's pool (the
        in-process analog of the RPC provider's broadcast_evidence)."""
        self.reported.append(evidence)
        if self.evidence_pool is not None:
            self.evidence_pool.add_evidence(evidence)
