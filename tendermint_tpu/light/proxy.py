"""Light-client RPC proxy (reference light/proxy/proxy.go:18 +
light/rpc/client.go): serves the standard JSON-RPC surface, but every
header-shaped answer is LIGHT-VERIFIED before it leaves, and abci_query
results are checked against a verified header's app_hash through merkle
proof operators (crypto/merkle.py ProofOperators). A caller can point any
normal RPC client at the proxy and get verified answers from an untrusted
full node.

The env object plugs straight into rpc/server.RPCServer — it implements
the same route-method protocol as rpc/core.Environment, raising RPCError
for the routes a stateless proxy cannot serve (tx indexing, consensus
introspection).

LightFleet integration: construct with ``lightd=`` (a running
light/fleet.LightD) and every header-shaped read rides the shared
verified-hop cache instead of this proxy's embedded client — N proxies
(or N requests) share one verification per hop — and two fleet routes
appear: ``light_block`` (the verified block) and ``hop_proof`` (the
aggregate hop proof: hex wire bytes + scheme). LightD's explicit
busy-shed maps to the RPC busy contract: ``LIGHT_BUSY_CODE`` (the
MEMPOOL_BUSY_CODE pattern — back off and resubmit, nothing was
queued)."""

from __future__ import annotations

import logging

from ..crypto import merkle
from ..rpc.core import MEMPOOL_BUSY_CODE, RPCError
from .client import LightClient

#: RPCError code for a shed light read — same value as the mempool's
#: busy CheckTx code on purpose: one "busy, back off" number for
#: clients across the whole read+write surface
LIGHT_BUSY_CODE = MEMPOOL_BUSY_CODE

_UNSUPPORTED = (
    "net_info",
    "consensus_state",
    "block_results",
    "unconfirmed_txs",
    "num_unconfirmed_txs",
    "check_tx",
    "tx",
    "tx_search",
    "block_search",
    "blockchain",
    "block_by_hash",
    "broadcast_evidence",
    "genesis",
    "consensus_params",
)


class LightProxyEnv:
    def __init__(
        self,
        light_client: LightClient,
        primary_rpc,  # rpc.client.HTTPClient against the primary
        *,
        lightd=None,  # light.fleet.LightD: reads ride the shared hop cache
        logger: logging.Logger | None = None,
    ):
        self.lc = light_client
        self.primary = primary_rpc
        self.lightd = lightd
        self.logger = logger or logging.getLogger("light.proxy")
        self.metrics = None

        for name in _UNSUPPORTED:
            setattr(self, name, self._unsupported(name))

    @staticmethod
    def _unsupported(name: str):
        async def handler(**_kw):
            raise RPCError(
                -32601, f"{name} is not served by the light proxy (stateless)"
            )

        return handler

    async def _verified(self, height: int):
        """One verified light block: through the attached LightD (shared
        hop cache + coalescing; busy-shed surfaces as the RPC busy
        contract) or this proxy's own embedded client."""
        if self.lightd is None:
            return await self.lc.verify_light_block_at_height(height)
        from .fleet import LightDBusyError

        try:
            return await self.lightd.sync(height)
        except LightDBusyError as e:
            raise RPCError(LIGHT_BUSY_CODE, str(e)) from e

    async def health(self) -> dict:
        return {}

    # -- LightFleet routes (served only with a LightD attached) ----------

    async def light_block(self, height: int | None = None) -> dict:
        """The verified light block, whole: signed header + validator
        set — what a re-verifying fleet client consumes."""
        lb = await self._verified(int(height or 0))
        return {
            "height": str(lb.height),
            "hash": lb.header.hash().hex(),
            "light_block": lb.encode().hex(),
        }

    async def hop_proof(self, height: int | None = None) -> dict:
        """The aggregate hop proof for `height` (light/fleet.HopProof
        wire bytes): one 96-byte BLS aggregate + signer bitmap for BLS
        committees, the per-sig form otherwise. Busy-shed maps to the
        RPC busy contract like every other fleet read."""
        if self.lightd is None:
            raise RPCError(
                -32601, "hop_proof requires a LightD serving layer"
            )
        from .fleet import LightDBusyError

        try:
            proof = await self.lightd.hop_proof(int(height or 0))
        except LightDBusyError as e:
            raise RPCError(LIGHT_BUSY_CODE, str(e)) from e
        return {
            "height": str(proof.height),
            "scheme": proof.scheme,
            "wire_bytes": str(proof.wire_bytes()),
            "proof": proof.encode().hex(),
        }

    async def _wait_for_height(self, height: int, timeout: float = 10.0) -> None:
        import asyncio

        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            st = await self.primary.status()
            if int(st["sync_info"]["latest_block_height"]) >= height:
                return
            if asyncio.get_running_loop().time() > deadline:
                raise RPCError(
                    -32000, f"primary never reached height {height} for proof"
                )
            await asyncio.sleep(0.1)

    async def status(self) -> dict:
        res = await self.primary.status()
        store = self.lightd.store if self.lightd is not None else self.lc.store
        latest = store.latest()
        if latest is not None:
            # overwrite the untrusted node's claims with verified facts
            res.setdefault("sync_info", {})
            res["sync_info"]["trusted_height"] = str(latest.height)
            res["sync_info"]["trusted_hash"] = latest.header.hash().hex()
        return res

    async def commit(self, height: int | None = None) -> dict:
        lb = await self._verified(int(height or 0))
        from ..rpc.core import _commit_json, _header_json

        return {
            "signed_header": {
                "header": _header_json(lb.header),
                "commit": _commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    async def header(self, height: int | None = None) -> dict:
        lb = await self._verified(int(height or 0))
        from ..rpc.core import _header_json

        return {"header": _header_json(lb.header)}

    async def validators(
        self, height: int | None = None, page: int = 1, per_page: int = 100
    ) -> dict:
        lb = await self._verified(int(height or 0))
        from ..rpc.core import _validator_json

        vals = lb.validators.validators
        page, per_page = max(1, int(page)), min(int(per_page), 100)
        start = (page - 1) * per_page
        return {
            "block_height": str(lb.height),
            "validators": [_validator_json(v) for v in vals[start : start + per_page]],
            "count": str(len(vals[start : start + per_page])),
            "total": str(len(vals)),
        }

    async def block(self, height: int | None = None) -> dict:
        """Fetch the full block from the primary, then require its header
        to hash to the light-verified header (light/rpc/client.go Block)."""
        res = await self.primary.block(height=height)
        got_height = int(res["block"]["header"]["height"])
        lb = await self._verified(got_height)
        got_hash = bytes.fromhex(res["block_id"]["hash"])
        if got_hash != lb.header.hash():
            raise RPCError(
                -32000,
                f"primary served block {got_height} with hash "
                f"{got_hash.hex()} != verified {lb.header.hash().hex()}",
            )
        return res

    async def broadcast_tx_async(self, tx: str) -> dict:
        return await self.primary.call("broadcast_tx_async", tx=tx)

    async def broadcast_tx_sync(self, tx: str) -> dict:
        return await self.primary.call("broadcast_tx_sync", tx=tx)

    async def broadcast_tx_commit(self, tx: str) -> dict:
        return await self.primary.call("broadcast_tx_commit", tx=tx)

    async def abci_info(self) -> dict:
        return await self.primary.call("abci_info")

    async def abci_query(
        self, path: str = "", data: str = "", height: int = 0, prove: bool = True
    ) -> dict:
        """Forward with prove=true, then verify the value against the
        app_hash of the header at query-height+1 (the app hash produced by
        executing block H lands in header H+1) — reference
        light/rpc/client.go ABCIQueryWithOptions."""
        res = await self.primary.call(
            "abci_query", path=path, data=data, height=int(height), prove=True
        )
        resp = res["response"]
        if int(resp.get("code", 0)) != 0:
            # App-level miss: the kvstore merkle tree has no absence
            # proofs (neighbor-leaf range proofs), so a "does not exist"
            # answer CANNOT be verified — a malicious primary could censor
            # any key by answering not-found. Surface that explicitly so
            # callers never mistake a miss for a proven absence (the
            # reference's iavl store proves absence; this one can't).
            resp["proof_verified"] = False
            resp["proof_unavailable"] = "negative results carry no absence proof"
            return res
        q_height = int(resp["height"])
        ops = [
            merkle.ProofOp(
                o["type"], bytes.fromhex(o["key"]), bytes.fromhex(o["data"])
            )
            for o in resp.get("proof_ops", {}).get("ops", [])
        ]
        if not ops:
            raise RPCError(-32000, "primary returned no proof for abci_query")
        # the app hash covering state at q_height lands in header
        # q_height+1 — which may not exist yet at the instant of the query
        # (reference light/rpc/client.go WaitForHeight before verifying)
        await self._wait_for_height(q_height + 1)
        lb = await self._verified(q_height + 1)
        value = bytes.fromhex(resp["value"])
        keypath = merkle.key_path(bytes.fromhex(resp["key"]))
        if not merkle.ProofOperators(ops).verify_value(
            lb.header.app_hash, keypath, value
        ):
            raise RPCError(
                -32000,
                f"abci_query proof verification FAILED against app hash at "
                f"height {q_height + 1}",
            )
        resp["proof_verified"] = True
        return res
