"""Light client (reference light/client.go:127).

Holds a trusted store of verified LightBlocks, a primary provider, and
witness providers. `verify_light_block_at_height` verifies forward via
sequential or skipping (bisection) verification — skipping needs only
log(n) headers thanks to the 1/3-overlap rule — or backwards via hash
linkage (client.go:878). After primary verification the header is cross-
checked against witnesses; a mismatch raises Divergence (the detector,
light/detector.go:28)."""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from fractions import Fraction

from ..store.db import DB, MemDB
from . import verifier
from .provider import LightBlockNotFoundError, Provider, ProviderError
from .types import LightBlock
from .verifier import ErrNewValSetCantBeTrusted, VerificationError

_LB_PREFIX = b"lb/"

#: ceiling on concurrent light-block fetches against a single provider
#: (the windowed sequential verifier would otherwise issue up to a full
#: 128-height window at once)
FETCH_CONCURRENCY = 16


async def _as_ready(value):
    return value


async def _gather_cancelling(coros: list) -> list:
    """gather() that bounds concurrency with a semaphore and, on the
    first failure, CANCELS every in-flight sibling and awaits them
    (no stray 'exception was never retrieved' tasks) before re-raising."""
    sem = asyncio.Semaphore(FETCH_CONCURRENCY)

    async def bounded(coro):
        try:
            async with sem:
                return await coro
        except asyncio.CancelledError:
            coro.close()  # no-op if already started; silences never-awaited
            raise

    tasks = [asyncio.ensure_future(bounded(c)) for c in coros]
    try:
        return list(await asyncio.gather(*tasks))
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise


@dataclass(frozen=True)
class TrustOptions:
    """How the client bootstraps trust (reference light/client.go
    TrustOptions): a header hash the user got out of band."""

    period_ns: int
    height: int
    hash: bytes


class Divergence(Exception):
    """A witness provided a conflicting verified header (light-client
    attack in progress; reference detector.go)."""

    def __init__(self, witness: Provider, trace: list[LightBlock], challenging: LightBlock):
        super().__init__(
            f"witness {witness!r} diverged at height {challenging.height}"
        )
        self.witness = witness
        self.trace = trace
        self.challenging = challenging


class TrustedStore:
    """Persisted verified light blocks (reference light/store/db)."""

    def __init__(self, db: DB | None = None):
        self.db = db or MemDB()

    def save(self, lb: LightBlock) -> None:
        self.db.set(_LB_PREFIX + lb.height.to_bytes(8, "big"), lb.encode())

    def get(self, height: int) -> LightBlock | None:
        raw = self.db.get(_LB_PREFIX + height.to_bytes(8, "big"))
        return LightBlock.decode(raw) if raw is not None else None

    def latest(self) -> LightBlock | None:
        for _k, raw in self.db.iterate(
            _LB_PREFIX, _LB_PREFIX + b"\xff" * 8, reverse=True
        ):
            return LightBlock.decode(raw)
        return None

    def lowest(self) -> LightBlock | None:
        for _k, raw in self.db.iterate(_LB_PREFIX, _LB_PREFIX + b"\xff" * 8):
            return LightBlock.decode(raw)
        return None

    def prune(self, keep: int) -> None:
        keys = [k for k, _ in self.db.iterate(_LB_PREFIX, _LB_PREFIX + b"\xff" * 8)]
        for k in keys[:-keep] if keep else keys:
            self.db.delete(k)


class LightClient:
    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: list[Provider] | None = None,
        *,
        store: TrustedStore | None = None,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        sequential: bool = False,
        logger: logging.Logger | None = None,
    ):
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses or [])
        self.store = store or TrustedStore()
        self.trust_level = trust_level
        self.sequential = sequential
        self.logger = logger or logging.getLogger("light")

    # -- bootstrap -------------------------------------------------------

    async def initialize(self) -> LightBlock:
        """Fetch + pin the trust-options header (reference
        client.go:311 initializeWithTrustOptions)."""
        lb = await self.primary.light_block(self.trust_options.height)
        lb.validate_basic(self.chain_id)
        if lb.header.hash() != self.trust_options.hash:
            raise VerificationError(
                f"trusted header hash mismatch at height {lb.height}: "
                f"{lb.header.hash().hex()} != {self.trust_options.hash.hex()}"
            )
        # the commit must actually be signed by the block's validator set
        from ..types.validation import verify_commit_light

        verify_commit_light(
            self.chain_id,
            lb.validators,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
            lane="backfill",
        )
        self.store.save(lb)
        return lb

    # -- main entry ------------------------------------------------------

    async def verify_light_block_at_height(
        self, height: int, now_ns: int | None = None
    ) -> LightBlock:
        """Reference VerifyLightBlockAtHeight client.go:406."""
        now_ns = time.time_ns() if now_ns is None else now_ns
        existing = self.store.get(height) if height else None
        if existing is not None:
            return existing
        latest = self.store.latest()
        if latest is None:
            latest = await self.initialize()
            # the anchor initialize() just pinned may BE the requested
            # height (statesync joiners trust the snapshot height
            # itself) — verifying it "forward" against itself would
            # raise "untrusted height <= trusted"
            existing = self.store.get(height) if height else None
            if existing is not None:
                return existing
        target = await self.primary.light_block(height)
        # Strategies BUFFER newly verified blocks instead of persisting:
        # nothing primary-supplied may reach the trusted store until the
        # witness cross-check has passed, or a divergence would leave
        # forged intermediate headers behind as future trust anchors.
        pending: list[LightBlock] = []
        if target.height < latest.height:
            verified = await self._verify_backwards(target, latest, pending)
        elif self.sequential:
            verified = await self._verify_sequential(latest, target, now_ns, pending)
        else:
            verified = await self._verify_skipping(latest, target, now_ns, pending)
        await self._detect_divergence(verified, now_ns, trust_anchor=latest)
        for lb in pending:
            self.store.save(lb)
        self.store.save(verified)
        return verified

    async def update(self, now_ns: int | None = None) -> LightBlock:
        """Verify the primary's latest header (reference client.go Update)."""
        latest = await self.primary.light_block(0)
        return await self.verify_light_block_at_height(latest.height, now_ns)

    # -- strategies ------------------------------------------------------

    async def _verify_sequential(
        self,
        trusted: LightBlock,
        target: LightBlock,
        now_ns: int,
        pending: list[LightBlock],
    ) -> LightBlock:
        """Reference verifySequential client.go:546, bulked: headers are
        fetched in windows and each window's commits are proven in ONE
        range-batched call (verifier.verify_adjacent_chain) — the
        structural trust chain is still checked strictly in order."""
        window = 128
        h = trusted.height + 1
        while h <= target.height:
            top = min(h + window - 1, target.height)
            # fetches are independent (verification is deferred to the
            # end of the window), so issue them concurrently — over a
            # real provider the serial RPC round-trips dominate, not the
            # signature math. Concurrency is semaphore-bounded and a
            # failed fetch cancels its in-flight siblings.
            chain = await _gather_cancelling(
                [
                    (
                        _as_ready(target)
                        if hh == target.height
                        else self.primary.light_block(hh)
                    )
                    for hh in range(h, top + 1)
                ]
            )
            trusted = verifier.verify_adjacent_chain(
                self.chain_id, trusted, chain, self.trust_options.period_ns, now_ns
            )
            pending.extend(chain)
            h = top + 1
        return trusted

    async def _verify_skipping(
        self,
        trusted: LightBlock,
        target: LightBlock,
        now_ns: int,
        pending: list[LightBlock],
    ) -> LightBlock:
        """Bisection (reference verifySkipping client.go:639): try to jump
        straight to the target; on 1/3-overlap failure, bisect."""
        stack = [target]
        while stack:
            lb = stack[-1]
            try:
                verifier.verify(
                    self.chain_id,
                    trusted,
                    lb,
                    self.trust_options.period_ns,
                    now_ns,
                    trust_level=self.trust_level,
                )
            except ErrNewValSetCantBeTrusted:
                mid = (trusted.height + lb.height) // 2
                if mid in (trusted.height, lb.height):
                    raise VerificationError(
                        "bisection cannot make progress (validator sets too disjoint)"
                    )
                stack.append(await self.primary.light_block(mid))
                continue
            pending.append(lb)
            trusted = lb
            stack.pop()
        return trusted

    async def _verify_backwards(
        self, target: LightBlock, trusted: LightBlock, pending: list[LightBlock]
    ) -> LightBlock:
        """Hash-linkage verification for heights below the trusted head
        (reference client.go:878): walk last_block_id back to the target."""
        cur = trusted
        while cur.height > target.height:
            prev_height = cur.height - 1
            prev = (
                target
                if prev_height == target.height
                else await self.primary.light_block(prev_height)
            )
            prev.validate_basic(self.chain_id)
            if cur.header.last_block_id.hash != prev.header.hash():
                raise VerificationError(
                    f"backwards verification failed at height {prev_height}: "
                    "hash chain broken"
                )
            pending.append(prev)
            cur = prev
        return cur

    # -- witness cross-check --------------------------------------------

    async def _detect_divergence(
        self,
        verified: LightBlock,
        now_ns: int,
        trust_anchor: LightBlock | None = None,
    ) -> None:
        """Compare the newly verified header against every witness
        (reference detector.go:28 detectDivergence). A witness that
        serves a DIFFERENT header for the same height with a valid
        commit is evidence of an attack: LightClientAttackEvidence is
        formed against the divergent chain and submitted to the primary
        and every witness (detector.go:215 newLightClientAttackEvidence),
        whose evidence pools verify and gossip it toward block inclusion."""
        if not self.witnesses:
            return
        for witness in list(self.witnesses):
            try:
                w_lb = await witness.light_block(verified.height)
            except (ProviderError, LightBlockNotFoundError):
                continue  # witness lagging; not divergence
            if w_lb.header.hash() == verified.header.hash():
                continue
            # conflicting header — check it's actually signed (i.e. a
            # real attack, not witness garbage)
            try:
                w_lb.validate_basic(self.chain_id)
                from ..types.validation import verify_commit_light

                verify_commit_light(
                    self.chain_id,
                    w_lb.validators,
                    w_lb.signed_header.commit.block_id,
                    w_lb.height,
                    w_lb.signed_header.commit,
                    lane="backfill",
                )
            except (ValueError, VerificationError):
                self.logger.info("dropping bad witness %r", witness)
                self.witnesses.remove(witness)
                continue
            await self._report_attack(verified, w_lb, trust_anchor, witness)
            raise Divergence(witness, [verified], w_lb)

    async def _report_attack(
        self,
        verified: LightBlock,
        conflicting: LightBlock,
        trust_anchor: LightBlock | None,
        witness: Provider,
    ) -> None:
        """Form LightClientAttackEvidence and submit it to every provider
        (reference detector.go:215). The common height is the last height
        both chains agreed at — the anchor this update verified from."""
        from ..types.evidence import LightClientAttackEvidence

        anchor = trust_anchor or self.store.latest()
        if anchor is None:
            return
        if anchor.height > conflicting.height:
            # backwards verification: the trust anchor sits ABOVE the
            # conflicting height, so no common ancestor height is known —
            # evidence built from it would fail validate_basic everywhere
            self.logger.warning(
                "divergence below trust anchor (%d > %d): no evidence formed",
                anchor.height,
                conflicting.height,
            )
            return
        import dataclasses

        def build(conflicting_lb: LightBlock, trusted_sh) -> object | None:
            try:
                ev = LightClientAttackEvidence(
                    conflicting_block=conflicting_lb,
                    common_height=anchor.height,
                    byzantine_validators=(),
                    total_voting_power=anchor.validators.total_voting_power(),
                    timestamp_ns=anchor.header.time_ns,
                )
                return dataclasses.replace(
                    ev,
                    byzantine_validators=tuple(
                        ev.get_byzantine_validators(anchor.validators, trusted_sh)
                    ),
                )
            except Exception as e:  # noqa: BLE001 — must not mask Divergence
                self.logger.error("failed to build attack evidence: %r", e)
                return None

        # The client cannot know which side forged, so evidence is formed
        # in BOTH directions (reference detector.go handles primary- and
        # witness-side attacks): against the witness's block for the
        # primary's chain, and against the primary's block for the
        # witness's chain — each pool keeps only the one that actually
        # conflicts with its committed header.
        against_witness = build(conflicting, verified.signed_header)
        against_primary = build(verified, conflicting.signed_header)
        targets = []
        if against_witness is not None:
            targets += [
                (p, against_witness)
                for p in [self.primary, *self.witnesses]
                if p is not witness
            ]
        if against_primary is not None:
            targets += [
                (p, against_primary)
                for p in self.witnesses
                if p is not self.primary
            ]
        for provider, ev in targets:
            try:
                await provider.report_evidence(ev)
                self.logger.info(
                    "reported light-client attack (common height %d) to %r",
                    anchor.height,
                    provider,
                )
            except Exception as e:  # noqa: BLE001
                self.logger.warning(
                    "failed to report evidence to %r: %r", provider, e
                )
