"""Version constants (reference version/version.go:13-15)."""

VERSION = "0.1.0"
ABCI_VERSION = "0.17.0"
BLOCK_PROTOCOL = 1
P2P_PROTOCOL = 1
