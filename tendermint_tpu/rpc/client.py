"""RPC clients (reference rpc/client): `HTTPClient` speaks JSON-RPC to a
node's RPC server; `websocket_events` yields subscription events.
`HTTPProvider` adapts the client into a light-client Provider (reference
light/provider/http)."""

from __future__ import annotations

import json
from typing import Any, AsyncIterator

import aiohttp

from ..light.provider import LightBlockNotFoundError, Provider, ProviderError
from ..light.types import LightBlock, SignedHeader
from ..types.block import (
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
)
from ..types.validator_set import Validator, ValidatorSet


class RPCClientError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"rpc error {code}: {message}")
        self.code = code


class HTTPClient:
    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")
        self._session: aiohttp.ClientSession | None = None
        self._id = 0

    async def _ensure(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def call(self, method: str, **params: Any) -> dict:
        session = await self._ensure()
        self._id += 1
        body = {
            "jsonrpc": "2.0",
            "id": self._id,
            "method": method,
            "params": {k: v for k, v in params.items() if v is not None},
        }
        async with session.post(self.base_url + "/", json=body) as resp:
            payload = await resp.json()
        if "error" in payload:
            raise RPCClientError(
                payload["error"].get("code", -1), payload["error"].get("message", "")
            )
        return payload["result"]

    # typed conveniences (the surface of reference rpc/client/interface.go)

    async def status(self) -> dict:
        return await self.call("status")

    async def block(self, height: int | None = None) -> dict:
        return await self.call("block", height=height)

    async def commit(self, height: int | None = None) -> dict:
        return await self.call("commit", height=height)

    async def validators(self, height: int | None = None, page: int = 1, per_page: int = 100) -> dict:
        return await self.call("validators", height=height, page=page, per_page=per_page)

    async def broadcast_tx_sync(self, tx: bytes) -> dict:
        return await self.call("broadcast_tx_sync", tx=tx.hex())

    async def broadcast_tx_commit(self, tx: bytes) -> dict:
        return await self.call("broadcast_tx_commit", tx=tx.hex())

    async def abci_query(self, path: str, data: bytes) -> dict:
        return await self.call("abci_query", path=path, data=data.hex())

    async def tx(self, hash_: bytes) -> dict:
        return await self.call("tx", hash=hash_.hex())

    async def tx_search(self, query: str) -> dict:
        return await self.call("tx_search", query=query)

    async def websocket_events(self, query: str) -> AsyncIterator[dict]:
        """Subscribe over the websocket endpoint and yield events."""
        session = await self._ensure()
        ws_url = self.base_url + "/websocket"
        async with session.ws_connect(ws_url) as ws:
            await ws.send_json(
                {"jsonrpc": "2.0", "id": 1, "method": "subscribe", "params": {"query": query}}
            )
            first = await ws.receive_json()  # ack
            if "error" in first:
                raise RPCClientError(-1, str(first["error"]))
            async for raw in ws:
                if raw.type != aiohttp.WSMsgType.TEXT:
                    break
                msg = json.loads(raw.data)
                if "result" in msg and msg["result"]:
                    yield msg["result"]


# -- JSON → domain decoding helpers ----------------------------------------


def _decode_block_id(d: dict) -> BlockID:
    return BlockID(
        bytes.fromhex(d["hash"]),
        PartSetHeader(d["parts"]["total"], bytes.fromhex(d["parts"]["hash"])),
    )


def _decode_header(d: dict) -> Header:
    return Header(
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time_ns=int(d["time"]),
        last_block_id=_decode_block_id(d["last_block_id"]),
        last_commit_hash=bytes.fromhex(d["last_commit_hash"]),
        data_hash=bytes.fromhex(d["data_hash"]),
        validators_hash=bytes.fromhex(d["validators_hash"]),
        next_validators_hash=bytes.fromhex(d["next_validators_hash"]),
        consensus_hash=bytes.fromhex(d["consensus_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
        last_results_hash=bytes.fromhex(d["last_results_hash"]),
        evidence_hash=bytes.fromhex(d["evidence_hash"]),
        proposer_address=bytes.fromhex(d["proposer_address"]),
        version=int(d["version"]["block"]),
    )


def _decode_commit(d: dict) -> Commit:
    sigs = tuple(
        CommitSig(
            flag=s["block_id_flag"],
            validator_address=bytes.fromhex(s["validator_address"]),
            timestamp_ns=int(s["timestamp"]),
            signature=bytes.fromhex(s["signature"]) if s["signature"] else b"",
        )
        for s in d["signatures"]
    )
    return Commit(int(d["height"]), d["round"], _decode_block_id(d["block_id"]), sigs)


class HTTPProvider(Provider):
    """Light-client provider over RPC (reference light/provider/http)."""

    def __init__(self, chain_id: str, client: HTTPClient):
        self._chain_id = chain_id
        self.client = client

    def chain_id(self) -> str:
        return self._chain_id

    async def light_block(self, height: int) -> LightBlock:
        try:
            com = await self.client.commit(height or None)
            h = int(com["signed_header"]["header"]["height"])
            # paginate: sets larger than one page must be fetched fully or
            # the reconstructed hash won't match the header
            raw_vals: list[dict] = []
            page = 1
            while True:
                vals = await self.client.validators(h, page=page, per_page=100)
                raw_vals.extend(vals["validators"])
                if len(raw_vals) >= int(vals["total"]) or not vals["validators"]:
                    break
                page += 1
        except RPCClientError as e:
            raise LightBlockNotFoundError(str(e)) from e
        except aiohttp.ClientError as e:
            raise ProviderError(str(e)) from e
        from ..crypto import pubkey_from_type_and_bytes

        validators = ValidatorSet(
            [
                Validator(
                    pubkey_from_type_and_bytes(
                        v["pub_key"]["type"], bytes.fromhex(v["pub_key"]["value"])
                    ),
                    int(v["voting_power"]),
                    int(v["proposer_priority"]),
                )
                for v in raw_vals
            ]
        )
        header = _decode_header(com["signed_header"]["header"])
        commit = _decode_commit(com["signed_header"]["commit"])
        return LightBlock(SignedHeader(header, commit), validators)

    async def report_evidence(self, evidence) -> None:
        await self.client.call("broadcast_evidence", evidence=evidence.encode().hex())
