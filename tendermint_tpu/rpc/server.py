"""JSON-RPC server (reference rpc/jsonrpc/server): HTTP POST body JSON-RPC
2.0, GET URI-style calls (/status?height=5), and a /websocket endpoint
for event subscriptions (subscribe/unsubscribe with pubsub queries)."""

from __future__ import annotations

import asyncio
import json
import logging

from aiohttp import WSMsgType, web

from ..libs.pubsub import Query
from .core import ROUTES, Environment, RPCError


def _rpc_response(id_, result=None, error=None) -> dict:
    out = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        out["error"] = error
    else:
        out["result"] = result
    return out


def _event_json(msg) -> dict:
    """Best-effort JSON for a pubsub event message."""
    data = msg.data
    d: dict = {"type": type(data).__name__}
    for attr in ("height", "round", "step", "index"):
        if hasattr(data, attr):
            d[attr] = getattr(data, attr)
    if hasattr(data, "tx"):
        d["tx"] = data.tx.hex()
    if hasattr(data, "block") and data.block is not None:
        d["block_height"] = data.block.header.height
        d["block_hash"] = data.block.hash().hex().upper()
    return {"query": str(msg.events or {}), "data": d, "events": msg.events}


class RPCServer:
    def __init__(
        self,
        env: Environment,
        *,
        enable_pprof: bool = False,
        logger: logging.Logger | None = None,
    ):
        self.env = env
        self.logger = logger or logging.getLogger("rpc.server")
        self.app = web.Application()
        self.app.router.add_post("/", self._handle_jsonrpc)
        self.app.router.add_get("/websocket", self._handle_ws)
        self.app.router.add_get("/metrics", self._handle_metrics)
        # flight-recorder endpoints (libs/trace.py): always on — reading
        # the span ring is cheap and the whole layer is off-switchable
        # via TMTPU_TRACE / [trace]
        self.app.router.add_get("/debug/traces", self._handle_traces)
        self.app.router.add_get("/debug/flight", self._handle_flight)
        if enable_pprof:
            # live profiling over HTTP — opt-in, like the reference which
            # only serves Go pprof when pprof-laddr is explicitly set
            # (config/config.go:529-530): profiling slows the event loop,
            # so it must never be reachable by default
            self.app.router.add_get("/debug/pprof/profile", self._handle_profile)
            self.app.router.add_get("/debug/pprof/heap", self._handle_heap)
            self.app.router.add_get("/debug/pprof/stacks", self._handle_stacks)
        for name in ROUTES:
            self.app.router.add_get(f"/{name}", self._make_uri_handler(name))
        self._runner: web.AppRunner | None = None
        self._site: web.TCPSite | None = None
        self.port: int | None = None

    # -- lifecycle -------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, host, port)
        await self._site.start()
        self.port = self._site._server.sockets[0].getsockname()[1]
        self.logger.info("RPC listening on %s:%d", host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        metrics = getattr(self.env, "metrics", None)
        if metrics is None:
            # an empty registry render, NOT a 404: scrapers and the e2e
            # harness must not have to special-case node roles that
            # carry no metrics object (seed nodes, light proxies)
            from ..libs.metrics import Registry

            return web.Response(
                text=Registry().render(), content_type="text/plain", charset="utf-8"
            )
        return web.Response(
            text=metrics.render(), content_type="text/plain", charset="utf-8"
        )

    # -- flight recorder (libs/trace.py) ---------------------------------

    async def _handle_traces(self, request: web.Request) -> web.Response:
        """Last N spans from the flight recorder as JSON. Filters:
        ?n=, ?subsystem=, ?trace_id= (one end-to-end trace)."""
        from ..libs import trace

        try:
            n = int(request.query["n"]) if "n" in request.query else None
            trace_id = (
                int(request.query["trace_id"])
                if "trace_id" in request.query
                else None
            )
        except ValueError:
            return web.Response(status=400, text="bad n/trace_id\n")
        spans = trace.RECORDER.dump(
            n, subsystem=request.query.get("subsystem"), trace_id=trace_id
        )
        return web.json_response(
            {"stats": trace.RECORDER.stats(), "spans": spans}
        )

    async def _handle_flight(self, request: web.Request) -> web.Response:
        """Flight-recorder status; ?dump=reason forces a dump (the same
        path a wedge/breaker-trip takes automatically)."""
        from ..libs import trace

        reason = request.query.get("dump")
        if reason:
            path = trace.auto_dump(f"manual-{reason}")
            return web.json_response(
                {"dumped": True, "path": path, "stats": trace.RECORDER.stats()}
            )
        return web.json_response({"stats": trace.RECORDER.stats()})

    # -- live profiling (reference pprof-laddr, config/config.go:529) ----

    _profiling = False

    async def _handle_profile(self, request: web.Request) -> web.Response:
        """CPU profile of the event-loop thread for ?seconds=N (default 5):
        the hot node's consensus/verification work all runs on this loop,
        so this is the profile that matters. One at a time."""
        import cProfile
        import io
        import pstats

        import math

        if RPCServer._profiling:
            return web.Response(status=429, text="profile already running\n")
        try:
            seconds = float(request.query.get("seconds", "5"))
        except ValueError:
            return web.Response(status=400, text="bad seconds\n")
        # NaN poisons min() AND asyncio.sleep (never fires, leaving the
        # profiler enabled forever) — require a finite positive window
        if not math.isfinite(seconds) or not 0 < seconds <= 120:
            return web.Response(status=400, text="seconds must be in (0, 120]\n")
        RPCServer._profiling = True
        prof = cProfile.Profile()
        try:
            prof.enable()
            await asyncio.sleep(seconds)
        finally:
            prof.disable()
            RPCServer._profiling = False
        buf = io.StringIO()
        stats = pstats.Stats(prof, stream=buf)
        stats.sort_stats("cumulative").print_stats(60)
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    async def _handle_heap(self, request: web.Request) -> web.Response:
        """Heap allocation snapshot via tracemalloc. First call arms
        tracing and returns a baseline notice; later calls report top
        allocation sites since then (?top=N, default 40)."""
        import tracemalloc

        if request.query.get("op") == "stop":
            tracemalloc.stop()
            return web.Response(text="tracemalloc disarmed\n", content_type="text/plain")
        top = min(int(request.query.get("top", "40")), 200)
        if not tracemalloc.is_tracing():
            tracemalloc.start(10)
            return web.Response(
                text="tracemalloc armed; call again for a snapshot\n",
                content_type="text/plain",
            )
        snap = tracemalloc.take_snapshot()
        lines = [
            f"heap snapshot: {len(snap.traces)} traces, "
            f"current={tracemalloc.get_traced_memory()[0]:,}B "
            f"peak={tracemalloc.get_traced_memory()[1]:,}B",
        ]
        for stat in snap.statistics("lineno")[:top]:
            lines.append(str(stat))
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    async def _handle_stacks(self, request: web.Request) -> web.Response:
        """All-thread stack dump (goroutine-dump analog)."""
        import sys
        import threading
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            out.append(f"Thread {names.get(ident, '?')} ({ident}):")
            out.extend(l.rstrip() for l in traceback.format_stack(frame))
            out.append("")
        return web.Response(text="\n".join(out), content_type="text/plain")

    # -- dispatch --------------------------------------------------------

    async def _call(self, method: str, params: dict):
        if method not in ROUTES:
            raise RPCError(-32601, f"method {method!r} not found")
        handler = getattr(self.env, method)
        return await handler(**(params or {}))

    async def _handle_jsonrpc(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                _rpc_response(None, error={"code": -32700, "message": "parse error"})
            )
        calls = body if isinstance(body, list) else [body]
        responses = []
        for call in calls:
            id_ = call.get("id")
            try:
                result = await self._call(call.get("method", ""), call.get("params") or {})
                responses.append(_rpc_response(id_, result))
            except RPCError as e:
                responses.append(
                    _rpc_response(id_, error={"code": e.code, "message": e.message})
                )
            except TypeError as e:
                responses.append(
                    _rpc_response(id_, error={"code": -32602, "message": str(e)})
                )
            except Exception as e:
                self.logger.exception("rpc %s failed", call.get("method"))
                responses.append(
                    _rpc_response(id_, error={"code": -32603, "message": repr(e)})
                )
        payload = responses if isinstance(body, list) else responses[0]
        return web.json_response(payload)

    def _make_uri_handler(self, name: str):
        async def handler(request: web.Request) -> web.Response:
            params = dict(request.query)
            try:
                result = await self._call(name, params)
                return web.json_response(_rpc_response(-1, result))
            except RPCError as e:
                return web.json_response(
                    _rpc_response(-1, error={"code": e.code, "message": e.message})
                )
            except Exception as e:
                return web.json_response(
                    _rpc_response(-1, error={"code": -32603, "message": repr(e)})
                )

        return handler

    # -- websocket subscriptions ----------------------------------------

    async def _handle_ws(self, request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        subscriber = f"ws-{id(ws)}"
        pumps: list[asyncio.Task] = []
        try:
            async for raw in ws:
                if raw.type != WSMsgType.TEXT:
                    continue
                try:
                    call = json.loads(raw.data)
                except json.JSONDecodeError:
                    continue
                method = call.get("method")
                id_ = call.get("id")
                params = call.get("params") or {}
                if method == "subscribe":
                    try:
                        q = Query.parse(params["query"])
                    except Exception as e:
                        await ws.send_json(
                            _rpc_response(id_, error={"code": -32602, "message": str(e)})
                        )
                        continue
                    # bounded fan-out: a slow websocket consumer loses
                    # events (counted in pubsub.DROPPED / /metrics), it
                    # never grows an unbounded queue or kills the sub
                    sub = self.env.event_bus.subscribe(
                        subscriber, q, buffer=256, drop_on_full=True
                    )
                    pumps.append(
                        asyncio.get_running_loop().create_task(
                            self._pump(ws, id_, sub)
                        )
                    )
                    await ws.send_json(_rpc_response(id_, {}))
                elif method == "unsubscribe_all" or method == "unsubscribe":
                    self.env.event_bus.unsubscribe_all(subscriber)
                    await ws.send_json(_rpc_response(id_, {}))
                else:
                    try:
                        result = await self._call(method, params)
                        await ws.send_json(_rpc_response(id_, result))
                    except RPCError as e:
                        await ws.send_json(
                            _rpc_response(id_, error={"code": e.code, "message": e.message})
                        )
                    except TypeError as e:
                        await ws.send_json(
                            _rpc_response(id_, error={"code": -32602, "message": str(e)})
                        )
                    except Exception as e:
                        # one bad request must not tear down the socket
                        # (and every live subscription with it)
                        await ws.send_json(
                            _rpc_response(id_, error={"code": -32603, "message": repr(e)})
                        )
        finally:
            self.env.event_bus.unsubscribe_all(subscriber)
            for p in pumps:
                p.cancel()
        return ws

    async def _pump(self, ws, id_, sub) -> None:
        try:
            async for msg in sub:
                await ws.send_json(_rpc_response(id_, _event_json(msg)))
        except Exception as e:
            # client gone / send raced the close — the pump just ends
            self.logger.debug("ws event pump %s ended: %r", id_, e)
