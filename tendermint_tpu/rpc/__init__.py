"""JSON-RPC layer (reference internal/rpc/core + rpc/jsonrpc): ~30
routes over HTTP POST (JSON-RPC 2.0), GET (URI params), and websocket
event subscriptions."""
