"""RPC handlers + the Environment they close over (reference
internal/rpc/core/env.go and the per-domain handler files). All handlers
return JSON-ready dicts; bytes are hex-encoded (upper-case hashes, like
the reference's JSON)."""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any

from ..abci import types as abci
from ..crypto.hashes import sha256
from ..libs.pubsub import Query
from ..mempool.ingress import IngressBusyError
from ..mempool.pool import MempoolFullError, TxInCacheError, TxRejectedError
from ..state.indexer import KVSink
from ..types.events import EventBus

#: CheckTx code returned when the ingress pipeline sheds (explicit
#: backpressure under tx flood) — clients should back off and resubmit;
#: distinct from any app rejection code so a flood is diagnosable from
#: the responses alone
MEMPOOL_BUSY_CODE = 429


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _hex(b: bytes) -> str:
    return b.hex().upper()


def _block_id_json(bid) -> dict:
    return {
        "hash": _hex(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": _hex(bid.part_set_header.hash),
        },
    }


def _header_json(h) -> dict:
    return {
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": str(h.time_ns),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hex(h.last_commit_hash),
        "data_hash": _hex(h.data_hash),
        "validators_hash": _hex(h.validators_hash),
        "next_validators_hash": _hex(h.next_validators_hash),
        "consensus_hash": _hex(h.consensus_hash),
        "app_hash": _hex(h.app_hash),
        "last_results_hash": _hex(h.last_results_hash),
        "evidence_hash": _hex(h.evidence_hash),
        "proposer_address": _hex(h.proposer_address),
        "version": {"block": str(h.version)},
    }


def _commit_json(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": s.flag,
                "validator_address": _hex(s.validator_address),
                "timestamp": str(s.timestamp_ns),
                "signature": s.signature.hex() if s.signature else None,
            }
            for s in c.signatures
        ],
    }


def _block_json(b) -> dict:
    return {
        "header": _header_json(b.header),
        "data": {"txs": [tx.hex() for tx in b.txs]},
        "evidence": {"evidence": [ev.encode().hex() for ev in b.evidence]},
        "last_commit": _commit_json(b.last_commit) if b.last_commit else None,
    }


def _validator_json(v) -> dict:
    return {
        "address": _hex(v.address),
        "pub_key": {"type": v.pub_key.TYPE, "value": v.pub_key.bytes().hex()},
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def _tx_result_json(r) -> dict:
    return {
        "hash": _hex(r.hash),
        "height": str(r.height),
        "index": r.index,
        "tx": r.tx.hex(),
        "tx_result": {
            "code": r.code,
            "data": r.data.hex(),
            "log": r.log,
            "events": r.events,
        },
    }


@dataclass
class Environment:
    """Everything the handlers reach into (reference env.go)."""

    chain_id: str
    genesis_doc: Any = None
    state_store: Any = None
    block_store: Any = None
    mempool: Any = None
    evidence_pool: Any = None
    consensus: Any = None
    app_conns: Any = None
    event_bus: EventBus | None = None
    sink: KVSink | None = None
    peer_manager: Any = None
    node_info: Any = None
    metrics: Any = None  # NodeMetrics, rendered by /metrics
    # TxIngress (mempool/ingress.py): when set, broadcast_tx_* routes
    # through the staged admission pipeline (bounded intake, batched
    # signature pre-verify, nonce lanes) instead of bare check_tx
    ingress: Any = None
    logger: logging.Logger = field(default_factory=lambda: logging.getLogger("rpc"))
    # in-flight fire-and-forget CheckTx tasks (broadcast_tx_async): held
    # so they are reachable (cancellable, exceptions retrieved) instead
    # of floating free of every Service reap
    _checktx_tasks: set = field(default_factory=set, repr=False)

    # ------------------------------------------------------------------
    # info routes
    # ------------------------------------------------------------------

    async def health(self) -> dict:
        return {}

    async def status(self) -> dict:
        height = self.block_store.height()
        meta = self.block_store.load_block_meta(height) if height else None
        state = self.state_store.load()
        val_info = {}
        if self.consensus is not None and self.consensus.priv_validator is not None:
            pub = self.consensus.priv_validator.get_pub_key()
            power = 0
            if state is not None and state.validators is not None:
                _, val = state.validators.get_by_address(pub.address())
                power = val.voting_power if val else 0
            val_info = {
                "address": _hex(pub.address()),
                "pub_key": {"type": pub.TYPE, "value": pub.bytes().hex()},
                "voting_power": str(power),
            }
        return {
            "node_info": {
                "id": self.node_info.node_id if self.node_info else "",
                "network": self.chain_id,
                "moniker": self.node_info.moniker if self.node_info else "",
            },
            "sync_info": {
                "latest_block_height": str(height),
                "latest_block_hash": _hex(meta.block_id.hash) if meta else "",
                "latest_app_hash": _hex(state.app_hash) if state else "",
                "latest_block_time": str(meta.header.time_ns) if meta else "0",
                "earliest_block_height": str(self.block_store.base()),
                "catching_up": False,
            },
            "validator_info": val_info,
        }

    async def net_info(self) -> dict:
        peers = self.peer_manager.connected_peers() if self.peer_manager else []
        return {
            "listening": True,
            "n_peers": str(len(peers)),
            "peers": [{"node_id": p} for p in peers],
        }

    async def genesis(self) -> dict:
        return {"genesis": self.genesis_doc.to_json() if self.genesis_doc else None}

    async def consensus_params(self, height: int | None = None) -> dict:
        state = self.state_store.load()
        h = int(height) if height else state.last_block_height + 1
        params = self.state_store.load_consensus_params(h) or state.consensus_params
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {
                    "max_bytes": str(params.block.max_bytes),
                    "max_gas": str(params.block.max_gas),
                },
                "evidence": {
                    "max_age_num_blocks": str(params.evidence.max_age_num_blocks),
                    "max_age_duration": str(params.evidence.max_age_duration_ns),
                    "max_bytes": str(params.evidence.max_bytes),
                },
                "validator": {
                    "pub_key_types": list(params.validator.pub_key_types)
                },
            },
        }

    async def consensus_state(self) -> dict:
        if self.consensus is None:
            raise RPCError(-32603, "consensus not running")
        rs = self.consensus.rs
        return {
            "round_state": {
                "height": str(rs.height),
                "round": rs.round,
                "step": rs.step.name,
                "proposal": rs.proposal is not None,
                "proposal_block_hash": _hex(rs.proposal_block.hash())
                if rs.proposal_block
                else None,
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
            }
        }

    # ------------------------------------------------------------------
    # block routes
    # ------------------------------------------------------------------

    def _height_or_latest(self, height) -> int:
        if height in (None, 0, "0", ""):
            return self.block_store.height()
        h = int(height)
        if h <= 0:
            raise RPCError(-32602, f"height must be positive, got {h}")
        if h > self.block_store.height():
            raise RPCError(
                -32602,
                f"height {h} beyond store height {self.block_store.height()}",
            )
        return h

    async def block(self, height: int | None = None) -> dict:
        h = self._height_or_latest(height)
        block = self.block_store.load_block(h)
        meta = self.block_store.load_block_meta(h)
        if block is None or meta is None:
            raise RPCError(-32603, f"no block at height {h}")
        return {"block_id": _block_id_json(meta.block_id), "block": _block_json(block)}

    async def block_by_hash(self, hash: str) -> dict:
        block = self.block_store.load_block_by_hash(bytes.fromhex(hash))
        if block is None:
            raise RPCError(-32603, f"no block with hash {hash}")
        return await self.block(block.header.height)

    async def header(self, height: int | None = None) -> dict:
        h = self._height_or_latest(height)
        meta = self.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no header at height {h}")
        return {"header": _header_json(meta.header)}

    async def commit(self, height: int | None = None) -> dict:
        h = self._height_or_latest(height)
        meta = self.block_store.load_block_meta(h)
        commit = self.block_store.load_block_commit(h)
        canonical = commit is not None
        if commit is None:
            commit = self.block_store.load_seen_commit(h)
        if meta is None or commit is None:
            raise RPCError(-32603, f"no commit at height {h}")
        return {
            "signed_header": {
                "header": _header_json(meta.header),
                "commit": _commit_json(commit),
            },
            "canonical": canonical,
        }

    async def blockchain(self, minHeight: int | None = None, maxHeight: int | None = None) -> dict:
        max_h = self._height_or_latest(maxHeight)
        min_h = max(int(minHeight or 1), self.block_store.base())
        max_h = min(max_h, min_h + 19)  # page limit, reference limits to 20
        metas = []
        for h in range(max_h, min_h - 1, -1):
            meta = self.block_store.load_block_meta(h)
            if meta is not None:
                metas.append(
                    {
                        "block_id": _block_id_json(meta.block_id),
                        "block_size": str(meta.block_size),
                        "header": _header_json(meta.header),
                        "num_txs": str(meta.num_txs),
                    }
                )
        return {
            "last_height": str(self.block_store.height()),
            "block_metas": metas,
        }

    async def block_results(self, height: int | None = None) -> dict:
        h = self._height_or_latest(height)
        responses = self.state_store.load_abci_responses(h)
        if responses is None:
            raise RPCError(-32603, f"no results for height {h}")
        return {
            "height": str(h),
            "txs_results": [
                {"code": r.code, "data": r.data.hex(), "log": r.log,
                 "gas_wanted": str(r.gas_wanted), "gas_used": str(r.gas_used)}
                for r in responses.deliver_txs
            ],
            "validator_updates": [
                {"pub_key": u.pub_key.hex(), "power": str(u.power)}
                for u in responses.end_block.validator_updates
            ],
        }

    async def validators(
        self, height: int | None = None, page: int = 1, per_page: int = 30
    ) -> dict:
        state = self.state_store.load()
        h = int(height) if height else state.last_block_height + 1
        vals = self.state_store.load_validators(h)
        if vals is None:
            raise RPCError(-32603, f"no validator set at height {h}")
        page, per_page = max(int(page), 1), min(int(per_page), 100)
        start = (page - 1) * per_page
        chunk = vals.validators[start : start + per_page]
        return {
            "block_height": str(h),
            "validators": [_validator_json(v) for v in chunk],
            "count": str(len(chunk)),
            "total": str(len(vals)),
        }

    # ------------------------------------------------------------------
    # tx routes
    # ------------------------------------------------------------------

    async def broadcast_tx_async(self, tx: str) -> dict:
        raw = bytes.fromhex(tx)
        if self.ingress is not None:
            # fire-and-forget through the staged pipeline: the verdict
            # future's exception is pre-retrieved by the ingress, so
            # dropping the handle leaks nothing; a full pipeline sheds
            # here synchronously (counted), which async mode swallows by
            # contract (it promises no CheckTx result)
            self.ingress.submit_nowait(raw, source="rpc")
            return {"code": 0, "hash": _hex(sha256(raw)), "log": ""}
        t = asyncio.get_running_loop().create_task(self._checktx_quiet(raw))
        self._checktx_tasks.add(t)
        t.add_done_callback(self._checktx_tasks.discard)
        return {"code": 0, "hash": _hex(sha256(raw)), "log": ""}

    async def _checktx_quiet(self, raw: bytes) -> None:
        try:
            await self.mempool.check_tx(raw)
        except Exception as e:
            # async broadcast promises no CheckTx result; rejections are
            # expected noise but must not vanish without a trace
            self.logger.debug("async checktx dropped tx: %r", e)

    async def broadcast_tx_sync(self, tx: str) -> dict:
        raw = bytes.fromhex(tx)
        try:
            if self.ingress is not None:
                # per-mode verdict future: sync mode awaits the full
                # admission verdict (verify -> nonce lane -> checktx ->
                # insert), not just the ABCI round-trip
                await self.ingress.submit_nowait(raw, source="rpc")
            else:
                await self.mempool.check_tx(raw)
        except TxInCacheError:
            return {"code": 0, "hash": _hex(sha256(raw)), "log": "tx already in cache"}
        except (IngressBusyError, MempoolFullError) as e:
            # explicit backpressure: the front door (or the pool behind
            # it) is full — back off and resubmit, nothing was buffered
            return {"code": MEMPOOL_BUSY_CODE, "hash": _hex(sha256(raw)), "log": str(e)}
        except TxRejectedError as e:
            return {"code": e.code or 1, "hash": _hex(sha256(raw)), "log": e.log}
        return {"code": 0, "hash": _hex(sha256(raw)), "log": ""}

    async def broadcast_tx_commit(self, tx: str, timeout: float = 30.0) -> dict:
        """Submit and wait for the tx to be committed (reference
        rpc/core/mempool.go BroadcastTxCommit — subscribes first)."""
        import asyncio
        import uuid

        raw = bytes.fromhex(tx)
        h = sha256(raw)
        if self.event_bus is None:
            raise RPCError(-32603, "event bus unavailable")
        q = Query.parse(f"tm.event='Tx' AND tx.hash='{_hex(h)}'")
        # unique subscriber id: concurrent commits of the same tx must not
        # collide on the (subscriber, query) key
        subscriber = f"btc-{uuid.uuid4().hex[:12]}"
        sub = self.event_bus.subscribe(subscriber, q, buffer=1)
        try:
            res = await self.broadcast_tx_sync(tx)
            if res["code"] != 0:
                return {"check_tx": res, "deliver_tx": None, "hash": _hex(h), "height": "0"}
            if "already in cache" in res.get("log", ""):
                # possibly committed long ago — answer from the index
                # rather than waiting for an event that already fired
                if self.sink is not None:
                    prior = self.sink.get_tx(h)
                    if prior is not None:
                        return {
                            "check_tx": res,
                            "deliver_tx": {
                                "code": prior.code,
                                "data": prior.data.hex(),
                                "log": prior.log,
                            },
                            "hash": _hex(h),
                            "height": str(prior.height),
                        }
            msg = await asyncio.wait_for(sub.next(), timeout)
            data = msg.data
            r = data.result
            return {
                "check_tx": res,
                "deliver_tx": {"code": r.code, "data": r.data.hex(), "log": r.log},
                "hash": _hex(h),
                "height": str(data.height),
            }
        except asyncio.TimeoutError:
            raise RPCError(-32603, "timed out waiting for tx to be committed")
        finally:
            self.event_bus.unsubscribe_all(subscriber)

    async def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self.mempool.reap_max_txs(int(limit))
        return {
            "n_txs": str(len(txs)),
            "total": str(self.mempool.size()),
            "total_bytes": str(self.mempool.size_bytes()),
            "txs": [t.hex() for t in txs],
        }

    async def num_unconfirmed_txs(self) -> dict:
        return {
            "n_txs": str(self.mempool.size()),
            "total": str(self.mempool.size()),
            "total_bytes": str(self.mempool.size_bytes()),
        }

    async def check_tx(self, tx: str) -> dict:
        res = await self.app_conns.mempool.check_tx(
            abci.RequestCheckTx(bytes.fromhex(tx))
        )
        return {"code": res.code, "log": res.log, "gas_wanted": str(res.gas_wanted)}

    async def tx(self, hash: str) -> dict:
        if self.sink is None:
            raise RPCError(-32603, "indexing disabled")
        res = self.sink.get_tx(bytes.fromhex(hash))
        if res is None:
            raise RPCError(-32603, f"tx {hash} not found")
        return _tx_result_json(res)

    async def tx_search(self, query: str, per_page: int = 30, **_kw) -> dict:
        if self.sink is None:
            raise RPCError(-32603, "indexing disabled")
        results = self.sink.search_txs(Query.parse(query), limit=int(per_page))
        return {
            "txs": [_tx_result_json(r) for r in results],
            "total_count": str(len(results)),
        }

    async def block_search(self, query: str, per_page: int = 30, **_kw) -> dict:
        if self.sink is None:
            raise RPCError(-32603, "indexing disabled")
        heights = self.sink.search_blocks(Query.parse(query), limit=int(per_page))
        blocks = []
        for h in heights:
            try:
                blocks.append(await self.block(h))
            except RPCError:
                continue
        return {"blocks": blocks, "total_count": str(len(blocks))}

    # ------------------------------------------------------------------
    # abci + evidence
    # ------------------------------------------------------------------

    async def abci_info(self) -> dict:
        res = await self.app_conns.query.info(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": res.last_block_app_hash.hex(),
            }
        }

    async def abci_query(
        self, path: str = "", data: str = "", height: int = 0, prove: bool = False
    ) -> dict:
        # URI params arrive as strings: 'false'/'0' must mean False
        if isinstance(prove, str):
            prove = prove.lower() not in ("", "0", "false", "no")
        res = await self.app_conns.query.query(
            abci.RequestQuery(
                data=bytes.fromhex(data), path=path, height=int(height), prove=bool(prove)
            )
        )
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "key": res.key.hex(),
                "value": res.value.hex(),
                "height": str(res.height),
                "proof_ops": {
                    "ops": [
                        {
                            "type": op.type_,
                            "key": op.key.hex(),
                            "data": op.data.hex(),
                        }
                        for op in res.proof_ops
                    ]
                },
            }
        }

    async def broadcast_evidence(self, evidence: str) -> dict:
        from ..types.evidence import decode_evidence

        ev = decode_evidence(bytes.fromhex(evidence))
        self.evidence_pool.add_evidence(ev)
        return {"hash": _hex(ev.hash())}

    # ------------------------------------------------------------------
    # LightFleet serving surface (light/fleet.py): a full node is the
    # provider side — it serves its own light blocks and hop proofs
    # straight from its stores (it IS the authority for them; clients
    # and LightDs verify). The light proxy overrides both with
    # hop-cache-backed, verified versions.
    # ------------------------------------------------------------------

    def _light_block_at(self, height) -> tuple[int, Any]:
        from ..light.types import LightBlock, SignedHeader

        h = int(height or 0) or self.block_store.height()
        meta = self.block_store.load_block_meta(h)
        commit = self.block_store.load_block_commit(h)
        if commit is None:
            commit = self.block_store.load_seen_commit(h)
        vals = self.state_store.load_validators(h)
        if meta is None or commit is None or vals is None:
            raise RPCError(-32603, f"no light block at height {h}")
        return h, LightBlock(SignedHeader(meta.header, commit), vals)

    async def light_block(self, height: int | None = None) -> dict:
        h, lb = self._light_block_at(height)
        return {
            "height": str(h),
            "hash": lb.header.hash().hex(),
            "light_block": lb.encode().hex(),
        }

    async def hop_proof(self, height: int | None = None) -> dict:
        """The hop proof for `height`, folded to the committee's best
        wire form (BLS committees: one 96-byte aggregate + signer
        bitmap; otherwise per-sig) — what a remote LightD or
        re-verifying client consumes."""
        from ..light.fleet import make_hop_proof

        h, lb = self._light_block_at(height)
        proof = make_hop_proof(lb)
        return {
            "height": str(h),
            "scheme": proof.scheme,
            "wire_bytes": str(proof.wire_bytes()),
            "proof": proof.encode().hex(),
        }


ROUTES = [
    "health",
    "status",
    "net_info",
    "genesis",
    "consensus_params",
    "consensus_state",
    "block",
    "block_by_hash",
    "header",
    "commit",
    "blockchain",
    "block_results",
    "validators",
    "broadcast_tx_async",
    "broadcast_tx_sync",
    "broadcast_tx_commit",
    "unconfirmed_txs",
    "num_unconfirmed_txs",
    "check_tx",
    "tx",
    "tx_search",
    "block_search",
    "abci_info",
    "abci_query",
    "broadcast_evidence",
    "light_block",
    "hop_proof",
]
