"""Deterministic protobuf-wire-compatible encoder.

The consensus-critical byte strings in this framework (canonical vote
sign-bytes, header field encodings, hashes) are produced by this module. It
implements the subset of the protobuf wire format needed for canonical
encodings — varint, fixed64/sfixed64, and length-delimited fields — with
strictly deterministic output (fields emitted in ascending tag order, default
values omitted, no unknown fields).

The reference builds its canonical sign-bytes from gogoproto-generated
marshalling (reference types/canonical.go:56, sfixed64 height/round); this
module provides the same determinism guarantees without a codegen step.
"""

from __future__ import annotations

import struct

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5


def uvarint(value: int) -> bytes:
    """Encode an unsigned integer as a protobuf base-128 varint."""
    if value < 0:
        raise ValueError("uvarint requires a non-negative value")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def svarint(value: int) -> bytes:
    """Zigzag-encoded signed varint."""
    return uvarint((value << 1) ^ (value >> 63) if value < 0 else value << 1)


def tag(field_number: int, wire_type: int) -> bytes:
    return uvarint((field_number << 3) | wire_type)


def varint_field(field_number: int, value: int) -> bytes:
    """Varint field; 0 is omitted (proto3 default-elision)."""
    if value == 0:
        return b""
    if value < 0:
        # proto encodes negative int64 as 10-byte two's complement varint
        value &= (1 << 64) - 1
    return tag(field_number, WIRE_VARINT) + uvarint(value)


def bool_field(field_number: int, value: bool) -> bytes:
    return varint_field(field_number, 1 if value else 0)


def sfixed64_field(field_number: int, value: int) -> bytes:
    if value == 0:
        return b""
    return tag(field_number, WIRE_FIXED64) + struct.pack("<q", value)


def fixed64_field(field_number: int, value: int) -> bytes:
    if value == 0:
        return b""
    return tag(field_number, WIRE_FIXED64) + struct.pack("<Q", value)


def bytes_field(field_number: int, value: bytes) -> bytes:
    """Length-delimited field; empty bytes are omitted."""
    if not value:
        return b""
    return tag(field_number, WIRE_BYTES) + uvarint(len(value)) + value


def string_field(field_number: int, value: str) -> bytes:
    return bytes_field(field_number, value.encode("utf-8"))


def message_field(field_number: int, encoded: bytes) -> bytes:
    """Embedded message field. Unlike bytes_field, an empty message is still
    emitted when explicitly requested (callers pass None to omit)."""
    return tag(field_number, WIRE_BYTES) + uvarint(len(encoded)) + encoded


def len_prefixed(encoded: bytes) -> bytes:
    """Length-delimit a full message (framing used for streams and hashing)."""
    return uvarint(len(encoded)) + encoded


def check_repeat(items, bound: int, what: str) -> None:
    """Clamp a repeated-field collection at decode. Wire frames arrive
    from untrusted peers (and durable bytes see chaos bit-rot), so a
    corrupt repeat count must raise, never allocate — the shared
    checker every decode loop calls with its module's named ``MAX_*``
    bound (the tmtlint wire-bounds rule recognizes the call as the
    clamp)."""
    if len(items) > bound:
        raise ValueError(f"wire frame repeats {what} beyond {bound}")


class Reader:
    """Minimal wire-format reader for decoding our own encodings."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def eof(self) -> bool:
        return self.pos >= len(self.data)

    def read_uvarint(self) -> int:
        shift = 0
        result = 0
        while True:
            if self.pos >= len(self.data):
                raise ValueError("truncated varint")
            b = self.data[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")

    def read_tag(self) -> tuple[int, int]:
        v = self.read_uvarint()
        return v >> 3, v & 0x7

    def read_fixed64(self) -> int:
        if self.pos + 8 > len(self.data):
            raise ValueError("truncated fixed64")
        (v,) = struct.unpack_from("<Q", self.data, self.pos)
        self.pos += 8
        return v

    def read_sfixed64(self) -> int:
        if self.pos + 8 > len(self.data):
            raise ValueError("truncated sfixed64")
        (v,) = struct.unpack_from("<q", self.data, self.pos)
        self.pos += 8
        return v

    def read_bytes(self) -> bytes:
        n = self.read_uvarint()
        if self.pos + n > len(self.data):
            raise ValueError("truncated bytes")
        v = self.data[self.pos : self.pos + n]
        self.pos += n
        return v

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def skip(self, wire_type: int) -> None:
        if wire_type == WIRE_VARINT:
            self.read_uvarint()
        elif wire_type == WIRE_FIXED64:
            self.pos += 8
        elif wire_type == WIRE_BYTES:
            self.read_bytes()
        elif wire_type == WIRE_FIXED32:
            self.pos += 4
        else:
            raise ValueError(f"unknown wire type {wire_type}")
