"""Metrics registry with Prometheus text exposition (the analog of the
reference's go-kit/prometheus metrics — one Metrics struct per subsystem
with a nop fallback, reference internal/consensus/metrics.go:19 etc.).

Counters, gauges, and histograms are process-local and lock-free (the
event loop serializes updates); `render()` emits the text format that
Prometheus scrapes, served by the node's /metrics endpoint."""

from __future__ import annotations

import time
from collections import defaultdict


class Counter:
    __slots__ = ("name", "help", "_values")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, **labels) -> None:
        self._values[tuple(sorted(labels.items()))] += value

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for labels, v in self._values.items():
            out.append(f"{self.name}{_fmt_labels(labels)} {_fmt(v)}")
        if not self._values:
            out.append(f"{self.name} 0")
        return out


class Gauge:
    __slots__ = ("name", "help", "_values")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        self._values[tuple(sorted(labels.items()))] = value

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for labels, v in self._values.items():
            out.append(f"{self.name}{_fmt_labels(labels)} {_fmt(v)}")
        if not self._values:
            out.append(f"{self.name} 0")
        return out


class Histogram:
    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count", "const_labels")

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

    def __init__(self, name: str, help_: str = "", buckets=None, const_labels=()):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        # constant labels stamped on every series (a HistogramFamily
        # child carries e.g. ("step", "propose"))
        self.const_labels = tuple(const_labels)

    def observe(self, value: float) -> None:
        self._sum += value
        self._count += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self._counts[i] += 1
                return
        self._counts[-1] += 1

    def _series(self) -> list[str]:
        base = self.const_labels
        out = []
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            out.append(
                f"{self.name}_bucket{_fmt_labels(base + (('le', _fmt(b)),))} {cum}"
            )
        cum += self._counts[-1]
        out.append(f"{self.name}_bucket{_fmt_labels(base + (('le', '+Inf'),))} {cum}")
        out.append(f"{self.name}_sum{_fmt_labels(base)} {_fmt(self._sum)}")
        out.append(f"{self.name}_count{_fmt_labels(base)} {self._count}")
        return out

    def render(self) -> list[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
            *self._series(),
        ]


class HistogramFamily:
    """One histogram name split by a single label (e.g.
    consensus_step_duration_seconds{step=}): children share buckets and
    render under one HELP/TYPE header."""

    __slots__ = ("name", "help", "label", "buckets", "_hists")

    def __init__(self, name: str, label: str, help_: str = "", buckets=None):
        self.name = name
        self.help = help_
        self.label = label
        self.buckets = tuple(buckets or Histogram.DEFAULT_BUCKETS)
        self._hists: dict[str, Histogram] = {}

    def labeled(self, value: str) -> Histogram:
        h = self._hists.get(value)
        if h is None:
            h = self._hists[value] = Histogram(
                self.name, self.help, self.buckets,
                const_labels=((self.label, value),),
            )
        return h

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for value in sorted(self._hists):
            out.extend(self._hists[value]._series())
        return out


# -- process-wide resilience events -----------------------------------------
#
# Crypto backends (crypto/batch.py) are process-wide singletons, not per-node
# objects, so their degradation events land in this module-level store;
# NodeMetrics folds them into its Prometheus output at render time.

RESILIENCE: dict[str, float] = {
    "tpu_fallback_batches": 0.0,  # batches re-verified on CPU after a TPU error
    "tpu_fallback_sigs": 0.0,  # signatures in those batches
    "tpu_breaker_opens": 0.0,  # TPU circuit-breaker trips
    "tpu_breaker_probes": 0.0,  # half-open probes sent back to the TPU
}


def record_resilience(name: str, value: float = 1.0) -> None:
    RESILIENCE[name] = RESILIENCE.get(name, 0.0) + value


# -- storage-layer events ----------------------------------------------------
#
# WAL objects are created before (and sometimes without) a NodeMetrics, so
# corruption/repair events land here, module-level, exactly like RESILIENCE;
# NodeMetrics folds them in at render time.

STORAGE: dict[str, float] = {
    "wal_corrupt_records": 0.0,  # corrupt/torn records hit during replay
    "wal_repairs": 0.0,  # WAL files truncated to the last whole record
    "wal_truncated_bytes": 0.0,  # damaged bytes rotated aside by repair
}


def record_storage(name: str, value: float = 1.0) -> None:
    STORAGE[name] = STORAGE.get(name, 0.0) + value


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Registry:
    def __init__(self, namespace: str = "tendermint_tpu"):
        self.namespace = namespace
        self._metrics: list = []

    def counter(self, subsystem: str, name: str, help_: str = "") -> Counter:
        m = Counter(f"{self.namespace}_{subsystem}_{name}", help_)
        self._metrics.append(m)
        return m

    def gauge(self, subsystem: str, name: str, help_: str = "") -> Gauge:
        m = Gauge(f"{self.namespace}_{subsystem}_{name}", help_)
        self._metrics.append(m)
        return m

    def histogram(self, subsystem: str, name: str, help_: str = "", buckets=None) -> Histogram:
        m = Histogram(f"{self.namespace}_{subsystem}_{name}", help_, buckets)
        self._metrics.append(m)
        return m

    def histogram_family(
        self, subsystem: str, name: str, label: str, help_: str = "", buckets=None
    ) -> HistogramFamily:
        m = HistogramFamily(
            f"{self.namespace}_{subsystem}_{name}", label, help_, buckets
        )
        self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: list[str] = []
        for m in self._metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


class NodeMetrics:
    """Per-subsystem metric sets (reference */metrics.go)."""

    def __init__(self, registry: Registry | None = None):
        r = self.registry = registry or Registry()
        # consensus (reference internal/consensus/metrics.go:19-60)
        self.consensus_height = r.gauge("consensus", "height", "current height")
        self.consensus_rounds = r.gauge("consensus", "rounds", "round of the current height")
        self.consensus_validators = r.gauge("consensus", "validators", "validator-set size")
        self.consensus_block_interval = r.histogram(
            "consensus", "block_interval_seconds", "time between blocks",
            buckets=(0.1, 0.25, 0.5, 1, 2, 5, 10, 30),
        )
        self.consensus_txs = r.gauge("consensus", "num_txs", "txs in the last block")
        self.consensus_byzantine = r.counter(
            "consensus", "byzantine_validators", "equivocations seen"
        )
        # mempool + tx ingress (mempool/pool.py, mempool/ingress.py —
        # live pools/ingresses registered process-wide, folded in at
        # render time like the verifyhub families: a tx flood is
        # diagnosable from one /metrics scrape alone)
        self.mempool_size = r.gauge("mempool", "size", "resident txs")
        self.mempool_failed = r.counter("mempool", "failed_txs", "rejected txs")
        self.mempool_bytes = r.gauge("mempool", "bytes", "resident tx bytes")
        self.mempool_tx_admitted = r.counter(
            "mempool", "tx_admitted", "txs inserted into the resident set"
        )
        self.mempool_tx_rejected = r.counter(
            "mempool", "tx_rejected",
            "txs rejected (size/malformed/bad-sig/stale-nonce/CheckTx/full)",
        )
        self.mempool_tx_evicted = r.counter(
            "mempool", "tx_evicted", "residents displaced by higher priority"
        )
        self.mempool_tx_shed = r.counter(
            "mempool", "tx_shed",
            "txs rejected-with-busy at the ingress intake (backpressure)",
        )
        self.mempool_tx_recheck_failed = r.counter(
            "mempool", "tx_recheck_failed",
            "residents dropped by the post-commit batched recheck",
        )
        from ..mempool.ingress import ADMIT_BUCKETS

        self.ingress_submitted = r.counter(
            "ingress", "submitted", "txs accepted into the admission pipeline"
        )
        self.ingress_dedup_drops = r.counter(
            "ingress", "dedup_drops",
            "duplicate submissions dropped before any verify/CheckTx work",
        )
        self.ingress_sig_failed = r.counter(
            "ingress", "sig_failed", "envelope signature pre-verify failures"
        )
        self.ingress_parked = r.counter(
            "ingress", "parked", "nonce-gap arrivals parked in a sender lane"
        )
        self.ingress_park_expired = r.counter(
            "ingress", "park_expired", "parked txs evicted on nonce-gap timeout"
        )
        self.ingress_park_adopted = r.counter(
            "ingress", "park_adopted",
            "fresh-lane parked txs adopted as the lane start on timeout",
        )
        self.ingress_stale_nonce = r.counter(
            "ingress", "stale_nonce", "txs below their sender lane watermark"
        )
        self.ingress_lane_full = r.counter(
            "ingress", "lane_full", "txs rejected busy at a full nonce lane"
        )
        self.ingress_depth = r.gauge(
            "ingress", "depth", "txs currently inside the bounded pipeline"
        )
        self.ingress_parked_now = r.gauge(
            "ingress", "parked_now", "txs currently parked across nonce lanes"
        )
        self.ingress_admit_latency = r.histogram(
            "ingress",
            "admit_latency_seconds",
            "submit-to-insert latency per admitted tx",
            buckets=ADMIT_BUCKETS,
        )
        self.ingress_verify_latency = r.histogram(
            "ingress",
            "verify_latency_seconds",
            "stage-A parse + signature pre-verify latency per tx",
            buckets=ADMIT_BUCKETS,
        )
        # LightD — the light-client serving layer (light/fleet.py; live
        # instances registered process-wide, folded at render time like
        # the ingress family)
        from ..light.fleet import SYNC_BUCKETS

        self.lightd_syncs = r.counter(
            "lightd", "syncs", "sync requests received (incl. shed)"
        )
        self.lightd_sheds = r.counter(
            "lightd", "sheds",
            "syncs rejected-with-busy at the session bound (backpressure)",
        )
        self.lightd_coalesced = r.counter(
            "lightd", "coalesced", "syncs joined onto an in-flight session"
        )
        self.lightd_hop_cache_hits = r.counter(
            "lightd", "hop_cache_hits",
            "syncs answered from the verified-hop cache (zero verification)",
        )
        self.lightd_hops_verified = r.counter(
            "lightd", "hops_verified",
            "skipping-verification checkpoints verified once and cached",
        )
        self.lightd_hop_scheme = r.counter(
            "lightd", "hops_by_scheme",
            "hops served per wire scheme (bls-aggregate vs per-sig)",
        )
        self.lightd_proofs_served = r.counter(
            "lightd", "proofs_served", "aggregate hop proofs served"
        )
        self.lightd_divergences = r.counter(
            "lightd", "divergences",
            "witness cross-checks that detected a light-client attack",
        )
        self.lightd_sessions = r.gauge(
            "lightd", "sessions", "verification sessions in flight right now"
        )
        self.lightd_hop_cache_hit_rate = r.gauge(
            "lightd", "hop_cache_hit_rate", "hits / (hits + misses)"
        )
        self.lightd_sync_latency = r.histogram(
            "lightd",
            "sync_latency_seconds",
            "request-to-verified-verdict latency per sync",
            buckets=SYNC_BUCKETS,
        )
        # BootD — the statesync snapshot-serving layer (statesync/
        # fleet.py; live instances registered process-wide, folded at
        # render time like the lightd family)
        from ..statesync.fleet import BOOT_BUCKETS

        self.bootd_chunk_requests = r.counter(
            "bootd", "chunk_requests", "chunk requests received (incl. shed)"
        )
        self.bootd_chunks_served = r.counter(
            "bootd", "chunks_served", "chunk payloads served"
        )
        self.bootd_chunk_bytes = r.counter(
            "bootd", "chunk_bytes", "chunk payload bytes served"
        )
        self.bootd_sheds = r.counter(
            "bootd", "sheds",
            "chunk requests shed-with-busy at the session bound (backpressure)",
        )
        self.bootd_coalesced = r.counter(
            "bootd", "coalesced", "chunk requests joined onto an in-flight load"
        )
        self.bootd_cache_hits = r.counter(
            "bootd", "cache_hits", "chunks served from the shared snapshot cache"
        )
        self.bootd_store_reads = r.counter(
            "bootd", "store_reads",
            "app store reads (cache misses that actually hit the app)",
        )
        self.bootd_snapshots_served = r.counter(
            "bootd", "snapshots_served", "snapshot manifests served to joiners"
        )
        self.bootd_backfill_heights = r.counter(
            "bootd", "backfill_heights",
            "backfilled heights whose commits passed hub verification",
        )
        self.bootd_backfill_sigs = r.counter(
            "bootd", "backfill_sigs",
            "per-signature commit verifications batched onto the backfill lane",
        )
        self.bootd_backfill_scheme = r.counter(
            "bootd", "backfill_by_scheme",
            "backfilled heights per commit scheme (bls-aggregate vs per-sig)",
        )
        self.bootd_poisoned_rejects = r.counter(
            "bootd", "poisoned_rejects",
            "snapshot restores rejected for poisoned bytes (peer punished)",
        )
        self.bootd_synced = r.counter(
            "bootd", "synced", "state syncs completed by this node"
        )
        self.bootd_sessions = r.gauge(
            "bootd", "sessions", "chunk-serving sessions in flight right now"
        )
        self.bootd_cache_hit_rate = r.gauge(
            "bootd", "cache_hit_rate", "hits / (hits + misses)"
        )
        self.bootd_time_to_synced = r.histogram(
            "bootd",
            "time_to_synced_seconds",
            "discovery-to-restored-state latency per completed sync",
            buckets=BOOT_BUCKETS,
        )
        # event fan-out (libs/pubsub.py drop_on_full subscriptions —
        # the websocket path; folded from pubsub.DROPPED at render)
        self.pubsub_dropped_events = r.counter(
            "pubsub", "dropped_events",
            "events dropped for slow drop-on-full subscribers (websocket fan-out)",
        )
        # p2p
        self.p2p_peers = r.gauge("p2p", "peers", "connected peers")
        self.p2p_msg_recv = r.counter("p2p", "message_receive_bytes_total", "inbound bytes")
        self.p2p_msg_send = r.counter("p2p", "message_send_bytes_total", "outbound bytes")
        # blocksync
        self.blocksync_applied = r.counter("blocksync", "blocks_applied", "blocks applied")
        self.blocksync_sigs = r.counter(
            "blocksync", "sigs_verified", "signatures batch-verified"
        )
        self.blocksync_bans = r.counter(
            "blocksync", "peer_bans", "peers banned for repeated request timeouts"
        )
        # resilience (crypto backend degradation, process-wide)
        self.crypto_tpu_fallbacks = r.counter(
            "crypto", "tpu_fallback_batches",
            "batches transparently re-verified on CPU after a TPU failure",
        )
        self.crypto_tpu_fallback_sigs = r.counter(
            "crypto", "tpu_fallback_sigs", "signatures CPU-re-verified on fallback"
        )
        self.crypto_breaker_opens = r.counter(
            "crypto", "tpu_breaker_opens", "TPU circuit-breaker trips"
        )
        self.crypto_breaker_probes = r.counter(
            "crypto", "tpu_breaker_probes", "half-open probes routed back to TPU"
        )
        # storage / WAL crash-consistency (consensus/wal.py, folded from
        # the module-level STORAGE events at render time)
        self.wal_corrupt_records = r.counter(
            "wal", "corrupt_records",
            "corrupt or torn WAL records hit during replay (truncation point logged)",
        )
        self.wal_repairs = r.counter(
            "wal", "repairs", "WAL files truncated to the last whole record on open"
        )
        self.wal_truncated_bytes = r.counter(
            "wal", "truncated_bytes", "damaged WAL bytes rotated aside by repair"
        )
        # verify hub (crypto/verify_hub.py — process-wide scheduler,
        # folded in at render time like the resilience events)
        self.verifyhub_dispatches = r.counter(
            "verifyhub", "dispatches", "micro-batches sent to a verifier"
        )
        self.verifyhub_sigs = r.counter(
            "verifyhub", "sigs_dispatched", "signatures verified via the hub"
        )
        self.verifyhub_cache_hits = r.counter(
            "verifyhub", "cache_hits", "verdicts served from the dedup LRU"
        )
        self.verifyhub_coalesced = r.counter(
            "verifyhub", "coalesced", "requests joined onto an in-flight verify"
        )
        self.verifyhub_occupancy = r.gauge(
            "verifyhub", "batch_occupancy", "mean signatures per dispatch"
        )
        self.verifyhub_dispatch_rate = r.gauge(
            "verifyhub", "dispatch_rate", "dispatches per second since hub start"
        )
        self.verifyhub_cache_hit_rate = r.gauge(
            "verifyhub", "cache_hit_rate", "fraction of requests served from cache"
        )
        # two-lane scheduler (live consensus packed ahead of catch-up
        # backfill in every micro-batch); series carry a lane label
        self.verifyhub_lane_submitted = r.counter(
            "verifyhub", "lane_submitted", "unique triples enqueued per lane"
        )
        self.verifyhub_lane_sigs = r.counter(
            "verifyhub", "lane_sigs_dispatched", "signatures dispatched per lane"
        )
        self.verifyhub_lane_queued = r.gauge(
            "verifyhub", "lane_queued", "triples currently queued per lane"
        )
        self.verifyhub_lane_promotions = r.counter(
            "verifyhub",
            "lane_promotions",
            "queued backfill entries pulled into the live lane by a live coalesce",
        )
        # scheme-partitioned dispatch (ed25519/sr25519 share the Edwards
        # kernel; bls12381 runs the pairing path — never one dispatch)
        self.verifyhub_scheme_sigs = r.counter(
            "verifyhub",
            "scheme_sigs",
            "signatures dispatched per signature scheme partition",
        )
        # hash hub (crypto/hash_hub.py — the SHA-256 chokepoint; folded
        # from the module STATS at render time like bls/resilience)
        self.hashhub_batches = r.counter(
            "hashhub", "batches", "sha256_many calls (one per merkle tree level)"
        )
        self.hashhub_messages = r.counter(
            "hashhub", "messages", "messages hashed through batch calls"
        )
        self.hashhub_singles = r.counter(
            "hashhub", "singles", "sha256_one calls (tx keys, leaf-hash cache fills)"
        )
        self.hashhub_occupancy = r.gauge(
            "hashhub", "batch_occupancy", "mean messages per sha256_many call"
        )
        self.hashhub_max_batch = r.gauge(
            "hashhub", "max_batch", "widest batch seen (bucket-ladder headroom)"
        )
        self.hashhub_device_batches = r.counter(
            "hashhub", "device_batches", "batches served by the JAX kernel"
        )
        self.hashhub_device_messages = r.counter(
            "hashhub", "device_messages", "messages hashed on the device route"
        )
        self.hashhub_fallbacks = r.counter(
            "hashhub", "fallbacks",
            "device batches re-hashed inline with hashlib after a backend error",
        )
        self.hashhub_breaker_skips = r.counter(
            "hashhub", "breaker_skips",
            "device-eligible batches kept on the host by the open TPU breaker",
        )
        self.hashhub_lane_batches = r.counter(
            "hashhub", "lane_batches", "sha256_many calls per lane"
        )
        self.hashhub_lane_messages = r.counter(
            "hashhub", "lane_messages", "messages hashed per lane (singles included)"
        )
        # remote verification sidecar, client side (crypto/verifyd.py —
        # module-level stores like RESILIENCE: the remote route is
        # process-wide, shared by every in-process hub)
        self.verifyhub_remote_dispatches = r.counter(
            "verifyhub", "remote_dispatches",
            "micro-batches answered by the verifyd sidecar over the socket",
        )
        self.verifyhub_remote_fallbacks = r.counter(
            "verifyhub", "remote_fallbacks",
            "micro-batches verified inline-local because the sidecar was "
            "unreachable, busy, or scheme-incompatible",
        )
        from ..crypto.verifyd import REMOTE_RTT

        self.verifyhub_remote_rtt = r.histogram(
            "verifyhub",
            "remote_rtt_seconds",
            "verifyd socket round-trip per remote batch",
            buckets=REMOTE_RTT.buckets,
        )
        # verifyd daemon side (folded from in-process daemons; a
        # standalone daemon serves the same numbers over its protocol
        # `stats` request / `cli verifyd --stats`)
        self.verifyd_clients = r.gauge(
            "verifyd", "clients", "client connections currently open"
        )
        self.verifyd_requests = r.counter(
            "verifyd", "requests", "verify_batch requests served"
        )
        self.verifyd_occupancy = r.gauge(
            "verifyd", "batch_occupancy",
            "mean signatures per daemon-hub dispatch (cross-client packed)",
        )
        self.verifyd_cross_client_packs = r.counter(
            "verifyd", "cross_client_packs",
            "device dispatches that mixed signatures from >1 client process",
        )
        self.verifyd_shed = r.counter(
            "verifyd", "shed",
            "requests answered busy at the bounded in-flight cap",
        )
        # BLS aggregate-commit path (crypto/bls.STATS, folded at render)
        self.bls_verifies = r.counter(
            "bls", "verifies", "single BLS signature verifications (memo misses)"
        )
        self.bls_verify_failures = r.counter(
            "bls", "verify_failures", "failed single BLS verifications"
        )
        self.bls_aggregate_verifies = r.counter(
            "bls", "aggregate_verifies", "aggregate-commit pairing-product checks"
        )
        self.bls_aggregate_failures = r.counter(
            "bls", "aggregate_failures", "rejected aggregate-commit checks"
        )
        self.bls_aggregate_signers = r.counter(
            "bls", "aggregate_signers", "signers covered by aggregate checks"
        )
        self.bls_pop_checks = r.counter(
            "bls", "pop_checks", "proof-of-possession verifications (genesis)"
        )
        # bucket layout shared with the hub's live histogram (one source
        # of truth — _fold_verify_hub copies counts index-for-index)
        from ..crypto.verify_hub import LATENCY_BUCKETS

        self.verifyhub_queue_latency = r.histogram(
            "verifyhub",
            "queue_latency_seconds",
            "submit-to-dispatch wait per request",
            buckets=LATENCY_BUCKETS,
        )
        # pipelined consensus ingest (consensus/ingest.py — per-CS
        # pipelines registered process-wide, folded in at render time)
        self.consensus_ingest_inflight = r.gauge(
            "consensus_ingest",
            "inflight",
            "messages submitted to the ingest pipeline and not yet applied",
        )
        self.consensus_ingest_submitted = r.counter(
            "consensus_ingest", "submitted", "messages entering stage-1 verify"
        )
        self.consensus_ingest_released = r.counter(
            "consensus_ingest",
            "released",
            "messages released in arrival order to the state machine",
        )
        self.consensus_ingest_dedup_drops = r.counter(
            "consensus_ingest",
            "dedup_drops",
            "gossip duplicates dropped against the vote-set before verification",
        )
        self.consensus_ingest_pre_verified = r.counter(
            "consensus_ingest",
            "pre_verified",
            "messages whose signature was proven in stage 1 (not re-checked at apply)",
        )
        self.consensus_ingest_verify_latency = r.histogram(
            "consensus_ingest",
            "verify_latency_seconds",
            "stage-1 intake-to-verdict wait per message",
            buckets=LATENCY_BUCKETS,
        )
        self.consensus_ingest_reorder_wait = r.histogram(
            "consensus_ingest",
            "reorder_wait_seconds",
            "verdict-to-in-order-release wait per message",
            buckets=LATENCY_BUCKETS,
        )
        # consensus step latency (consensus/state.py per-CS histograms
        # registered process-wide, folded in at render time)
        from ..consensus.state import STEP_BUCKETS, STEP_LABELS

        self.consensus_step_duration = r.histogram_family(
            "consensus",
            "step_duration_seconds",
            "step",
            "time spent per consensus step (propose/prevote/precommit/commit)",
            buckets=STEP_BUCKETS,
        )
        for label in STEP_LABELS:  # every step series present from scrape 1
            self.consensus_step_duration.labeled(label)
        self.consensus_time_to_commit = r.histogram(
            "consensus",
            "time_to_commit_seconds",
            "height start to committed block",
            buckets=STEP_BUCKETS,
        )
        # backend attach telemetry (crypto/backend_telemetry.py —
        # process-wide like the crypto backends themselves)
        from ..crypto.backend_telemetry import ATTACH_BUCKETS

        self.backend_attach_attempts = r.counter(
            "backend", "attach_attempts", "accelerator backend init attempts"
        )
        self.backend_attach_failures = r.counter(
            "backend", "attach_failures", "init attempts that raised or hung"
        )
        self.backend_fallbacks = r.counter(
            "backend", "fallbacks",
            "TPU->CPU fallback events (every failed device batch; "
            "active-kind transitions gate the flight dump, not this count)"
        )
        self.backend_breaker_transitions = r.counter(
            "backend", "breaker_transitions", "TPU breaker state changes"
        )
        self.backend_attach_latency = r.histogram(
            "backend",
            "attach_latency_seconds",
            "per-attempt backend init latency",
            buckets=ATTACH_BUCKETS,
        )
        self.backend_compile = r.gauge(
            "backend", "compile_seconds", "last XLA compile/warmup time per shape"
        )
        self.backend_active = r.gauge(
            "backend", "active", "1 for the verifier kind currently routing batches"
        )
        self.backend_compile_cache_hits = r.counter(
            "backend", "compile_cache_hits",
            "compiles answered by the persistent XLA cache (~0 ms deserialize)",
        )
        self.backend_compile_cache_misses = r.counter(
            "backend", "compile_cache_misses", "cold XLA compiles"
        )
        self.backend_mesh_devices = r.gauge(
            "backend", "mesh_devices",
            "device mesh size (state=total at attach, state=active now)",
        )
        self.backend_mesh_degrades = r.counter(
            "backend", "mesh_degrades",
            "mesh membership transitions (per-device breaker trips + recoveries)",
        )
        self.backend_shard_sigs = r.counter(
            "backend", "shard_sigs",
            "signatures dispatched per device shard (padding excluded)",
        )
        # abci
        self.abci_latency = r.histogram(
            "abci", "connection_latency_seconds", "app call latency"
        )

    def _fold_verify_hub(self) -> None:
        from ..crypto.verify_hub import running_hub

        hub = running_hub()
        if hub is None:
            return
        s = hub.stats()
        self.verifyhub_dispatches._values[()] = s["dispatches"]
        self.verifyhub_sigs._values[()] = s["dispatched_sigs"]
        self.verifyhub_cache_hits._values[()] = s["cache_hits"]
        self.verifyhub_coalesced._values[()] = s["coalesced"]
        self.verifyhub_occupancy.set(round(s["mean_occupancy"], 3))
        self.verifyhub_dispatch_rate.set(round(s["dispatch_rate"], 3))
        self.verifyhub_cache_hit_rate.set(round(s["cache_hit_rate"], 4))
        for lane in ("live", "backfill"):
            self.verifyhub_lane_submitted._values[(("lane", lane),)] = s[
                f"lane_{lane}_submitted"
            ]
            self.verifyhub_lane_sigs._values[(("lane", lane),)] = s[
                f"lane_{lane}_dispatched"
            ]
            self.verifyhub_lane_queued.set(s[f"lane_{lane}_queued"], lane=lane)
        self.verifyhub_lane_promotions._values[()] = s["lane_promotions"]
        for scheme in ("edwards", "bls"):
            self.verifyhub_scheme_sigs._values[(("scheme", scheme),)] = s[
                f"scheme_{scheme}_sigs"
            ]
        # consistent snapshot taken under the hub lock (a mid-copy
        # dispatch would otherwise skew _count against the bucket sums)
        counts, sum_, count = hub.latency_snapshot()
        dst = self.verifyhub_queue_latency
        if len(counts) == len(dst._counts):  # same LATENCY_BUCKETS layout
            dst._counts = counts
            dst._sum = sum_
            dst._count = count

    def _fold_verifyd(self) -> None:
        from ..crypto import verifyd

        # client side: process-wide module stores (always present)
        cs = verifyd.CLIENT_STATS
        self.verifyhub_remote_dispatches._values[()] = cs["remote_dispatches"]
        self.verifyhub_remote_fallbacks._values[()] = cs["remote_fallbacks"]
        counts, sum_, count = verifyd.remote_rtt_snapshot()
        dst = self.verifyhub_remote_rtt
        if len(counts) == len(dst._counts):
            dst._counts = counts
            dst._sum = sum_
            dst._count = count
        # daemon side: only when a daemon runs in THIS process
        agg = verifyd.aggregate_daemons()
        if agg is None:
            return
        self.verifyd_clients.set(agg["clients"])
        self.verifyd_requests._values[()] = agg["requests"]
        self.verifyd_occupancy.set(round(agg["batch_occupancy"], 3))
        self.verifyd_cross_client_packs._values[()] = agg["cross_client_packs"]
        self.verifyd_shed._values[()] = agg["shed"]

    def _fold_ingest(self) -> None:
        from ..consensus import ingest

        s, verify_hist, reorder_hist = ingest.aggregate()
        if s is None:
            return
        self.consensus_ingest_inflight.set(s["inflight"])
        self.consensus_ingest_submitted._values[()] = s["submitted"]
        self.consensus_ingest_released._values[()] = s["released"]
        self.consensus_ingest_dedup_drops._values[()] = s["dedup_drops"]
        self.consensus_ingest_pre_verified._values[()] = s["pre_verified"]
        for src, dst in (
            (verify_hist, self.consensus_ingest_verify_latency),
            (reorder_hist, self.consensus_ingest_reorder_wait),
        ):
            counts, sum_, count = src
            if len(counts) == len(dst._counts):  # same LATENCY_BUCKETS layout
                dst._counts = counts
                dst._sum = sum_
                dst._count = count

    def _fold_mempool(self) -> None:
        from ..libs import pubsub
        from ..mempool import ingress as mp_ingress
        from ..mempool import pool as mp_pool

        self.pubsub_dropped_events._values[()] = pubsub.DROPPED["events"]
        agg = mp_pool.aggregate_pools()
        ing, admit_hist, verify_hist = mp_ingress.aggregate()
        if agg is not None:
            stats, size, size_bytes = agg
            self.mempool_size.set(size)
            self.mempool_bytes.set(size_bytes)
            self.mempool_tx_admitted._values[()] = stats["admitted"]
            self.mempool_tx_evicted._values[()] = stats["evicted"]
            self.mempool_tx_recheck_failed._values[()] = stats["recheck_failed"]
            # rejections: pool-level (size/CheckTx/full) + ingress-level
            # (malformed/bad-sig/stale-nonce/park-expired) are disjoint —
            # an ingress rejection never reaches the pool
            self.mempool_tx_rejected._values[()] = stats["rejected"] + (
                ing["rejected"] if ing is not None else 0.0
            )
        if ing is None:
            return
        self.mempool_tx_shed._values[()] = ing["shed"]
        self.ingress_submitted._values[()] = ing["submitted"]
        self.ingress_dedup_drops._values[()] = ing["dedup_drops"]
        self.ingress_sig_failed._values[()] = ing["sig_failed"]
        self.ingress_parked._values[()] = ing["parked"]
        self.ingress_park_expired._values[()] = ing["park_expired"]
        self.ingress_park_adopted._values[()] = ing["park_adopted"]
        self.ingress_stale_nonce._values[()] = ing["stale_nonce"]
        self.ingress_lane_full._values[()] = ing["lane_full"]
        self.ingress_depth.set(ing["depth"])
        self.ingress_parked_now.set(ing["parked_now"])
        for src, dst in (
            (admit_hist, self.ingress_admit_latency),
            (verify_hist, self.ingress_verify_latency),
        ):
            counts, sum_, count = src
            if len(counts) == len(dst._counts):  # same ADMIT_BUCKETS layout
                dst._counts = counts
                dst._sum = sum_
                dst._count = count

    def _fold_lightd(self) -> None:
        from ..light import fleet

        s, hist = fleet.aggregate()
        if s is None:
            return
        self.lightd_syncs._values[()] = s["syncs"]
        self.lightd_sheds._values[()] = s["sheds"]
        self.lightd_coalesced._values[()] = s["coalesced"]
        self.lightd_hop_cache_hits._values[()] = s["hop_cache_hits"]
        self.lightd_hops_verified._values[()] = s["hops_verified"]
        self.lightd_hop_scheme._values[(("scheme", "bls-aggregate"),)] = s[
            "agg_hops"
        ]
        self.lightd_hop_scheme._values[(("scheme", "per-sig"),)] = s[
            "per_sig_hops"
        ]
        self.lightd_proofs_served._values[()] = s["proofs_served"]
        self.lightd_divergences._values[()] = s["divergences"]
        self.lightd_sessions.set(s["sessions_now"])
        lookups = s["hop_cache_hits"] + s["hop_cache_misses"]
        self.lightd_hop_cache_hit_rate.set(
            round(s["hop_cache_hits"] / lookups, 4) if lookups else 0.0
        )
        counts, sum_, count = hist
        dst = self.lightd_sync_latency
        if len(counts) == len(dst._counts):  # same SYNC_BUCKETS layout
            dst._counts = counts
            dst._sum = sum_
            dst._count = count

    def _fold_bootd(self) -> None:
        from ..statesync import fleet

        s, hist = fleet.aggregate()
        if s is None:
            return
        self.bootd_chunk_requests._values[()] = s["chunk_requests"]
        self.bootd_chunks_served._values[()] = s["chunks_served"]
        self.bootd_chunk_bytes._values[()] = s["chunk_bytes"]
        self.bootd_sheds._values[()] = s["sheds"]
        self.bootd_coalesced._values[()] = s["coalesced"]
        self.bootd_cache_hits._values[()] = s["cache_hits"]
        self.bootd_store_reads._values[()] = s["store_reads"]
        self.bootd_snapshots_served._values[()] = s["snapshots_served"]
        self.bootd_backfill_heights._values[()] = s["backfill_heights"]
        self.bootd_backfill_sigs._values[()] = s["backfill_sigs"]
        self.bootd_backfill_scheme._values[(("scheme", "bls-aggregate"),)] = s[
            "backfill_agg_heights"
        ]
        self.bootd_backfill_scheme._values[(("scheme", "per-sig"),)] = (
            s["backfill_heights"] - s["backfill_agg_heights"]
        )
        self.bootd_poisoned_rejects._values[()] = s["poisoned_rejects"]
        self.bootd_synced._values[()] = s["synced"]
        self.bootd_sessions.set(s["sessions_now"])
        lookups = s["cache_hits"] + s["cache_misses"]
        self.bootd_cache_hit_rate.set(
            round(s["cache_hits"] / lookups, 4) if lookups else 0.0
        )
        counts, sum_, count = hist
        dst = self.bootd_time_to_synced
        if len(counts) == len(dst._counts):  # same BOOT_BUCKETS layout
            dst._counts = counts
            dst._sum = sum_
            dst._count = count

    def _fold_steps(self) -> None:
        from ..consensus.state import aggregate_step_metrics

        per_step, ttc = aggregate_step_metrics()
        if per_step is None:
            return
        for label, (counts, sum_, count) in per_step.items():
            dst = self.consensus_step_duration.labeled(label)
            if len(counts) == len(dst._counts):
                dst._counts = counts
                dst._sum = sum_
                dst._count = count
        counts, sum_, count = ttc
        dst = self.consensus_time_to_commit
        if len(counts) == len(dst._counts):
            dst._counts = counts
            dst._sum = sum_
            dst._count = count

    def _fold_backend(self) -> None:
        from ..crypto import backend_telemetry as bt

        self.backend_attach_attempts._values[()] = bt.BACKEND["attach_attempts"]
        self.backend_attach_failures._values[()] = bt.BACKEND["attach_failures"]
        self.backend_fallbacks._values[()] = bt.BACKEND["fallbacks"]
        self.backend_breaker_transitions._values[()] = bt.BACKEND[
            "breaker_transitions"
        ]
        # rebuild the attach-latency histogram from the bounded
        # observation list (attach events are rare; ≤512 entries)
        dst = self.backend_attach_latency
        dst._counts = [0] * (len(dst.buckets) + 1)
        dst._sum = 0.0
        dst._count = 0
        for v in bt.ATTACH_LATENCIES:
            dst.observe(v)
        self.backend_compile_cache_hits._values[()] = bt.BACKEND[
            "compile_cache_hits"
        ]
        self.backend_compile_cache_misses._values[()] = bt.BACKEND[
            "compile_cache_misses"
        ]
        for shape, seconds in bt.COMPILE_SECONDS.items():
            self.backend_compile.set(round(seconds, 4), shape=shape)
        active = bt.ACTIVE["kind"]
        for kind in ("tpu", "cpu", "none"):
            self.backend_active.set(1.0 if kind == active else 0.0, kind=kind)
        self.backend_mesh_devices.set(bt.MESH["devices_total"], state="total")
        self.backend_mesh_devices.set(bt.MESH["devices_active"], state="active")
        self.backend_mesh_degrades._values[()] = bt.MESH["degrade_transitions"]
        for dev, sigs in bt.SHARD_SIGS.items():
            self.backend_shard_sigs._values[(("device", dev),)] = sigs

    def render(self) -> str:
        # fold the process-wide resilience events in at scrape time
        self.crypto_tpu_fallbacks._values[()] = RESILIENCE["tpu_fallback_batches"]
        self.crypto_tpu_fallback_sigs._values[()] = RESILIENCE["tpu_fallback_sigs"]
        self.crypto_breaker_opens._values[()] = RESILIENCE["tpu_breaker_opens"]
        self.crypto_breaker_probes._values[()] = RESILIENCE["tpu_breaker_probes"]
        self.wal_corrupt_records._values[()] = STORAGE["wal_corrupt_records"]
        self.wal_repairs._values[()] = STORAGE["wal_repairs"]
        self.wal_truncated_bytes._values[()] = STORAGE["wal_truncated_bytes"]
        self._fold_verify_hub()
        self._fold_verifyd()
        self._fold_ingest()
        self._fold_mempool()
        self._fold_lightd()
        self._fold_bootd()
        self._fold_steps()
        self._fold_backend()
        self._fold_bls()
        self._fold_hashhub()
        return self.registry.render()

    def _fold_hashhub(self) -> None:
        # same lazy-import contract as _fold_bls: the hub module loads
        # with crypto anyway, but a scrape must never be the importer
        import sys

        hh = sys.modules.get("tendermint_tpu.crypto.hash_hub")
        if hh is None:
            return
        s = hh.STATS
        self.hashhub_batches._values[()] = s["batches"]
        self.hashhub_messages._values[()] = s["messages"]
        self.hashhub_singles._values[()] = s["singles"]
        self.hashhub_occupancy.set(
            round(s["messages"] / s["batches"], 3) if s["batches"] else 0.0
        )
        self.hashhub_max_batch.set(s["max_batch"])
        self.hashhub_device_batches._values[()] = s["device_batches"]
        self.hashhub_device_messages._values[()] = s["device_messages"]
        self.hashhub_fallbacks._values[()] = s["fallback_batches"]
        self.hashhub_breaker_skips._values[()] = s["breaker_skips"]
        for lane, n in s["lane_batches"].items():
            self.hashhub_lane_batches._values[(("lane", lane),)] = n
        for lane, n in s["lane_messages"].items():
            self.hashhub_lane_messages._values[(("lane", lane),)] = n

    def _fold_bls(self) -> None:
        # only fold when the BLS module is already loaded: importing it
        # at scrape time would pay the bls_math derivations on nodes
        # that never touch a BLS key
        import sys

        bls = sys.modules.get("tendermint_tpu.crypto.bls")
        if bls is None:
            return
        s = bls.STATS
        self.bls_verifies._values[()] = s["verifies"]
        self.bls_verify_failures._values[()] = s["verify_failures"]
        self.bls_aggregate_verifies._values[()] = s["aggregate_verifies"]
        self.bls_aggregate_failures._values[()] = s["aggregate_failures"]
        self.bls_aggregate_signers._values[()] = s["aggregate_signers"]
        self.bls_pop_checks._values[()] = s["pop_checks"]


class _LastBlock:
    time: float | None = None


def observe_block(metrics: NodeMetrics, block, rs=None) -> None:
    """Update consensus metrics on a committed block."""
    metrics.consensus_height.set(block.header.height)
    metrics.consensus_txs.set(len(block.txs))
    now = time.monotonic()
    if _LastBlock.time is not None:
        metrics.consensus_block_interval.observe(now - _LastBlock.time)
    _LastBlock.time = now
    if rs is not None:
        metrics.consensus_rounds.set(rs.round)
        if rs.validators is not None:
            metrics.consensus_validators.set(len(rs.validators))
