"""Unified retry/backoff/circuit-breaker primitives.

Every subsystem that talks to an unreliable dependency — a peer over the
network (blocksync, statesync, light client) or an accelerator backend
(crypto/batch) — shares these three pieces instead of growing its own
fixed-timeout loop:

  * `BackoffPolicy` — exponential backoff with FULL jitter (AWS
    architecture-blog formulation: sleep = uniform(0, min(cap, base·2^n));
    full jitter decorrelates retry storms after a common-cause failure,
    which truncated jitter does not).
  * `retry()` — drives an async callable under a policy + deadline.
  * `CircuitBreaker` — classic closed → open → half-open machine: after
    `failure_threshold` consecutive failures the circuit opens and calls
    fail fast; after `reset_timeout` one probe is admitted (half-open);
    its success closes the circuit, its failure re-opens with the timeout
    doubled up to `max_reset_timeout`.

The RNG is injectable so tests pin jitter; time is injectable so breaker
tests don't sleep."""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Iterator


@dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff, full jitter, optional attempt/elapsed limits."""

    base: float = 0.1  # first-retry ceiling, seconds
    cap: float = 10.0  # per-sleep ceiling
    multiplier: float = 2.0
    max_attempts: int = 0  # 0 = unbounded (deadline still applies)
    deadline: float = 0.0  # total elapsed budget, seconds; 0 = none

    def sleep_for(self, attempt: int, rng: random.Random | None = None) -> float:
        """Jittered sleep before retry number `attempt` (0-based)."""
        ceiling = min(self.cap, self.base * self.multiplier**attempt)
        return (rng or random).uniform(0.0, ceiling)

    def sleeps(self, rng: random.Random | None = None) -> Iterator[float]:
        """The (possibly unbounded) sleep sequence, for callers that drive
        their own loop."""
        attempt = 0
        while self.max_attempts <= 0 or attempt < self.max_attempts:
            yield self.sleep_for(attempt, rng)
            attempt += 1


class RetriesExhaustedError(Exception):
    """All attempts failed; `last` carries the final underlying error."""

    def __init__(self, attempts: int, last: BaseException | None):
        super().__init__(f"retries exhausted after {attempts} attempts: {last!r}")
        self.attempts = attempts
        self.last = last


async def retry(
    fn: Callable[[], Awaitable],
    policy: BackoffPolicy,
    *,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    give_up_on: tuple[type[BaseException], ...] = (),
    rng: random.Random | None = None,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Callable[[int, BaseException], None] | None = None,
):
    """Run `fn` until it returns, raising RetriesExhaustedError when the
    policy's attempt budget or deadline runs out. Exceptions outside
    `retry_on` — or inside `give_up_on`, which wins even over a matching
    retry_on base class (e.g. a definitive not-found subclassing a
    transient error type) — propagate immediately."""
    start = clock()
    attempt = 0
    last: BaseException | None = None
    while True:
        try:
            return await fn()
        except give_up_on:
            raise
        except retry_on as e:
            last = e
        attempt += 1
        if policy.max_attempts > 0 and attempt >= policy.max_attempts:
            raise RetriesExhaustedError(attempt, last)
        delay = policy.sleep_for(attempt - 1, rng)
        if policy.deadline > 0 and clock() - start + delay > policy.deadline:
            raise RetriesExhaustedError(attempt, last)
        if on_retry is not None:
            on_retry(attempt, last)
        await asyncio.sleep(delay)


class CircuitOpenError(Exception):
    """Call refused: the circuit is open (failing fast)."""


@dataclass
class CircuitBreaker:
    """Consecutive-failure circuit breaker with exponential re-open
    timeout. Synchronous and event-loop-free: callers guard work with
    `allow()` and report outcomes via `record_success()/record_failure()`,
    or wrap both in the `guard()` context manager."""

    failure_threshold: int = 3
    reset_timeout: float = 5.0
    max_reset_timeout: float = 300.0
    clock: Callable[[], float] = time.monotonic
    name: str = ""

    _failures: int = field(default=0, init=False)
    _state: str = field(default="closed", init=False)  # closed|open|half-open
    _opened_at: float = field(default=0.0, init=False)
    _current_timeout: float = field(default=0.0, init=False)
    #: lifetime counters for metrics/introspection
    opens: int = field(default=0, init=False)
    half_opens: int = field(default=0, init=False)

    @property
    def state(self) -> str:
        # surface the half-open transition lazily: "open" becomes
        # "half-open" the moment the reset timeout elapses
        if self._state == "open" and (
            self.clock() - self._opened_at >= self._current_timeout
        ):
            return "half-open"
        return self._state

    def allow(self) -> bool:
        """May the caller attempt the protected operation right now?
        In half-open, exactly one probe is admitted per reset window."""
        st = self.state
        if st == "closed":
            return True
        if st == "half-open" and self._state == "open":
            # claim the single probe slot
            self._state = "half-open"
            self.half_opens += 1
            return True
        return False

    def record_success(self) -> None:
        self._failures = 0
        self._state = "closed"
        self._current_timeout = 0.0

    def record_failure(self) -> None:
        if self._state == "half-open":
            # probe failed: re-open with a doubled timeout
            self._trip(self._current_timeout * 2)
            return
        if self._state == "open":
            # a straggler call that started before the trip; the clock
            # is already running, don't extend it
            return
        self._failures += 1
        if self._failures >= self.failure_threshold:
            self._trip(self.reset_timeout)

    def _trip(self, timeout: float) -> None:
        self._state = "open"
        self._opened_at = self.clock()
        self._current_timeout = min(
            max(timeout, self.reset_timeout), self.max_reset_timeout
        )
        self.opens += 1

    def guard(self) -> "_BreakerGuard":
        """`with breaker.guard(): ...` — raises CircuitOpenError when the
        circuit refuses the call, records success/failure from whether the
        body raised."""
        return _BreakerGuard(self)


class _BreakerGuard:
    def __init__(self, breaker: CircuitBreaker):
        self.breaker = breaker

    def __enter__(self) -> CircuitBreaker:
        if not self.breaker.allow():
            raise CircuitOpenError(
                f"circuit {self.breaker.name or 'breaker'} is open"
            )
        return self.breaker

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()
        return False
