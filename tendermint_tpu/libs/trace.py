"""Flight-recorder tracing — bounded structured spans over the verify
funnel (the instrument panel ROADMAP's perf items keep needing: BENCH
rounds lost the TPU four times out of five and the only artifact was a
stderr tail).

A *span* is one named interval inside one *trace*: ``(trace_id, parent,
subsystem, name, start, duration, attrs)``. A trace follows one message
end-to-end — gossip receive → consensus ingest stage 1 → VerifyHub
queue/pack/dispatch → device (or CPU-fallback) execution → reorder
release → state-machine apply — so "where did this vote spend its
time?" is answerable from data instead of log archaeology.

Design constraints (all load-bearing):

  * **Clock discipline.** Spans live in the injectable Clock's
    *monotonic duration domain* (`libs/clock.Clock.monotonic`) and
    never read the wall clock: tracing must not perturb the same-seed
    bit-reproducibility the chaos matrices assert, and a span duration
    must mean the same thing under a frozen `ManualClock` (whose
    monotonic domain still advances).
  * **Allocation-light, drop-on-full.** Recording appends one small
    tuple to a bounded ring (`collections.deque(maxlen=N)`); the oldest
    span falls out when the ring is full. Nothing in here awaits,
    locks, or backpressures the hot path.
  * **Off-switchable.** ``TMTPU_TRACE=0`` (or ``[trace] enabled=false``
    via `configure`) turns the layer off: `start()` returns None,
    `span()` returns one shared no-op singleton, `record()`/`emit()`
    return before touching the ring — near-zero overhead.

Two recording APIs:

  * ``with span("hub", "dispatch", attrs...) as sp:`` — context-manager
    style for code blocks. The tmtlint `span-discipline` rule enforces
    that `span()` results are ALWAYS entered via `with` (a span held in
    a variable and never closed is a leak that silently under-reports).
  * ``record(ctx, "ingest", "verify", t0, t1, attrs...)`` — explicit
    boundary timestamps for contiguous pipeline stages, so per-stage
    durations share boundaries and sum EXACTLY to the end-to-end time.

The ring dumps on demand (`/debug/traces`, `scripts/tracectl.py`) and
automatically on wedge/breaker-trip via `auto_dump(reason)` (wired from
`libs/watchdog.LoopWatchdog` and the TPU breaker in `crypto/batch.py`).

Env knobs: TMTPU_TRACE=0 disables, TMTPU_TRACE_RING sizes the ring,
TMTPU_TRACE_DIR points auto-dumps at a directory.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import re
from collections import deque

from .clock import SYSTEM, Clock

logger = logging.getLogger("libs.trace")

DEFAULT_RING = 4096

#: process-wide id source — a counter, not uuid/random/time: trace ids
#: never enter protocol output, and a counter keeps seeded paths clean
#: for the nondeterminism analyzer
_ids = itertools.count(1)


class TraceCtx:
    """Propagated handle for one end-to-end trace: the id, the clock the
    trace is timed on, the trace's own t0 (the root span's start), and a
    small `marks` dict for boundary timestamps shared across pipeline
    stages (so stage durations sum EXACTLY to the end-to-end span)."""

    __slots__ = ("trace_id", "t0", "clock", "marks")

    def __init__(self, trace_id: int, t0: float, clock: Clock):
        self.trace_id = trace_id
        self.t0 = t0
        self.clock = clock
        self.marks: dict[str, float] = {}


class Span:
    """One in-progress span (context-manager use only — see the
    span-discipline lint rule). `set(k=v)` attaches attrs mid-flight."""

    __slots__ = ("_rec", "trace_id", "subsystem", "name", "_clock", "_t0", "attrs")

    def __init__(self, rec, trace_id, subsystem, name, clock, attrs):
        self._rec = rec
        self.trace_id = trace_id
        self.subsystem = subsystem
        self.name = name
        self._clock = clock
        self._t0 = 0.0
        self.attrs = attrs

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self._t0 = self._clock.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.attrs["error"] = repr(exc)
        self._rec._append(
            self.trace_id,
            self.subsystem,
            self.name,
            self._t0,
            self._clock.monotonic() - self._t0,
            self.attrs or None,
        )


class _NopSpan:
    """Shared do-nothing span for disabled tracing: one module-level
    instance, zero per-call allocation."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NOP_SPAN = _NopSpan()


class FlightRecorder:
    """Bounded per-process span ring (the "flight recorder"). All nodes
    in one process share it — like the VerifyHub they also share — so a
    dump shows the whole funnel, cross-node dedup included."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        ring_size: int = DEFAULT_RING,
        out_dir: str = "",
    ):
        self.enabled = enabled
        self.ring_size = max(1, ring_size)
        self.out_dir = out_dir
        # (trace_id, subsystem, name, start_s, duration_s, attrs|None)
        self._ring: deque[tuple] = deque(maxlen=self.ring_size)
        self.recorded = 0  # total appended; dropped = recorded - len(ring)
        # auto_dump records (reason + path); bounded — /debug/flight?dump=
        # is operator-reachable, and stats() returns this list in every
        # /debug response, so it must not grow without limit
        self.dumps: deque = deque(maxlen=64)
        self._dump_seq = itertools.count(1)

    # -- recording -------------------------------------------------------

    def _append(self, trace_id, subsystem, name, start_s, dur_s, attrs) -> None:
        # deque.append with maxlen evicts the oldest atomically under the
        # GIL — safe from both the event loop and the hub's threads
        self._ring.append((trace_id, subsystem, name, start_s, dur_s, attrs))
        self.recorded += 1

    def start(self, clock: Clock | None = None) -> TraceCtx | None:
        """Open a new trace at the funnel edge; None when disabled (every
        downstream record/finish call then no-ops on the None ctx)."""
        if not self.enabled:
            return None
        clock = clock or SYSTEM
        return TraceCtx(next(_ids), clock.monotonic(), clock)

    def record(
        self,
        ctx: TraceCtx | None,
        subsystem: str,
        name: str,
        start_s: float,
        end_s: float,
        **attrs,
    ) -> None:
        """Record one contiguous pipeline stage with explicit boundary
        timestamps (taken from the ctx's clock by the caller)."""
        if ctx is None or not self.enabled:
            return
        self._append(
            ctx.trace_id, subsystem, name, start_s, end_s - start_s, attrs or None
        )

    def finish(self, ctx: TraceCtx | None, subsystem: str, name: str, **attrs) -> None:
        """Close a trace: records the root span [ctx.t0, now]."""
        if ctx is None or not self.enabled:
            return
        now = ctx.clock.monotonic()
        self._append(ctx.trace_id, subsystem, name, ctx.t0, now - ctx.t0, attrs or None)

    def span(
        self,
        subsystem: str,
        name: str,
        *,
        ctx: TraceCtx | None = None,
        clock: Clock | None = None,
        **attrs,
    ) -> Span | _NopSpan:
        """Context-manager span for a code block. With a ctx the span
        joins that trace (and times on its clock); without one it is a
        standalone event on `clock` (default SYSTEM)."""
        if not self.enabled:
            return NOP_SPAN
        if ctx is not None:
            return Span(self, ctx.trace_id, subsystem, name, ctx.clock, attrs)
        return Span(self, 0, subsystem, name, clock or SYSTEM, attrs)

    def emit(
        self,
        subsystem: str,
        name: str,
        *,
        duration_s: float = 0.0,
        clock: Clock | None = None,
        **attrs,
    ) -> None:
        """Point-in-time event (attach attempt, breaker trip): a span of
        the given duration ending now."""
        if not self.enabled:
            return
        now = (clock or SYSTEM).monotonic()
        self._append(0, subsystem, name, now - duration_s, duration_s, attrs or None)

    # -- introspection ---------------------------------------------------

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        return self.recorded - len(self._ring)

    def dump(
        self, n: int | None = None, *, subsystem: str | None = None,
        trace_id: int | None = None,
    ) -> list[dict]:
        """Last `n` spans (oldest first) as JSON-ready dicts, optionally
        filtered by subsystem or trace id."""
        spans = list(self._ring)
        out = []
        for tid, sub, name, start, dur, attrs in spans:
            if subsystem is not None and sub != subsystem:
                continue
            if trace_id is not None and tid != trace_id:
                continue
            d = {
                "trace_id": tid,
                "subsystem": sub,
                "name": name,
                "start_s": round(start, 6),
                "duration_ms": round(dur * 1e3, 4),
            }
            if attrs:
                d["attrs"] = attrs
            out.append(d)
        if n is not None:
            out = out[-n:]
        return out

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "ring_size": self.ring_size,
            "spans": len(self._ring),
            "recorded": self.recorded,
            "dropped": self.dropped,
            "auto_dumps": list(self.dumps),
        }

    def auto_dump(self, reason: str) -> str | None:
        """Dump the ring because something went wrong (loop wedge, hub
        timeout, breaker trip). Returns the file path when `out_dir` is
        set, else records the event in-memory only. Diagnostics must
        never raise into the caller."""
        if not self.enabled:
            return None
        entry: dict = {"reason": reason, "spans": len(self._ring)}
        path = None
        if self.out_dir:
            try:
                os.makedirs(self.out_dir, exist_ok=True)
                # reasons reach here from operator input too
                # (/debug/flight?dump=<reason>) — keep the filename flat
                safe = re.sub(r"[^A-Za-z0-9._-]+", "_", reason) or "dump"
                path = os.path.join(
                    self.out_dir, f"flight-{safe}-{next(self._dump_seq)}.json"
                )
                with open(path, "w", encoding="utf-8") as f:
                    json.dump({"reason": reason, "spans": self.dump()}, f)
                entry["path"] = path
            except Exception as e:  # noqa: BLE001 — diagnostics must not raise
                logger.warning("flight dump for %r failed: %r", reason, e)
                path = None
        self.dumps.append(entry)
        logger.error(
            "flight recorder dumped (%s): %d spans%s",
            reason,
            len(self._ring),
            f" -> {path}" if path else "",
        )
        return path

    def clear(self) -> None:
        self._ring.clear()
        self.recorded = 0
        self.dumps.clear()


def _env_enabled(default: bool) -> bool:
    v = os.environ.get("TMTPU_TRACE")
    if v is None or v == "":
        return default
    return v.lower() not in ("0", "false", "no")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return int(v)
    except ValueError:
        # a malformed diagnostics knob must not kill the process: trace
        # is imported at module level by the whole verify funnel
        logger.warning("ignoring malformed %s=%r (want an int)", name, v)
        return default


#: THE process recorder. Module import reads the env so library users
#: (and tests that set TMTPU_TRACE before import) get the right mode
#: without any node wiring.
RECORDER = FlightRecorder(
    enabled=_env_enabled(True),
    ring_size=_env_int("TMTPU_TRACE_RING", DEFAULT_RING),
    out_dir=os.environ.get("TMTPU_TRACE_DIR", ""),
)


#: set once the first Node applied its `[trace]` section — the recorder
#: is process-wide, so a later node's (possibly default) config must not
#: silently clobber the first one's dump_dir/enabled mid-run
_node_configured = False


def configure_once(
    enabled: bool | None = None,
    ring_size: int | None = None,
    out_dir: str | None = None,
) -> bool:
    """Node-boot hook: apply `[trace]` config the FIRST time a node in
    this process starts; later nodes (multi-node tests, harnesses) are
    no-ops. Returns whether this call configured the recorder. Tests
    that need to reconfigure use `configure` / RECORDER directly."""
    global _node_configured
    if _node_configured:
        return False
    _node_configured = True
    configure(enabled=enabled, ring_size=ring_size, out_dir=out_dir)
    return True


def configure(
    enabled: bool | None = None,
    ring_size: int | None = None,
    out_dir: str | None = None,
) -> FlightRecorder:
    """Apply `[trace]` config to the process recorder. Env wins over
    explicit values (the same contract as the TMTPU_VERIFYHUB_* knobs):
    an operator exporting TMTPU_TRACE=0 silences every in-process node
    regardless of TOML."""
    if enabled is not None:
        RECORDER.enabled = _env_enabled(enabled)
    if ring_size is not None:
        size = _env_int("TMTPU_TRACE_RING", ring_size)
        if size != RECORDER.ring_size:
            RECORDER.ring_size = max(1, size)
            RECORDER._ring = deque(RECORDER._ring, maxlen=RECORDER.ring_size)
    if out_dir is not None:
        RECORDER.out_dir = os.environ.get("TMTPU_TRACE_DIR", "") or out_dir
    return RECORDER


# -- module-level conveniences (the names call sites import) ---------------


def is_enabled() -> bool:
    return RECORDER.enabled


def start(clock: Clock | None = None) -> TraceCtx | None:
    return RECORDER.start(clock)


def record(ctx, subsystem, name, start_s, end_s, **attrs) -> None:
    RECORDER.record(ctx, subsystem, name, start_s, end_s, **attrs)


def finish(ctx, subsystem, name, **attrs) -> None:
    RECORDER.finish(ctx, subsystem, name, **attrs)


def span(subsystem, name, *, ctx=None, clock=None, **attrs):
    return RECORDER.span(subsystem, name, ctx=ctx, clock=clock, **attrs)


def emit(subsystem, name, *, duration_s=0.0, clock=None, **attrs) -> None:
    RECORDER.emit(subsystem, name, duration_s=duration_s, clock=clock, **attrs)


def auto_dump(reason: str) -> str | None:
    return RECORDER.auto_dump(reason)
