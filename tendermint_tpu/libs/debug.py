"""Live-node debugging hooks (the analog of the reference's pprof server,
config/config.go:529, and `tendermint debug` collection,
cmd/tendermint/commands/debug/).

Go exposes goroutine/heap profiles over HTTP; the Python equivalent here:

  install_debug_handlers(home) — called by `start`:
    * faulthandler on SIGSEGV/SIGABRT (hard-crash tracebacks),
    * SIGUSR1 → dump every thread's Python stack AND every asyncio task
      to <home>/debug/stacks-<ts>.txt (the goroutine-dump analog),
    * a pidfile at <home>/node.pid so `debug kill` can target the node.

  collect_node_state(...) — snapshot a live node over RPC (status,
  consensus state, net info, unconfirmed txs) for `debug dump` bundles.
"""

from __future__ import annotations

import faulthandler
import io
import json
import os
import signal
import sys
import time


def _dump_asyncio_tasks(buf: io.StringIO) -> None:
    import asyncio

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        # signal handlers run on the main thread; if the loop lives on a
        # different thread (embedders) its tasks can't be enumerated from
        # here — say so rather than writing a misleading empty list
        buf.write(
            "\n=== asyncio tasks: no running loop on the signal thread ===\n"
        )
        return
    tasks = asyncio.all_tasks(loop)
    buf.write(f"\n=== asyncio tasks ({len(tasks)}) ===\n")
    for t in tasks:
        buf.write(f"-- {t.get_name()}: {t!r}\n")
        stack = t.get_stack(limit=8)
        for frame in stack:
            buf.write(
                f"   {frame.f_code.co_filename}:{frame.f_lineno} "
                f"{frame.f_code.co_name}\n"
            )


def install_debug_handlers(home: str) -> None:
    debug_dir = os.path.join(home, "debug")
    os.makedirs(debug_dir, exist_ok=True)
    pid_path = os.path.join(home, "node.pid")
    if os.path.exists(pid_path):
        # refuse to clobber a LIVE node's pidfile (a second accidental
        # `start` would otherwise point `debug kill` at the wrong pid —
        # or delete the file on its way out)
        try:
            with open(pid_path) as f:
                old_pid = int(f.read().strip())
            os.kill(old_pid, 0)
        except (OSError, ValueError):
            pass  # stale or unreadable: take it over
        else:
            raise RuntimeError(
                f"node already running in {home} (pid {old_pid}); "
                "remove node.pid if this is stale"
            )
    with open(pid_path, "w") as f:
        f.write(str(os.getpid()))
    faulthandler.enable()

    def on_sigusr1(_sig, _frame) -> None:
        ts = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(debug_dir, f"stacks-{ts}.txt")
        with open(path, "w") as f:
            f.write(f"=== thread stacks pid={os.getpid()} ===\n")
            f.flush()
            # faulthandler writes via the fd, not the Python file object
            faulthandler.dump_traceback(file=f)
            buf = io.StringIO()
            try:
                _dump_asyncio_tasks(buf)
            except Exception as e:  # noqa: BLE001 — diagnostics must not crash
                buf.write(f"(task dump failed: {e!r})\n")
            f.write(buf.getvalue())
        print(f"debug: stacks dumped to {path}", file=sys.stderr)

    signal.signal(signal.SIGUSR1, on_sigusr1)


async def collect_node_state(rpc_client) -> dict:
    """Snapshot a live node over RPC (reference debug/dump.go shape)."""
    out: dict = {"collected_at": time.time()}
    for name, method in (
        ("status", "status"),
        ("consensus_state", "consensus_state"),
        ("net_info", "net_info"),
        ("num_unconfirmed_txs", "num_unconfirmed_txs"),
    ):
        try:
            out[name] = await rpc_client.call(method)
        except Exception as e:  # noqa: BLE001
            out[name] = {"error": repr(e)}
    return out


def write_dump_bundle(dest_dir: str, snapshot: dict, home: str | None) -> str:
    """Write one timestamped dump bundle: the RPC snapshot plus local
    artifacts (config, recent stack dumps) when `home` is given. Bundle
    names carry a monotonic suffix so rapid dumps never merge."""
    ts = time.strftime("%Y%m%d-%H%M%S")
    n = 0
    while True:
        bundle = os.path.join(dest_dir, f"dump-{ts}-{n}")
        if not os.path.exists(bundle):
            break
        n += 1
    os.makedirs(bundle)
    with open(os.path.join(bundle, "node_state.json"), "w") as f:
        json.dump(snapshot, f, indent=2, default=repr)
    if home:
        cfg = os.path.join(home, "config", "config.toml")
        if os.path.exists(cfg):
            import shutil

            shutil.copy(cfg, os.path.join(bundle, "config.toml"))
        debug_dir = os.path.join(home, "debug")
        if os.path.isdir(debug_dir):
            import shutil

            for name in sorted(os.listdir(debug_dir))[-3:]:
                shutil.copy(
                    os.path.join(debug_dir, name), os.path.join(bundle, name)
                )
    return bundle
