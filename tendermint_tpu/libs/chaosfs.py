"""Chaos-fs: seeded, deterministic storage-fault injection.

`libs/chaos.py` covers the network half of the fault model; this module
covers the disk half — the write path under `consensus/wal.py`,
`store/blockstore.py`, `store/db.py`, and `state/store.py`. It is both
the **injectable I/O layer** those subsystems are required to use (the
tmtlint `fs-discipline` rule forbids raw `open(.., "wb")`/`os.fsync`
there, and `transitive-fs` forbids reaching one through a helper in
another file) and the fault controller that perturbs it.

Fault classes (all per-operation, all drawn from ONE seeded RNG so a
fault schedule is reproducible):

  * **torn writes** — at `simulate_crash()`, un-fsynced bytes survive
    only partially: the tail is cut at a seeded (or configured,
    `torn_offset`) byte offset, typically mid-record. This is the
    sector-granularity reality `fsync` exists to paper over.
  * **lost-but-acked fsyncs** — `fsync` returns success but the durable
    watermark does not advance; the "synced" bytes are torn away by the
    next crash. Models firmware write-cache lies.
  * **disk-full (ENOSPC) mid-record** — a write persists only a prefix
    and raises `OSError(ENOSPC)`; either probabilistic (`enospc_rate`)
    or armed at an exact cumulative byte count (`enospc_at_byte`).
  * **bit-rot on read** — a read returns one flipped byte
    (`bitrot_rate`), exercising CRC detection and WAL repair.

The crash model: bytes below the per-file durable watermark (advanced by
honest fsyncs) ALWAYS survive `simulate_crash()`; bytes above it are
dropped, except a torn partial tail. `WAL.repair()` must therefore bring
any post-crash file back to a replayable state.

`ChaosDB` applies the ENOSPC/bit-rot classes to any `store.db.DB`
(SQLite batches are atomic, so torn DB writes cannot happen by
construction — the WAL is where torn writes live).

Env mirror (`config.ChaosFSConfig`): TMTPU_CHAOS_FS_SEED, _TORN,
_TORN_OFFSET, _LOST_FSYNC, _ENOSPC, _ENOSPC_AT, _BITROT.
"""

from __future__ import annotations

import errno
import os
import random
from dataclasses import dataclass
from typing import Iterator

from ..store.db import DB


@dataclass(frozen=True)
class ChaosFSConfig:
    seed: int = 0
    torn_write_rate: float = 0.0  # P(un-fsynced tail is torn, not dropped, at crash)
    torn_offset: int = -1  # fixed tear offset into the volatile tail; -1 = seeded
    lost_fsync_rate: float = 0.0  # P(fsync acked but not durable)
    enospc_rate: float = 0.0  # P(write fails ENOSPC mid-record)
    enospc_at_byte: int = -1  # arm ENOSPC at an exact cumulative byte; -1 = off
    bitrot_rate: float = 0.0  # P(read returns one flipped byte)

    @classmethod
    def from_env(cls) -> "ChaosFSConfig":
        def f(name: str, default: float = 0.0) -> float:
            raw = os.environ.get(name, "")
            return float(raw) if raw else default

        return cls(
            seed=int(os.environ.get("TMTPU_CHAOS_FS_SEED", "0") or 0),
            torn_write_rate=f("TMTPU_CHAOS_FS_TORN"),
            torn_offset=int(os.environ.get("TMTPU_CHAOS_FS_TORN_OFFSET", "-1") or -1),
            lost_fsync_rate=f("TMTPU_CHAOS_FS_LOST_FSYNC"),
            enospc_rate=f("TMTPU_CHAOS_FS_ENOSPC"),
            enospc_at_byte=int(os.environ.get("TMTPU_CHAOS_FS_ENOSPC_AT", "-1") or -1),
            bitrot_rate=f("TMTPU_CHAOS_FS_BITROT"),
        )

    def enabled(self) -> bool:
        return any(
            (
                self.torn_write_rate,
                self.lost_fsync_rate,
                self.enospc_rate,
                self.enospc_at_byte >= 0,
                self.bitrot_rate,
            )
        )


def _flip_one_byte(rng: random.Random, data: bytes) -> bytes:
    """One seeded bit-rot hit: a single byte XORed with a nonzero mask."""
    i = rng.randrange(len(data))
    flip = 1 + rng.getrandbits(8) % 255
    return data[:i] + bytes([data[i] ^ flip]) + data[i + 1 :]


class FS:
    """The injectable file-I/O layer. The real implementation is this
    base class; `ChaosFS` perturbs it. Storage subsystems take an `fs`
    and never touch `open`/`os.fsync` directly (lint-enforced)."""

    def open(self, path: str, mode: str = "rb"):
        return open(path, mode)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())

    def rename(self, src: str, dst: str) -> None:
        os.rename(src, dst)

    def remove(self, path: str) -> None:
        os.remove(path)

    def truncate(self, path: str, size: int) -> None:
        with open(path, "r+b") as f:
            f.truncate(size)

    def listdir(self, path: str) -> list[str]:
        return os.listdir(path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)


REAL_FS = FS()


class _ChaosFile:
    """File wrapper that routes durability and fault rolls through the
    owning ChaosFS controller."""

    def __init__(self, fs: "ChaosFS", inner, path: str, writable: bool):
        self._fs = fs
        self._inner = inner
        self.path = path
        self._writable = writable

    def write(self, data: bytes) -> int:
        return self._fs._write(self, data)

    def read(self, n: int = -1) -> bytes:
        return self._fs._read(self, self._inner.read(n))

    def flush(self) -> None:
        self._inner.flush()

    def fileno(self) -> int:
        return self._inner.fileno()

    def tell(self) -> int:
        return self._inner.tell()

    def seek(self, pos: int, whence: int = 0) -> int:
        return self._inner.seek(pos, whence)

    def truncate(self, size: int | None = None) -> int:
        return self._inner.truncate(size)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ChaosFS(FS):
    """Seeded fault-injecting FS + the shared storage-chaos controller
    (also wraps DBs via `wrap_db`). One RNG, one fault-counter map."""

    def __init__(self, config: ChaosFSConfig | None = None):
        self.config = config or ChaosFSConfig()
        self.rng = random.Random(self.config.seed)
        # path -> durable byte watermark (bytes guaranteed to survive a
        # simulated crash). Only files opened for writing are tracked.
        self.durable: dict[str, int] = {}
        self._written = 0  # cumulative bytes, drives enospc_at_byte
        self._halted = False  # "the process just died": fsyncs stop counting
        self._enospc_fired = False  # enospc_at_byte is one-shot (disk freed)
        self.faults: dict[str, int] = {
            "torn_write": 0, "lost_fsync": 0, "enospc": 0, "bitrot": 0,
            "crash_lost_bytes": 0, "db_enospc": 0, "db_bitrot": 0,
        }

    # -- FS interface ----------------------------------------------------

    def open(self, path: str, mode: str = "rb"):
        inner = open(path, mode)
        writable = any(c in mode for c in "wa+x")
        if writable and path not in self.durable:
            # pre-existing bytes survived a previous session: durable
            self.durable[path] = self.getsize(path) if self.exists(path) else 0
        if "w" in mode or "x" in mode:
            self.durable[path] = 0
        return _ChaosFile(self, inner, path, writable)

    def fsync(self, f) -> None:
        if not isinstance(f, _ChaosFile):
            REAL_FS.fsync(f)
            return
        f.flush()
        os.fsync(f.fileno())
        if self._halted:
            return  # post-mortem teardown: nothing becomes durable anymore
        cfg = self.config
        if cfg.lost_fsync_rate > 0 and self.rng.random() < cfg.lost_fsync_rate:
            self.faults["lost_fsync"] += 1
            return  # acked, but the watermark does not move
        self.durable[f.path] = os.fstat(f.fileno()).st_size

    def rename(self, src: str, dst: str) -> None:
        os.rename(src, dst)
        if src in self.durable:
            self.durable[dst] = self.durable.pop(src)

    def remove(self, path: str) -> None:
        os.remove(path)
        self.durable.pop(path, None)

    def truncate(self, path: str, size: int) -> None:
        REAL_FS.truncate(path, size)
        if path in self.durable:
            self.durable[path] = min(self.durable[path], size)

    # -- fault rolls (called by _ChaosFile) ------------------------------

    def _write(self, f: _ChaosFile, data: bytes) -> int:
        cfg = self.config
        full = len(data)
        cut = -1
        if (
            not self._enospc_fired
            and 0 <= cfg.enospc_at_byte <= self._written + full
        ):
            # one-shot: the disk is "full" once; the post-restart process
            # finds space again (the operator freed it)
            self._enospc_fired = True
            cut = max(0, cfg.enospc_at_byte - self._written)
        elif cfg.enospc_rate > 0 and self.rng.random() < cfg.enospc_rate:
            cut = self.rng.randrange(full) if full else 0
        if cut >= 0:
            self.faults["enospc"] += 1
            f._inner.write(data[:cut])
            self._written += cut
            raise OSError(errno.ENOSPC, "chaosfs: no space left on device", f.path)
        f._inner.write(data)
        self._written += full
        return full

    def _read(self, f: _ChaosFile, data: bytes) -> bytes:
        cfg = self.config
        if data and cfg.bitrot_rate > 0 and self.rng.random() < cfg.bitrot_rate:
            self.faults["bitrot"] += 1
            return _flip_one_byte(self.rng, data)
        return data

    # -- the crash -------------------------------------------------------

    def halt(self) -> None:
        """Freeze the durability view: the process "dies" HERE. In-process
        harnesses still run clean teardown (Service.stop flushes + fsyncs
        the WAL), which a real crash never gets — calling `halt()` first
        makes those post-mortem fsyncs no-ops on the watermark, so
        `simulate_crash()` reflects the crash instant."""
        self._halted = True

    def simulate_crash(self) -> dict[str, int]:
        """Apply the crash model: every tracked file loses its un-fsynced
        tail — entirely, or (torn-write roll) down to a partial, usually
        mid-record, fragment. Returns {path: surviving_size}. Call with
        writers closed (the in-process analog of the process dying)."""
        cfg = self.config
        out: dict[str, int] = {}
        for path in sorted(self.durable):  # sorted: deterministic RNG order
            if not self.exists(path):
                continue
            size = self.getsize(path)
            keep = min(self.durable[path], size)
            volatile = size - keep
            if volatile > 0:
                if cfg.torn_write_rate > 0 and self.rng.random() < cfg.torn_write_rate:
                    self.faults["torn_write"] += 1
                    if cfg.torn_offset >= 0:
                        keep += min(cfg.torn_offset, volatile)
                    else:
                        keep += self.rng.randrange(1, volatile + 1)
                self.faults["crash_lost_bytes"] += size - keep
                REAL_FS.truncate(path, keep)
            self.durable[path] = keep
            out[path] = keep
        self._halted = False  # the restarted process fsyncs for real again
        return out

    # -- DB side ---------------------------------------------------------

    def wrap_db(self, db: DB) -> "ChaosDB":
        return ChaosDB(self, db)


class ChaosDB(DB):
    """ENOSPC + bit-rot injection over any DB. Batches stay atomic (the
    real engines guarantee that); a failed batch applies nothing."""

    def __init__(self, fs: ChaosFS, inner: DB):
        self.fs = fs
        self.inner = inner

    def _roll_enospc(self) -> None:
        cfg = self.fs.config
        if cfg.enospc_rate > 0 and self.fs.rng.random() < cfg.enospc_rate:
            self.fs.faults["db_enospc"] += 1
            raise OSError(errno.ENOSPC, "chaosfs: db write hit disk-full")

    def _rot(self, value: bytes | None) -> bytes | None:
        cfg = self.fs.config
        if (
            value
            and cfg.bitrot_rate > 0
            and self.fs.rng.random() < cfg.bitrot_rate
        ):
            self.fs.faults["db_bitrot"] += 1
            return _flip_one_byte(self.fs.rng, value)
        return value

    def get(self, key: bytes) -> bytes | None:
        return self._rot(self.inner.get(key))

    def set(self, key: bytes, value: bytes) -> None:
        self._roll_enospc()
        self.inner.set(key, value)

    def delete(self, key: bytes) -> None:
        self.inner.delete(key)

    def iterate(
        self, start: bytes = b"", end: bytes | None = None, reverse: bool = False
    ) -> Iterator[tuple[bytes, bytes]]:
        for k, v in self.inner.iterate(start, end, reverse):
            yield k, self._rot(v)

    def write_batch(self, sets, deletes=()):
        self._roll_enospc()
        self.inner.write_batch(sets, deletes)

    def close(self) -> None:
        self.inner.close()
