"""Injectable time sources for consensus (the deterministic-clock analog
of the reference's tmtime package, plus the chaos side: per-validator
skew).

Consensus stamps wall-clock time into protocol output — vote timestamps
(`ConsensusState._vote_time_ns`) and, through the weighted-median rule,
block header times — so chaos matrices over live consensus were not
bit-reproducible: two runs with the same fault seed produced different
hashes purely because `time.time_ns()` moved. Threading a `Clock`
through `consensus/state.py`, `ticker.py`, and `reactor.py` fixes both
halves:

  * determinism — a `ManualClock` frozen at (or behind) genesis makes
    every vote timestamp collapse to `block_time + 1ms` via the
    vote-time minimum rule (state.go:2237 voteTime), so timestamps are
    a pure function of (height, genesis_time): identical across runs
    regardless of asyncio scheduling;
  * clock skew as a fault class — a `SkewedClock` per validator (offset
    drawn deterministically from the chaos seed, libs/chaos.py
    `ChaosNetwork.clock_for`) models committee deployments where NTP
    drift puts validators hundreds of ms apart, and `rate` models a
    fast/slow oscillator (timeouts fire early/late through the ticker).

The default `SYSTEM` clock is `time.time_ns()` — production behavior is
unchanged unless a clock is injected.
"""

from __future__ import annotations

import time


class Clock:
    """Time source interface. `now_ns` is the wall-clock reading stamped
    into votes/blocks; `rate` scales *durations* (a 1.05 clock runs 5%
    fast: its owner's timeouts fire early by that factor)."""

    rate: float = 1.0

    def now_ns(self) -> int:
        raise NotImplementedError

    def timeout_s(self, duration_ns: int) -> float:
        """Real seconds this clock's owner waits for a nominal duration."""
        return duration_ns / 1e9 / self.rate

    def monotonic_ns(self) -> int:
        """Duration/deadline domain: RTO samples, ban cooldowns, grace
        windows. Never stamped into protocol output, and it always
        advances — a ManualClock freezes only the wall-clock domain, so
        deadline watchdogs (e.g. `wait_for_height`) still fire under a
        frozen clock. Scaled by `rate`: a fast oscillator's owner sees
        durations elapse early, matching its scaled timeouts."""
        return int(time.monotonic_ns() * self.rate)

    def monotonic(self) -> float:
        """`monotonic_ns` in float seconds (the time.monotonic shape)."""
        return self.monotonic_ns() / 1e9


class SystemClock(Clock):
    def now_ns(self) -> int:
        return time.time_ns()


class ManualClock(Clock):
    """Frozen/settable clock for deterministic tests. Never advances on
    its own; `advance()`/`set_ns()` move it explicitly."""

    def __init__(self, start_ns: int = 0, rate: float = 1.0):
        self._now_ns = start_ns
        self.rate = rate

    def now_ns(self) -> int:
        return self._now_ns

    def advance(self, delta_ns: int) -> None:
        self._now_ns += delta_ns

    def set_ns(self, now_ns: int) -> None:
        self._now_ns = now_ns


class SkewedClock(Clock):
    """A clock offset (and optionally drifting) from a base clock — one
    validator's wrong wall clock in a chaos run."""

    def __init__(self, base: Clock | None = None, offset_ns: int = 0, rate: float = 1.0):
        self.base = base or SYSTEM
        self.offset_ns = offset_ns
        self.rate = rate

    def now_ns(self) -> int:
        return self.base.now_ns() + self.offset_ns


SYSTEM = SystemClock()
