"""Crash-point injection (reference internal/libs/fail/fail.go:27).

Numbered `fail_point(n)` call sites sit at every commit sub-step; setting
FAIL_TEST_INDEX=n makes the n-th point terminate the process, letting the
crash-recovery matrix exercise a restart at EVERY intermediate state
(reference call sites state.go:1647-1712, execution.go:170-217).

Tests run in-process, so `set_crash_callback` replaces the default
os._exit with an exception the harness treats as the crash."""

from __future__ import annotations

import os
from typing import Callable

_callback: Callable[[int], None] | None = None
_index: int | None = None
_counter = 0


class InjectedCrash(BaseException):
    """Raised instead of exiting when a test callback is installed.
    BaseException so ordinary `except Exception` recovery paths don't
    swallow the simulated crash."""

    def __init__(self, point: int):
        super().__init__(f"injected crash at fail point {point}")
        self.point = point


def _get_index() -> int | None:
    global _index
    if _index is None:
        raw = os.environ.get("FAIL_TEST_INDEX", "")
        _index = int(raw) if raw else -1
    return _index


def set_crash_callback(cb: Callable[[int], None] | None, index: int | None = None) -> None:
    """Install a test crash handler and (optionally) override the index."""
    global _callback, _index, _counter
    _callback = cb
    _counter = 0
    if index is not None:
        _index = index


def reset() -> None:
    global _callback, _index, _counter
    _callback = None
    _index = None
    _counter = 0


def fail_point(point: int) -> None:
    """Crash if FAIL_TEST_INDEX (or the test override) equals this
    call-site number (reference fail.Fail)."""
    idx = _get_index()
    if idx is None or idx < 0 or point != idx:
        return
    if _callback is not None:
        _callback(point)
        return
    os._exit(99)
