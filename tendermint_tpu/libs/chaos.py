"""Chaos-net: seeded, deterministic network fault injection.

`libs/fail.py` injects process *crashes*; this module injects *network*
faults — the other half of the fault model committee-based consensus is
judged against (drops, delays, reordering, duplication, corruption,
partitions). A `ChaosNetwork` is the shared, seeded controller; wrapping
any p2p `Transport` in `ChaosTransport` threads the fault plan under
every reactor's send/recv path with zero changes to the reactors
themselves — the wrapper speaks the plain Transport/Connection interface
(p2p/transport.py).

Determinism: all randomness flows from ONE `random.Random(seed)` owned
by the controller, so a fault schedule is reproducible given the same
seed and the same message sequence per link. (Asyncio scheduling still
varies across runs; what is bit-reproducible is protocol OUTPUT — e.g.
synced block hashes — not packet timings.)

Config surface (env mirrors `config.ChaosConfig`):

  TMTPU_CHAOS_SEED       int     master seed (default 0)
  TMTPU_CHAOS_DROP       float   per-message drop probability
  TMTPU_CHAOS_DELAY_MS   float   p50 extra latency (exponential tail)
  TMTPU_CHAOS_DUP        float   duplication probability
  TMTPU_CHAOS_REORDER    float   reorder probability (delays one msg past
                                 its successor)
  TMTPU_CHAOS_CORRUPT    float   payload bit-flip probability
  TMTPU_CHAOS_BW         float   per-link bandwidth cap, bytes/sec — a
                                 leaky-bucket queue whose backlog turns
                                 into delivery delay (queue buildup)
  TMTPU_CHAOS_GRAY_MS    float   gray failure: fixed per-message delay
                                 (slow-but-alive, tuned to sit just under
                                 timeout thresholds)
  TMTPU_CHAOS_SKEW_MS    float   max |clock skew| per validator; each
                                 node's offset is drawn deterministically
                                 from (seed, node_id) — see `clock_for`
  TMTPU_CHAOS_DRIFT      float   max |oscillator rate error| per
                                 validator (0.05 = up to 5% fast/slow;
                                 consensus timeouts fire early/late)

Beyond the symmetric `partition()`, `partition_oneway(src, dst)` models
asymmetric reachability: src→dst traffic drops while dst→src flows (the
half-open links real WAN partitions produce)."""

from __future__ import annotations

import asyncio
import os
import random
from dataclasses import dataclass, field, replace

from ..p2p.transport import Connection, Transport


@dataclass(frozen=True)
class ChaosConfig:
    """Per-link fault rates. All probabilities are per message."""

    seed: int = 0
    drop_rate: float = 0.0
    delay_ms: float = 0.0  # p50 of an exponential extra-latency distribution
    duplicate_rate: float = 0.0
    reorder_rate: float = 0.0
    corrupt_rate: float = 0.0
    # per-link bandwidth cap in BYTES/sec (0 = unlimited): messages queue
    # behind each other on the link and backlog becomes delivery delay
    bandwidth_rate: float = 0.0
    # gray failure: a fixed delay on EVERY message (slow-but-alive; pick a
    # value just under the consumer's timeout to model the worst kind of
    # sick peer — one that never looks dead)
    gray_delay_ms: float = 0.0
    # max |per-validator clock skew| handed out by `ChaosNetwork.clock_for`
    clock_skew_ms: float = 0.0
    # max |per-validator oscillator drift| (rate error): 0.05 means each
    # validator's clock runs up to 5% fast/slow, so its consensus
    # timeouts fire early/late through the ticker's duration scaling
    clock_drift: float = 0.0
    # channel_id -> rate overrides, e.g. {0x40: ChaosConfig(drop_rate=0.5)}
    per_channel: dict = field(default_factory=dict)
    # per-link RNG streams instead of the one shared stream: every
    # (src, dst) link draws from random.Random(f"{seed}:{src}:{dst}"),
    # so a link's fault schedule depends only on ITS OWN message
    # sequence — the cross-process determinism contract RouterNet-XL
    # needs (K worker processes each own the send side of their links;
    # no shared RNG can span them). In-process harnesses keep the
    # shared stream by default: existing seeds pin existing schedules.
    link_seeded: bool = False

    @classmethod
    def from_env(cls) -> "ChaosConfig":
        def f(name: str, default: float = 0.0) -> float:
            raw = os.environ.get(name, "")
            return float(raw) if raw else default

        return cls(
            seed=int(os.environ.get("TMTPU_CHAOS_SEED", "0") or 0),
            drop_rate=f("TMTPU_CHAOS_DROP"),
            delay_ms=f("TMTPU_CHAOS_DELAY_MS"),
            duplicate_rate=f("TMTPU_CHAOS_DUP"),
            reorder_rate=f("TMTPU_CHAOS_REORDER"),
            corrupt_rate=f("TMTPU_CHAOS_CORRUPT"),
            bandwidth_rate=f("TMTPU_CHAOS_BW"),
            gray_delay_ms=f("TMTPU_CHAOS_GRAY_MS"),
            clock_skew_ms=f("TMTPU_CHAOS_SKEW_MS"),
            clock_drift=f("TMTPU_CHAOS_DRIFT"),
        )

    def enabled(self) -> bool:
        return any(
            (
                self.drop_rate,
                self.delay_ms,
                self.duplicate_rate,
                self.reorder_rate,
                self.corrupt_rate,
                self.bandwidth_rate,
                self.gray_delay_ms,
                self.clock_skew_ms,
                self.clock_drift,
                self.per_channel,
            )
        )

    def for_channel(self, channel_id: int) -> "ChaosConfig":
        override = self.per_channel.get(channel_id)
        if override is None:
            return self
        # overrides inherit the parent seed (one RNG per network anyway)
        return replace(override, seed=self.seed)


class ChaosNetwork:
    """Shared fault controller: one seeded RNG, one partition map, and
    fault counters for every link that threads through it.

    Partitions are sets of node-id groups: traffic BETWEEN groups is
    dropped, traffic within a group flows (subject to the rate faults).
    `heal()` clears them. Per-peer rate overrides target a specific
    node id in either direction."""

    def __init__(self, config: ChaosConfig | None = None):
        self.config = config or ChaosConfig()
        self.rng = random.Random(self.config.seed)
        # link_seeded mode: lazily-built per-link RNGs (see ChaosConfig)
        self._link_rngs: dict[tuple[str, str], random.Random] = {}
        self._groups: list[set[str]] = []
        self._oneway: list[tuple[set[str], set[str]]] = []  # (src, dst) blocked
        self._per_peer: dict[str, ChaosConfig] = {}
        # per-link leaky bucket for bandwidth shaping: (local, remote) ->
        # loop time at which the link's queue drains
        self._link_busy: dict[tuple[str, str], float] = {}
        # observability: fault class -> injected count (mirrored into
        # libs/metrics by whoever owns a NodeMetrics)
        self.faults: dict[str, int] = {
            "drop": 0, "delay": 0, "duplicate": 0, "reorder": 0,
            "corrupt": 0, "partition_drop": 0, "asym_drop": 0,
            "shaped": 0, "gray_delay": 0, "clock_skew": 0,
        }

    # -- topology faults -------------------------------------------------

    def partition(self, *groups: set[str] | list[str] | tuple[str, ...]) -> None:
        """Split the net: nodes in different groups cannot talk. Nodes in
        no group keep full connectivity to everyone (they are treated as
        a member of every group)."""
        self._groups = [set(g) for g in groups]

    def partition_oneway(
        self,
        src: str | set[str] | list[str] | tuple[str, ...],
        dst: str | set[str] | list[str] | tuple[str, ...],
    ) -> None:
        """Asymmetric partition: src→dst traffic is dropped while dst→src
        flows — the half-open link state symmetric partitions can't model
        (A believes B is down; B keeps answering into the void)."""
        to_set = lambda x: {x} if isinstance(x, str) else set(x)  # noqa: E731
        self._oneway.append((to_set(src), to_set(dst)))

    def heal(self) -> None:
        self._groups = []
        self._oneway = []

    def set_peer_config(self, node_id: str, config: ChaosConfig) -> None:
        """Rate override for any link whose far end is `node_id`."""
        self._per_peer[node_id] = config

    def set_gray(self, node_id: str, delay_ms: float) -> None:
        """Mark a peer gray: every message to it crawls by a fixed
        `delay_ms` (inheriting the network's other rates) — slow-but-alive
        rather than dead."""
        self._per_peer[node_id] = replace(self.config, gray_delay_ms=delay_ms)

    def clock_for(self, node_id: str, base=None):
        """A per-validator clock under the clock fault classes: a fixed
        offset (`clock_skew_ms`) and/or an oscillator rate error
        (`clock_drift` — the ticker scales timeout durations by it, so a
        fast validator fires consensus timeouts early). Both are drawn
        from an RNG keyed on (seed, node_id) — NOT the shared stream —
        so they are identical across runs regardless of the order clocks
        are handed out. Returns `base` (or the system clock) untouched
        when both fault classes are off."""
        from .clock import SYSTEM, SkewedClock

        skew_ms = self.config.clock_skew_ms
        drift = self.config.clock_drift
        if skew_ms <= 0 and drift <= 0:
            return base or SYSTEM
        r = random.Random(f"{self.config.seed}:clock:{node_id}")
        offset_ns = int(r.uniform(-skew_ms, skew_ms) * 1e6) if skew_ms > 0 else 0
        rate = 1.0 + (r.uniform(-drift, drift) if drift > 0 else 0.0)
        self.faults["clock_skew"] += 1
        return SkewedClock(base, offset_ns, rate=rate)

    def partitioned(self, a: str, b: str) -> bool:
        if not self._groups:
            return False
        ga = [i for i, g in enumerate(self._groups) if a in g]
        gb = [i for i, g in enumerate(self._groups) if b in g]
        if not ga or not gb:
            return False  # ungrouped nodes see everyone
        return not set(ga) & set(gb)

    def partitioned_oneway(self, src: str, dst: str) -> bool:
        return any(src in s and dst in d for s, d in self._oneway)

    # -- per-message fault plan -----------------------------------------

    def plan(
        self,
        local: str,
        remote: str,
        channel_id: int,
        nbytes: int = 0,
        now: float = 0.0,
    ) -> "_Faults":
        """Roll the dice for ONE message on the (local→remote, channel)
        link. Called under the event loop, so RNG use is serialized and
        the draw sequence is deterministic per seed. `nbytes`/`now` (loop
        time) feed bandwidth shaping; callers that don't shape may omit
        them."""
        cfg = self._per_peer.get(remote, self.config).for_channel(channel_id)
        if self.partitioned(local, remote):
            self.faults["partition_drop"] += 1
            return _Faults(drop=True)
        if self.partitioned_oneway(local, remote):
            self.faults["asym_drop"] += 1
            return _Faults(drop=True)
        if self.config.link_seeded:
            rng = self._link_rngs.get((local, remote))
            if rng is None:
                rng = random.Random(
                    f"{self.config.seed}:{local}:{remote}"
                )
                self._link_rngs[(local, remote)] = rng
        else:
            rng = self.rng
        drop = cfg.drop_rate > 0 and rng.random() < cfg.drop_rate
        if drop:
            self.faults["drop"] += 1
            return _Faults(drop=True)
        delay_s = 0.0
        if cfg.gray_delay_ms > 0:
            delay_s += cfg.gray_delay_ms / 1e3
            self.faults["gray_delay"] += 1
        if cfg.bandwidth_rate > 0 and nbytes > 0:
            # leaky bucket: the message transmits after everything already
            # queued on this link; backlog IS the delay (queue buildup)
            link = (local, remote)
            start = max(now, self._link_busy.get(link, 0.0))
            done = start + nbytes / cfg.bandwidth_rate
            self._link_busy[link] = done
            if done > now:
                delay_s += done - now
                if start > now:
                    self.faults["shaped"] += 1
        if cfg.delay_ms > 0:
            # exponential with median delay_ms: tail models queueing
            delay_s += rng.expovariate(0.6931471805599453 / (cfg.delay_ms / 1e3))
            self.faults["delay"] += 1
        duplicate = cfg.duplicate_rate > 0 and rng.random() < cfg.duplicate_rate
        if duplicate:
            self.faults["duplicate"] += 1
        reorder = cfg.reorder_rate > 0 and rng.random() < cfg.reorder_rate
        if reorder:
            self.faults["reorder"] += 1
        corrupt_at = -1
        if cfg.corrupt_rate > 0 and rng.random() < cfg.corrupt_rate:
            corrupt_at = rng.getrandbits(30)
            self.faults["corrupt"] += 1
        return _Faults(
            delay_s=delay_s,
            duplicate=duplicate,
            reorder=reorder,
            corrupt_at=corrupt_at,
        )

    def wrap(self, transport: Transport, node_id: str) -> "ChaosTransport":
        return ChaosTransport(self, transport, node_id)


@dataclass(frozen=True)
class _Faults:
    drop: bool = False
    delay_s: float = 0.0
    duplicate: bool = False
    reorder: bool = False
    corrupt_at: int = -1  # byte offset seed; -1 = no corruption


def _corrupt(data: bytes, at: int) -> bytes:
    if not data:
        return data
    i = at % len(data)
    return data[:i] + bytes([data[i] ^ (1 + (at >> 8) % 255)]) + data[i + 1 :]


class ChaosConnection(Connection):
    """Send-side fault injection over any Connection. Faults ride the
    send path (one side of each link is enough to model a lossy link;
    wrapping both sides compounds rates)."""

    def __init__(self, net: ChaosNetwork, inner: Connection, local: str):
        self.net = net
        self.inner = inner
        self.local = local
        self.remote = ""  # learned at handshake
        self._inflight: set[asyncio.Task] = set()

    async def handshake(self, node_info, priv_key):
        peer_info = await self.inner.handshake(node_info, priv_key)
        self.remote = peer_info.node_id
        return peer_info

    async def send_message(self, channel_id: int, data: bytes) -> None:
        remote = self.remote or self.inner.remote_addr
        plan = self.net.plan(
            self.local, remote, channel_id,
            nbytes=len(data), now=asyncio.get_running_loop().time(),
        )
        if plan.drop:
            return
        if plan.corrupt_at >= 0:
            data = _corrupt(bytes(data), plan.corrupt_at)
        copies = 2 if plan.duplicate else 1
        if plan.delay_s <= 0 and not plan.reorder:
            for _ in range(copies):
                await self.inner.send_message(channel_id, data)
            return
        # delayed / reordered: deliver from a task so the sender never
        # blocks on injected latency. Reorder = extra delay that pushes
        # the message past its successors.
        delay = plan.delay_s + (0.05 if plan.reorder else 0.0)
        t = asyncio.get_running_loop().create_task(
            self._deliver_later(channel_id, data, delay, copies)
        )
        self._inflight.add(t)
        t.add_done_callback(self._inflight.discard)

    async def _deliver_later(
        self, channel_id: int, data: bytes, delay: float, copies: int
    ) -> None:
        await asyncio.sleep(delay)
        try:
            for _ in range(copies):
                await self.inner.send_message(channel_id, data)
        except ConnectionError:
            pass  # link died while the message was in flight
        except asyncio.CancelledError:
            raise  # teardown cancels in-flight deliveries; don't absorb it

    async def receive_message(self) -> tuple[int, bytes]:
        return await self.inner.receive_message()

    @property
    def remote_addr(self) -> str:
        return self.inner.remote_addr

    async def close(self) -> None:
        for t in list(self._inflight):
            t.cancel()
        await self.inner.close()


class ChaosTransport(Transport):
    """Thread a ChaosNetwork under any Transport: both dialed and
    accepted connections come back chaos-wrapped."""

    def __init__(self, net: ChaosNetwork, inner: Transport, node_id: str):
        self.net = net
        self.inner = inner
        self.node_id = node_id
        self.PROTOCOL = inner.PROTOCOL

    async def listen(self, endpoint: str) -> None:
        await self.inner.listen(endpoint)

    def endpoint(self) -> str | None:
        return self.inner.endpoint()

    async def accept(self) -> Connection:
        return ChaosConnection(self.net, await self.inner.accept(), self.node_id)

    async def dial(self, address) -> Connection:
        return ChaosConnection(self.net, await self.inner.dial(address), self.node_id)

    async def close(self) -> None:
        await self.inner.close()
