"""Event-loop liveness watchdog — the asyncio analog of the reference's
deadlock-detecting mutexes (internal/libs/sync/deadlock.go:1-6, which
swap in go-deadlock when build-tagged).

Go detects a mutex held too long; the equivalent failure mode in a
single-threaded asyncio node is the LOOP wedging: a coroutine doing
blocking I/O / CPU inline, or a genuine deadlock between tasks awaiting
each other. Either way the symptom is identical — the loop stops
scheduling — and the diagnosis needs the same artifact Go prints: where
everything is stuck.

LoopWatchdog runs a daemon THREAD (it must live off the loop to observe
the loop being stuck) that schedules a trivial heartbeat callback via
`call_soon_threadsafe` and waits. If the heartbeat doesn't run within
`threshold_s`, it writes every thread's Python stack and every asyncio
task's stack to `<dir>/wedged-<ts>.txt` and logs loudly. One report per
wedge (re-armed once the loop breathes again) — a wedged loop that
recovers produces exactly one bundle, not a spray. A wedge also dumps
the flight recorder (`libs/trace.auto_dump`): the spans leading up to
the stall are the other half of the diagnosis.

BackendInitWatchdog is the other watchdog this module grew for the
ROADMAP attach problem: accelerator backend init (jax.devices() through
a TPU tunnel) historically got ONE 180 s cliff — it either came up or
the whole round fell to the CPU path with nothing recorded. The
watchdog replaces the cliff with bounded short attempts plus a cheap
periodic probe of earlier (still running) attempts, and records every
attempt into `crypto/backend_telemetry` so attach behavior is visible
in /metrics and the BENCH JSON.
"""

from __future__ import annotations

import io
import logging
import os
import threading
import time
import traceback

logger = logging.getLogger("libs.watchdog")


class LoopWatchdog:
    """Watches one asyncio loop from a side thread.

    start() must be called from the loop's thread (it captures the
    running loop); stop() from anywhere."""

    def __init__(
        self,
        out_dir: str,
        *,
        threshold_s: float = 5.0,
        interval_s: float = 2.0,
    ):
        self.out_dir = out_dir
        self.threshold_s = threshold_s
        self.interval_s = interval_s
        self._loop = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._beat = threading.Event()
        self.reports: list[str] = []  # paths of wedge reports written

    def start(self) -> None:
        import asyncio

        self._loop = asyncio.get_running_loop()
        self._thread = threading.Thread(
            target=self._run, name="loop-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # wake a thread parked in _beat.wait() immediately — without this,
        # stop() called FROM the loop thread would deadlock against its
        # own queued heartbeat for up to threshold_s
        self._beat.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- internals -------------------------------------------------------

    def _run(self) -> None:
        wedged = False
        while not self._stop.is_set():
            self._beat.clear()
            try:
                self._loop.call_soon_threadsafe(self._beat.set)
            except RuntimeError:
                return  # loop closed
            responded = self._beat.wait(self.threshold_s)
            if self._stop.is_set():
                return
            if not responded and not wedged:
                wedged = True
                self._report()
                try:
                    from . import trace

                    trace.auto_dump("loop-wedged")
                except Exception as e:  # noqa: BLE001 — diagnostics only
                    logger.debug("flight dump on wedge failed: %r", e)
            elif responded:
                wedged = False
            self._stop.wait(self.interval_s)

    def _report(self) -> None:
        buf = io.StringIO()
        buf.write(
            f"=== event loop unresponsive for >{self.threshold_s}s "
            f"at {time.strftime('%Y-%m-%dT%H:%M:%S')} ===\n\n"
        )
        frames = {t.ident: t.name for t in threading.enumerate()}
        import sys

        for ident, frame in sys._current_frames().items():
            buf.write(f"--- thread {frames.get(ident, ident)} ---\n")
            buf.write("".join(traceback.format_stack(frame)))
            buf.write("\n")
        # task stacks: enumerable from outside the loop thread —
        # all_tasks(loop) only reads the weak set
        try:
            import asyncio

            for task in asyncio.all_tasks(self._loop):
                state = (
                    "cancelled"
                    if task.cancelled()
                    else "done" if task.done() else "pending"
                )
                buf.write(f"--- task {task.get_name()} ({state}) ---\n")
                stack = task.get_stack()
                for f in stack:
                    buf.write("".join(traceback.format_stack(f)[-1:]))
            buf.write("\n")
        except Exception as e:  # noqa: BLE001 — diagnostics must not raise
            buf.write(f"(task enumeration failed: {e!r})\n")
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"wedged-{int(time.time()*1000)}.txt")
        with open(path, "w") as f:
            f.write(buf.getvalue())
        self.reports.append(path)
        logger.error(
            "event loop wedged >%ss; stacks dumped to %s", self.threshold_s, path
        )


class BackendInitWatchdog:
    """Bounded-retry, watchdogged backend init (ROADMAP: "a backend-init
    watchdog that probes cheaply and retries instead of one 180 s
    cliff").

    `run(fn)` executes `fn` on a daemon thread with a per-attempt
    timeout. A hung attempt is NOT a verdict: Python cannot kill the
    thread (jax backend init holds a global lock), so the thread keeps
    running and every later poll cheaply re-checks whether it finished
    late — a tunnel that comes up at t=70 s is adopted by the attempt
    that timed out at t=60 s, instead of being thrown away. Each
    attempt (latency, outcome, error) is recorded into
    `crypto/backend_telemetry` (-> /metrics + flight-recorder spans)
    and kept in `self.log` for callers that serialize the story.
    `crypto/batch._probe_tpu` runs the node-side attach behind this;
    bench.py keeps its own re-exec-based init (a hung jax init holds a
    global lock only a fresh process truly escapes) but emits the same
    record shape into the BENCH JSON.
    """

    def __init__(
        self,
        *,
        attempts: int = 3,
        timeout_s: float = 60.0,
        backoff_s: float = 5.0,
        poll_s: float = 1.0,
        name: str = "backend-init",
    ):
        self.attempts = max(1, attempts)
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        self.poll_s = max(0.05, poll_s)
        self.name = name
        #: structured per-attempt records: {latency_s, outcome, error?}
        self.log: list[dict] = []

    def _spawn(self, fn) -> dict:
        slot: dict = {"t0": time.monotonic()}

        def runner():
            try:
                slot["result"] = fn()
            except Exception as e:  # noqa: BLE001 — reported per attempt
                slot["error"] = e
            slot["elapsed"] = time.monotonic() - slot["t0"]

        t = threading.Thread(target=runner, name=self.name, daemon=True)
        slot["thread"] = t
        t.start()
        return slot

    @staticmethod
    def _settled(slot: dict) -> bool:
        return "result" in slot or "error" in slot

    def run(self, fn):
        """Returns `fn()`'s result when truthy, or None when every
        bounded attempt raised, returned falsy, or hung (the caller
        picks its fallback). Never raises."""
        from ..crypto import backend_telemetry as bt

        outstanding: list[dict] = []
        for i in range(self.attempts):
            slot = self._spawn(fn)
            outstanding.append(slot)
            deadline = time.monotonic() + self.timeout_s
            while time.monotonic() < deadline:
                # cheap probe: any attempt (this one OR an earlier hung
                # one that finished late) settling ends the wait
                for s in outstanding:
                    if self._settled(s):
                        break
                else:
                    slot["thread"].join(self.poll_s)
                    continue
                break
            settled = next((s for s in outstanding if s.get("result")), None)
            if settled is not None:
                latency = settled.get("elapsed", time.monotonic() - settled["t0"])
                self.log.append({"latency_s": round(latency, 3), "outcome": "ok"})
                bt.record_attach_attempt(latency, True)
                return settled["result"]
            # a clean falsy return ("no backend here") is a FAILED
            # attempt, not a success: telemetry must not count it as an
            # attach, and the bounded retries still apply — a tunnel can
            # answer "not yet" before it answers "ready"
            unavailable = next((s for s in outstanding if "result" in s), None)
            failed = next((s for s in outstanding if "error" in s), None)
            if unavailable is not None:
                outstanding.remove(unavailable)
                latency = unavailable.get(
                    "elapsed", time.monotonic() - unavailable["t0"]
                )
                self.log.append(
                    {"latency_s": round(latency, 3), "outcome": "unavailable"}
                )
                bt.record_attach_attempt(latency, False, error="unavailable")
                logger.warning(
                    "%s attempt %d/%d: backend unavailable after %.1fs",
                    self.name, i + 1, self.attempts, latency,
                )
            elif failed is not None:
                outstanding.remove(failed)
                latency = failed.get("elapsed", time.monotonic() - failed["t0"])
                err = repr(failed["error"])
                self.log.append(
                    {"latency_s": round(latency, 3), "outcome": "error", "error": err}
                )
                bt.record_attach_attempt(latency, False, error=err)
                logger.warning(
                    "%s attempt %d/%d failed after %.1fs: %s",
                    self.name, i + 1, self.attempts, latency, err,
                )
            else:
                latency = time.monotonic() - slot["t0"]
                self.log.append(
                    {"latency_s": round(latency, 3), "outcome": "hung"}
                )
                bt.record_attach_attempt(latency, False, error="hung")
                logger.warning(
                    "%s attempt %d/%d hung past %.0fs (thread left running; "
                    "later attempts keep probing it)",
                    self.name, i + 1, self.attempts, self.timeout_s,
                )
            if i < self.attempts - 1 and self.backoff_s:
                time.sleep(self.backoff_s * (i + 1))
        return None
