"""Event-loop liveness watchdog — the asyncio analog of the reference's
deadlock-detecting mutexes (internal/libs/sync/deadlock.go:1-6, which
swap in go-deadlock when build-tagged).

Go detects a mutex held too long; the equivalent failure mode in a
single-threaded asyncio node is the LOOP wedging: a coroutine doing
blocking I/O / CPU inline, or a genuine deadlock between tasks awaiting
each other. Either way the symptom is identical — the loop stops
scheduling — and the diagnosis needs the same artifact Go prints: where
everything is stuck.

LoopWatchdog runs a daemon THREAD (it must live off the loop to observe
the loop being stuck) that schedules a trivial heartbeat callback via
`call_soon_threadsafe` and waits. If the heartbeat doesn't run within
`threshold_s`, it writes every thread's Python stack and every asyncio
task's stack to `<dir>/wedged-<ts>.txt` and logs loudly. One report per
wedge (re-armed once the loop breathes again) — a wedged loop that
recovers produces exactly one bundle, not a spray.
"""

from __future__ import annotations

import io
import logging
import os
import threading
import time
import traceback

logger = logging.getLogger("libs.watchdog")


class LoopWatchdog:
    """Watches one asyncio loop from a side thread.

    start() must be called from the loop's thread (it captures the
    running loop); stop() from anywhere."""

    def __init__(
        self,
        out_dir: str,
        *,
        threshold_s: float = 5.0,
        interval_s: float = 2.0,
    ):
        self.out_dir = out_dir
        self.threshold_s = threshold_s
        self.interval_s = interval_s
        self._loop = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._beat = threading.Event()
        self.reports: list[str] = []  # paths of wedge reports written

    def start(self) -> None:
        import asyncio

        self._loop = asyncio.get_running_loop()
        self._thread = threading.Thread(
            target=self._run, name="loop-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # wake a thread parked in _beat.wait() immediately — without this,
        # stop() called FROM the loop thread would deadlock against its
        # own queued heartbeat for up to threshold_s
        self._beat.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- internals -------------------------------------------------------

    def _run(self) -> None:
        wedged = False
        while not self._stop.is_set():
            self._beat.clear()
            try:
                self._loop.call_soon_threadsafe(self._beat.set)
            except RuntimeError:
                return  # loop closed
            responded = self._beat.wait(self.threshold_s)
            if self._stop.is_set():
                return
            if not responded and not wedged:
                wedged = True
                self._report()
            elif responded:
                wedged = False
            self._stop.wait(self.interval_s)

    def _report(self) -> None:
        buf = io.StringIO()
        buf.write(
            f"=== event loop unresponsive for >{self.threshold_s}s "
            f"at {time.strftime('%Y-%m-%dT%H:%M:%S')} ===\n\n"
        )
        frames = {t.ident: t.name for t in threading.enumerate()}
        import sys

        for ident, frame in sys._current_frames().items():
            buf.write(f"--- thread {frames.get(ident, ident)} ---\n")
            buf.write("".join(traceback.format_stack(frame)))
            buf.write("\n")
        # task stacks: enumerable from outside the loop thread —
        # all_tasks(loop) only reads the weak set
        try:
            import asyncio

            for task in asyncio.all_tasks(self._loop):
                state = (
                    "cancelled"
                    if task.cancelled()
                    else "done" if task.done() else "pending"
                )
                buf.write(f"--- task {task.get_name()} ({state}) ---\n")
                stack = task.get_stack()
                for f in stack:
                    buf.write("".join(traceback.format_stack(f)[-1:]))
            buf.write("\n")
        except Exception as e:  # noqa: BLE001 — diagnostics must not raise
            buf.write(f"(task enumeration failed: {e!r})\n")
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"wedged-{int(time.time()*1000)}.txt")
        with open(path, "w") as f:
            f.write(buf.getvalue())
        self.reports.append(path)
        logger.error(
            "event loop wedged >%ss; stacks dumped to %s", self.threshold_s, path
        )
