"""Event pubsub with a query DSL (reference libs/pubsub/pubsub.go:111 and
libs/pubsub/query).

Messages are published with an attached event map `{composite_key:
[values]}` (e.g. `{"tm.event": ["Tx"], "tx.hash": ["AB12…"]}`); subscribers
filter with queries like `tm.event='Tx' AND tx.height>5`. The same Query
class drives RPC websocket subscriptions and the event indexer."""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from typing import Any


class QueryError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<and>AND)\b
      | (?P<op><=|>=|=|<|>)
      | (?P<exists>EXISTS)\b
      | (?P<contains>CONTAINS)\b
      | (?P<str>'(?:[^'\\]|\\.)*')
      | (?P<time>TIME\s+\S+|DATE\s+\S+)
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


@dataclass(frozen=True)
class Condition:
    key: str
    op: str  # '=', '<', '>', '<=', '>=', 'EXISTS', 'CONTAINS'
    operand: Any = None

    def matches(self, values: list[str]) -> bool:
        if self.op == "EXISTS":
            return True
        for v in values:
            if self.op == "=":
                if isinstance(self.operand, (int, float)):
                    try:
                        if float(v) == float(self.operand):
                            return True
                    except ValueError:
                        pass
                elif v == self.operand:
                    return True
            elif self.op == "CONTAINS":
                if str(self.operand) in v:
                    return True
            else:  # numeric comparisons
                try:
                    x = float(v)
                except ValueError:
                    continue
                y = float(self.operand)
                if (
                    (self.op == "<" and x < y)
                    or (self.op == ">" and x > y)
                    or (self.op == "<=" and x <= y)
                    or (self.op == ">=" and x >= y)
                ):
                    return True
        return False


@dataclass(frozen=True)
class Query:
    """Conjunction of conditions over the event map."""

    conditions: tuple[Condition, ...] = ()
    source: str = ""

    @classmethod
    def parse(cls, s: str) -> "Query":
        tokens: list[tuple[str, str]] = []
        pos = 0
        while pos < len(s):
            m = _TOKEN_RE.match(s, pos)
            if m is None or m.end() == pos:
                if s[pos:].strip():
                    raise QueryError(f"bad query near {s[pos:]!r}")
                break
            pos = m.end()
            for name, val in m.groupdict().items():
                if val is not None:
                    tokens.append((name, val))
                    break
        conds: list[Condition] = []
        i = 0
        while i < len(tokens):
            kind, val = tokens[i]
            if kind == "and":
                i += 1
                continue
            if kind != "key":
                raise QueryError(f"expected key, got {val!r}")
            if i + 1 >= len(tokens):
                raise QueryError(f"dangling key {val!r}")
            okind, oval = tokens[i + 1]
            if okind == "exists":
                conds.append(Condition(val, "EXISTS"))
                i += 2
                continue
            if okind == "contains":
                if i + 2 >= len(tokens):
                    raise QueryError("CONTAINS needs an operand")
                _, sval = tokens[i + 2]
                conds.append(Condition(val, "CONTAINS", _unquote(sval)))
                i += 3
                continue
            if okind != "op":
                raise QueryError(f"expected operator after {val!r}")
            if i + 2 >= len(tokens):
                raise QueryError("operator needs an operand")
            vkind, vval = tokens[i + 2]
            if vkind == "str":
                operand: Any = _unquote(vval)
            elif vkind == "num":
                operand = float(vval) if "." in vval else int(vval)
            elif vkind == "time":
                operand = vval.split(None, 1)[1]
            else:
                raise QueryError(f"bad operand {vval!r}")
            conds.append(Condition(val, oval, operand))
            i += 3
        return cls(tuple(conds), s)

    def matches(self, events: dict[str, list[str]]) -> bool:
        return all(
            c.key in events and c.matches(events[c.key]) for c in self.conditions
        )

    def __str__(self) -> str:
        return self.source


ALL = Query(source="<all>")  # empty conjunction matches everything


@dataclass
class Message:
    data: Any
    events: dict[str, list[str]] = field(default_factory=dict)


_CANCELLED = object()  # sentinel waking readers parked on the queue

#: process-wide drop accounting for drop_on_full subscriptions (the
#: websocket event fan-out); NodeMetrics folds it in at render time
DROPPED: dict[str, float] = {"events": 0.0}


class Subscription:
    def __init__(
        self, subscriber: str, query: Query, buffer: int,
        drop_on_full: bool = False,
    ):
        self.subscriber = subscriber
        self.query = query
        # +1 slot so the cancellation sentinel always fits
        self._queue: asyncio.Queue = asyncio.Queue(buffer + 1)
        self.cancelled: str | None = None  # cancellation reason
        # drop-with-counter instead of cancel-the-laggard: a slow
        # websocket consumer loses events (counted) but keeps its
        # subscription — bounded fan-out, never an unbounded queue
        self.drop_on_full = drop_on_full
        self.dropped = 0

    def _cancel(self, reason: str) -> None:
        self.cancelled = reason
        try:
            self._queue.put_nowait(_CANCELLED)
        except asyncio.QueueFull:
            pass

    async def next(self) -> Message:
        if self.cancelled and self._queue.empty():
            raise RuntimeError(f"subscription cancelled: {self.cancelled}")
        msg = await self._queue.get()
        if msg is _CANCELLED:
            raise RuntimeError(f"subscription cancelled: {self.cancelled}")
        return msg

    def __aiter__(self):
        return self

    async def __anext__(self) -> Message:
        try:
            return await self.next()
        except RuntimeError:
            raise StopAsyncIteration from None


class PubSub:
    """In-process pubsub server. Unlike the Go original there is no
    subscriber goroutine: publish fans out synchronously to subscription
    queues; a full queue cancels the laggard (out-of-band, like the
    reference's ErrOutOfCapacity)."""

    def __init__(self):
        self._subs: dict[tuple[str, str], Subscription] = {}

    def subscribe(
        self, subscriber: str, query: Query, buffer: int = 100,
        drop_on_full: bool = False,
    ) -> Subscription:
        key = (subscriber, str(query))
        if key in self._subs:
            raise ValueError(f"already subscribed: {key}")
        sub = Subscription(subscriber, query, buffer, drop_on_full)
        self._subs[key] = sub
        return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        sub = self._subs.pop((subscriber, str(query)), None)
        if sub is not None:
            sub._cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        for key in [k for k in self._subs if k[0] == subscriber]:
            self._subs.pop(key)._cancel("unsubscribed")

    def num_clients(self) -> int:
        return len({k[0] for k in self._subs})

    def publish(self, data: Any, events: dict[str, list[str]] | None = None) -> None:
        events = events or {}
        msg = Message(data, events)
        for key, sub in list(self._subs.items()):
            if not sub.query.matches(events):
                continue
            if sub._queue.qsize() >= sub._queue.maxsize - 1:
                if sub.drop_on_full:
                    # slow subscriber: drop THIS event with a counter,
                    # keep the subscription (websocket fan-out contract)
                    sub.dropped += 1
                    DROPPED["events"] += 1
                else:
                    self._subs.pop(key, None)
                    sub._cancel("out of capacity")
            else:
                sub._queue.put_nowait(msg)


def _unquote(s: str) -> str:
    return s[1:-1].replace("\\'", "'") if s.startswith("'") else s
