"""Flow-rate limiting and measurement (reference internal/libs/flowrate —
mikioh/flowrate — as used by MConnection's send/recv monitors,
internal/p2p/conn/connection.go:122-150).

`RateLimiter` is an asyncio token bucket: `await limiter.throttle(n)`
sleeps exactly long enough that the long-run average stays at `rate`
bytes/sec, with up to one `burst` of credit. This is the connection-level
backpressure discipline — senders BLOCK instead of dropping at a full
queue, so a slow peer slows its own stream rather than silently shedding
consensus-critical messages (VERDICT r3 weak #6).

`Meter` tracks an exponentially-weighted transfer rate for reporting
(the reference's flowrate.Monitor Status.AvgRate analog).
"""

from __future__ import annotations

import asyncio
import time


class RateLimiter:
    """Token bucket. rate: bytes/sec (0 = unlimited); burst: max bytes of
    accumulated credit (default one second's worth)."""

    def __init__(self, rate: float, burst: float | None = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._credit = self.burst
        self._last = time.monotonic()
        self._lock = asyncio.Lock()

    async def throttle(self, n: int) -> None:
        """Consume `n` bytes of credit, sleeping until available. Requests
        larger than the burst are allowed but pay the full debt (the
        bucket goes negative) so the AVERAGE still converges to `rate`."""
        if self.rate <= 0 or n <= 0:
            return
        async with self._lock:
            now = time.monotonic()
            self._credit = min(
                self.burst, self._credit + (now - self._last) * self.rate
            )
            self._last = now
            self._credit -= n
            if self._credit < 0:
                await asyncio.sleep(-self._credit / self.rate)

    def would_block(self, n: int) -> bool:
        now = time.monotonic()
        credit = min(self.burst, self._credit + (now - self._last) * self.rate)
        return credit < n


class Meter:
    """EWMA transfer-rate meter (reference flowrate.Monitor)."""

    def __init__(self, window_s: float = 1.0):
        self.window_s = window_s
        self.total = 0
        self._rate = 0.0
        self._last = time.monotonic()

    def update(self, n: int) -> None:
        now = time.monotonic()
        dt = now - self._last
        self.total += n
        if dt > 0:
            alpha = min(1.0, dt / self.window_s)
            inst = n / dt
            self._rate += alpha * (inst - self._rate)
            self._last = now

    @property
    def rate(self) -> float:
        """Bytes/sec, decayed toward zero while idle."""
        now = time.monotonic()
        dt = now - self._last
        if dt > self.window_s * 4:
            return 0.0
        return self._rate
