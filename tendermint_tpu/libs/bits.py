"""BitArray — thread-compatible bit vector used for vote bookkeeping and
block-part tracking (analog of reference libs/bits/bit_array.go)."""

from __future__ import annotations

import secrets

# bit offsets set in each possible byte value (true_indices hot loop)
_BYTE_BITS = tuple(
    tuple(i for i in range(8) if b >> i & 1) for b in range(256)
)


class BitArray:
    __slots__ = ("size", "_bits")

    # Hard allocation cap. Legitimate arrays track validators (hundreds)
    # or block parts (thousands); sizes arrive from the WIRE in several
    # gossip messages (vote-set bits, part-set headers, has-vote growth),
    # so without a cap one corrupt varint is a multi-GiB bytearray
    # allocation — a remote memory bomb. Oversize raises ValueError,
    # which the reactors attribute to the sending peer.
    MAX_SIZE = 1 << 24

    def __init__(self, size: int):
        if size < 0:
            raise ValueError("negative BitArray size")
        if size > self.MAX_SIZE:
            raise ValueError(
                f"BitArray size {size} exceeds MAX_SIZE {self.MAX_SIZE}"
            )
        self.size = size
        self._bits = bytearray((size + 7) // 8)

    @classmethod
    def from_indices(cls, size: int, indices) -> "BitArray":
        ba = cls(size)
        for i in indices:
            ba.set(i, True)
        return ba

    def get(self, i: int) -> bool:
        if not 0 <= i < self.size:
            return False
        return bool(self._bits[i >> 3] & (1 << (i & 7)))

    def set(self, i: int, value: bool) -> bool:
        if not 0 <= i < self.size:
            return False
        if value:
            self._bits[i >> 3] |= 1 << (i & 7)
        else:
            self._bits[i >> 3] &= ~(1 << (i & 7))
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.size)
        ba._bits = bytearray(self._bits)
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        out = BitArray(max(self.size, other.size))
        for i in range(len(out._bits)):
            a = self._bits[i] if i < len(self._bits) else 0
            b = other._bits[i] if i < len(other._bits) else 0
            out._bits[i] = a | b
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.size, other.size))
        for i in range(len(out._bits)):
            out._bits[i] = self._bits[i] & other._bits[i]
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.size)
        for i in range(len(out._bits)):
            out._bits[i] = ~self._bits[i] & 0xFF
        # clear padding bits beyond size
        extra = len(out._bits) * 8 - out.size
        if extra:
            out._bits[-1] &= 0xFF >> extra
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other."""
        out = self.copy()
        n = min(len(self._bits), len(other._bits))
        for i in range(n):
            out._bits[i] &= ~other._bits[i] & 0xFF
        return out

    def is_empty(self) -> bool:
        return not any(self._bits)

    def is_full(self) -> bool:
        if self.size == 0:
            return True
        full, extra = divmod(self.size, 8)
        if any(b != 0xFF for b in self._bits[:full]):
            return False
        if extra:
            return self._bits[full] == (0xFF >> (8 - extra))
        return True

    def pick_random(self) -> int | None:
        """Pick a uniformly random set bit index, or None if empty."""
        ones = self.true_indices()
        if not ones:
            return None
        return ones[secrets.randbelow(len(ones))]

    def true_indices(self) -> list[int]:
        """Set bit indices, byte-at-a-time via a 256-entry offset table.
        This is the consensus gossip hot loop — every vote-gossip tick
        diffs vote sets and walks the result, so the naive per-bit
        `get()` walk (8 calls per byte) dominated committee-scale
        profiles (24M get() calls in a 90s window at 150 validators)."""
        out: list[int] = []
        bits = self._bits
        byte_bits = _BYTE_BITS
        for byte_i, b in enumerate(bits):
            if b:
                base = byte_i << 3
                out.extend(base + off for off in byte_bits[b])
        # wire-decoded arrays (from_bytes) may carry garbage padding
        # bits beyond `size`; everything else keeps padding clear
        while out and out[-1] >= self.size:
            out.pop()
        return out

    def num_true(self) -> int:
        return sum(bin(b).count("1") for b in self._bits)

    def to_bytes(self) -> bytes:
        return bytes(self._bits)

    @classmethod
    def from_bytes(cls, size: int, data: bytes) -> "BitArray":
        ba = cls(size)
        ba._bits[: len(data)] = data[: len(ba._bits)]
        return ba

    def __len__(self) -> int:
        return self.size

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitArray)
            and self.size == other.size
            and self._bits == other._bits
        )

    def __repr__(self) -> str:
        s = "".join("x" if self.get(i) else "_" for i in range(min(self.size, 64)))
        return f"BitArray{{{s}{'…' if self.size > 64 else ''}}}"
