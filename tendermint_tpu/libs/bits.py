"""BitArray — thread-compatible bit vector used for vote bookkeeping and
block-part tracking (analog of reference libs/bits/bit_array.go)."""

from __future__ import annotations

import secrets


class BitArray:
    __slots__ = ("size", "_bits")

    def __init__(self, size: int):
        if size < 0:
            raise ValueError("negative BitArray size")
        self.size = size
        self._bits = bytearray((size + 7) // 8)

    @classmethod
    def from_indices(cls, size: int, indices) -> "BitArray":
        ba = cls(size)
        for i in indices:
            ba.set(i, True)
        return ba

    def get(self, i: int) -> bool:
        if not 0 <= i < self.size:
            return False
        return bool(self._bits[i >> 3] & (1 << (i & 7)))

    def set(self, i: int, value: bool) -> bool:
        if not 0 <= i < self.size:
            return False
        if value:
            self._bits[i >> 3] |= 1 << (i & 7)
        else:
            self._bits[i >> 3] &= ~(1 << (i & 7))
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.size)
        ba._bits = bytearray(self._bits)
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        out = BitArray(max(self.size, other.size))
        for i in range(len(out._bits)):
            a = self._bits[i] if i < len(self._bits) else 0
            b = other._bits[i] if i < len(other._bits) else 0
            out._bits[i] = a | b
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.size, other.size))
        for i in range(len(out._bits)):
            out._bits[i] = self._bits[i] & other._bits[i]
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.size)
        for i in range(len(out._bits)):
            out._bits[i] = ~self._bits[i] & 0xFF
        # clear padding bits beyond size
        extra = len(out._bits) * 8 - out.size
        if extra:
            out._bits[-1] &= 0xFF >> extra
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other."""
        out = self.copy()
        n = min(len(self._bits), len(other._bits))
        for i in range(n):
            out._bits[i] &= ~other._bits[i] & 0xFF
        return out

    def is_empty(self) -> bool:
        return not any(self._bits)

    def is_full(self) -> bool:
        if self.size == 0:
            return True
        full, extra = divmod(self.size, 8)
        if any(b != 0xFF for b in self._bits[:full]):
            return False
        if extra:
            return self._bits[full] == (0xFF >> (8 - extra))
        return True

    def pick_random(self) -> int | None:
        """Pick a uniformly random set bit index, or None if empty."""
        ones = [i for i in range(self.size) if self.get(i)]
        if not ones:
            return None
        return ones[secrets.randbelow(len(ones))]

    def true_indices(self) -> list[int]:
        return [i for i in range(self.size) if self.get(i)]

    def num_true(self) -> int:
        return sum(bin(b).count("1") for b in self._bits)

    def to_bytes(self) -> bytes:
        return bytes(self._bits)

    @classmethod
    def from_bytes(cls, size: int, data: bytes) -> "BitArray":
        ba = cls(size)
        ba._bits[: len(data)] = data[: len(ba._bits)]
        return ba

    def __len__(self) -> int:
        return self.size

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitArray)
            and self.size == other.size
            and self._bits == other._bits
        )

    def __repr__(self) -> str:
        s = "".join("x" if self.get(i) else "_" for i in range(min(self.size, 64)))
        return f"BitArray{{{s}{'…' if self.size > 64 else ''}}}"
