"""Async service lifecycle, the analog of the reference's BaseService
(reference libs/service/service.go).

A Service can be started once, stopped once, and exposes `wait_stopped()`.
Subclasses override `on_start` / `on_stop`. Unlike the Go original there is
no goroutine bookkeeping — asyncio tasks registered via `spawn` are cancelled
on stop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Coroutine


class Service:
    def __init__(self, name: str | None = None, logger: logging.Logger | None = None):
        self.name = name or type(self).__name__
        self.logger = logger or logging.getLogger(self.name)
        self._started = False
        self._stopped = asyncio.Event()
        self._stopping = False
        self._tasks: list[asyncio.Task] = []

    @property
    def is_running(self) -> bool:
        return self._started and not self._stopping

    async def start(self) -> None:
        if self._started:
            raise RuntimeError(f"service {self.name} already started")
        self._started = True
        self.logger.debug("starting %s", self.name)
        await self.on_start()

    #: per-task reap grace before a second cancel is issued (stop() must
    #: terminate even when a task resists cancellation)
    STOP_GRACE = 2.0

    async def stop(self) -> None:
        if not self._started or self._stopping:
            return
        self._stopping = True
        self.logger.debug("stopping %s", self.name)
        await self.on_stop()
        # Reap with three hardenings over a naive `for t: await t`:
        #  * snapshot + re-scan: done-callbacks mutate self._tasks during
        #    the loop (a live-list `for` skips entries), and teardown
        #    paths legitimately spawn late tasks (e.g. _disconnect_peer)
        #    that must be reaped too;
        #  * re-cancel on timeout: pre-3.11 asyncio.wait_for can ABSORB a
        #    cancellation that races the inner future's completion,
        #    leaving a "cancelled" task running its loop forever — the
        #    second cancel lands at its next await;
        #  * bounded waits: a task that still refuses to die is logged
        #    and abandoned rather than wedging the whole shutdown.
        seen: set[asyncio.Task] = set()
        queue = list(self._tasks)
        # broadcast the first cancel to EVERY task up front: the reap below
        # is sequential, and a stuck task must not delay its siblings'
        # cancellation (they'd keep routing/dialing mid-shutdown)
        for t in queue:
            t.cancel()
        while queue:
            t = queue.pop()
            if t in seen:
                continue
            seen.add(t)
            # asyncio.wait (unlike wait_for) neither cancels nor awaits the
            # task on timeout, so each grace window is a TRUE bound even
            # against a task that absorbs cancellation
            t.cancel()
            _done, not_done = await asyncio.wait({t}, timeout=self.STOP_GRACE)
            if not_done:
                t.cancel()
                _done, not_done = await asyncio.wait({t}, timeout=self.STOP_GRACE)
                if not_done:
                    self.logger.warning(
                        "%s: task %s did not stop; abandoning",
                        self.name,
                        t.get_name(),
                    )
            if t.done() and not t.cancelled():
                t.exception()  # consume, silencing 'never retrieved'
            # teardown paths legitimately spawn late tasks (e.g.
            # _disconnect_peer); queue them un-cancelled so their cleanup
            # runs — the bounded reap cancels them when their turn comes
            queue.extend(x for x in self._tasks if x not in seen and x not in queue)
        self._tasks.clear()
        self._stopped.set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    def spawn(self, coro: Coroutine, name: str | None = None) -> asyncio.Task:
        """Run a coroutine for the lifetime of the service."""
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.append(task)
        task.add_done_callback(self._on_task_done)
        return task

    def _on_task_done(self, task: asyncio.Task) -> None:
        try:
            self._tasks.remove(task)
        except ValueError:
            pass
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None and not self._stopping:
            self.logger.error("task %s crashed: %r", task.get_name(), exc)

    async def on_start(self) -> None:  # override
        pass

    async def on_stop(self) -> None:  # override
        pass
