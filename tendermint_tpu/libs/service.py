"""Async service lifecycle, the analog of the reference's BaseService
(reference libs/service/service.go).

A Service can be started once, stopped once, and exposes `wait_stopped()`.
Subclasses override `on_start` / `on_stop`. Unlike the Go original there is
no goroutine bookkeeping — asyncio tasks registered via `spawn` are cancelled
on stop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Coroutine


class Service:
    def __init__(self, name: str | None = None, logger: logging.Logger | None = None):
        self.name = name or type(self).__name__
        self.logger = logger or logging.getLogger(self.name)
        self._started = False
        self._stopped = asyncio.Event()
        self._stopping = False
        self._tasks: list[asyncio.Task] = []

    @property
    def is_running(self) -> bool:
        return self._started and not self._stopping

    async def start(self) -> None:
        if self._started:
            raise RuntimeError(f"service {self.name} already started")
        self._started = True
        self.logger.debug("starting %s", self.name)
        await self.on_start()

    async def stop(self) -> None:
        if not self._started or self._stopping:
            return
        self._stopping = True
        self.logger.debug("stopping %s", self.name)
        await self.on_stop()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except BaseException:  # noqa: B036 — reaping; outcomes are logged elsewhere
                pass
        self._tasks.clear()
        self._stopped.set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    def spawn(self, coro: Coroutine, name: str | None = None) -> asyncio.Task:
        """Run a coroutine for the lifetime of the service."""
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.append(task)
        task.add_done_callback(self._on_task_done)
        return task

    def _on_task_done(self, task: asyncio.Task) -> None:
        try:
            self._tasks.remove(task)
        except ValueError:
            pass
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None and not self._stopping:
            self.logger.error("task %s crashed: %r", task.get_name(), exc)

    async def on_start(self) -> None:  # override
        pass

    async def on_stop(self) -> None:  # override
        pass
