"""PartSet — blocks split into 64 KB merkle-proved parts for gossip
(reference types/part_set.go). A proposer splits the encoded block; peers
reassemble parts in any order, each carrying an inclusion proof against the
PartSetHeader hash."""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import merkle
from ..libs import protoenc as pe
from ..libs.bits import BitArray
from .block import PartSetHeader
from .keys import BLOCK_PART_SIZE


@dataclass(frozen=True)
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def leaf_hash(self) -> bytes:
        """SHA-256(0x00||bytes_), computed once and cached: receive-side
        proof verification and any re-gossip reuse one derivation
        instead of re-hashing the 64 KB payload per consumer. Safe on
        the frozen dataclass — bytes_ never changes."""
        cached = self.__dict__.get("_leaf_hash")
        if cached is None:
            from ..crypto import hash_hub

            cached = hash_hub.sha256_one(
                merkle.LEAF_PREFIX + self.bytes_, lane=hash_hub.LANE_VERIFY
            )
            self.__dict__["_leaf_hash"] = cached
        return cached

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.index + 1)
        out += pe.bytes_field(2, self.bytes_)
        out += pe.message_field(3, self.proof.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Part":
        r = pe.Reader(data)
        index, bytes_, proof = 0, b"", merkle.Proof(0, 0, b"", [])
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                index = r.read_uvarint() - 1
            elif f == 2:
                bytes_ = r.read_bytes()
            elif f == 3:
                proof = merkle.Proof.decode(r.read_bytes())
            else:
                r.skip(wt)
        return cls(index, bytes_, proof)


class PartSet:
    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE) -> "PartSet":
        from ..crypto import hash_hub

        chunks = [data[i : i + part_size] for i in range(0, len(data), part_size)] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(
            chunks, lane=hash_hub.LANE_BUILD
        )
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        # install directly: this process just BUILT the tree, so
        # re-verifying every proof through add_part would re-derive each
        # leaf hash from the 64 KB chunk it was computed from one line
        # up (the redundant-rehash ISSUE 20 names). Receive-side parts
        # still take the verifying add_part path.
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            part = Part(i, chunk, proof)
            part.__dict__["_leaf_hash"] = proof.leaf_hash
            ps.parts[i] = part
            ps.parts_bit_array.set(i, True)
            ps.count += 1
            ps.byte_size += len(chunk)
        return ps

    def __init__(self, header: PartSetHeader):
        self.header = header
        self.parts: list[Part | None] = [None] * header.total
        self.parts_bit_array = BitArray(header.total)
        self.count = 0
        self.byte_size = 0

    def add_part(self, part: Part) -> bool:
        """Verify the part's proof and slot it in. Returns False for
        duplicates; raises on invalid proofs."""
        if not 0 <= part.index < self.header.total:
            raise ValueError(f"part index {part.index} out of range")
        if self.parts[part.index] is not None:
            return False
        if part.proof.index != part.index or part.proof.total != self.header.total:
            raise ValueError("part proof position mismatch")
        # the cached leaf hash is derived from part.bytes_ itself, so
        # passing it only skips the re-derivation, not the check
        if not part.proof.verify(
            self.header.hash, part.bytes_, leaf_hash=part.leaf_hash()
        ):
            raise ValueError("invalid part proof")
        self.parts[part.index] = part
        self.parts_bit_array.set(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def get_part(self, idx: int) -> Part | None:
        if 0 <= idx < len(self.parts):
            return self.parts[idx]
        return None

    def is_complete(self) -> bool:
        return self.count == self.header.total

    def assemble(self) -> bytes:
        if not self.is_complete():
            raise ValueError("incomplete part set")
        return b"".join(p.bytes_ for p in self.parts)
