"""Domain types: blocks, votes, commits, validator sets, validation.

The consensus-critical surface mirrors the reference's types package
(reference types/ — Block/Header/Commit in block.go, Vote in vote.go,
ValidatorSet in validator_set.go, VerifyCommit* in validation.go) with
byte-deterministic canonical encodings produced by libs/protoenc.
"""

from .keys import SignedMsgType, BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL
from .block import BlockID, PartSetHeader, CommitSig, Commit, Header, Block
from .vote import Vote
from .validator_set import Validator, ValidatorSet
from .vote_set import VoteSet
from . import validation
