"""Block, Header, Commit, CommitSig, BlockID, PartSetHeader.

Structural analog of reference types/block.go. All hashes are RFC-6962
merkle roots over deterministic field encodings (libs/protoenc); every type
has encode()/decode() used for storage, gossip, and hashing — there is no
separate "proto" layer, the canonical encoding IS the wire format.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crypto.hashes import HASH_SIZE
from ..crypto import merkle
from ..libs import protoenc as pe
from .canonical import vote_sign_bytes, encode_timestamp
from .keys import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    SignedMsgType,
)

# Wire-side sanity bounds. Blocks and commits arrive from untrusted
# peers (block-sync, catch-up gossip, light provider responses) and the
# chaos matrix corrupts frames that still parse — a flipped repeat
# count must raise at decode, never allocate (tmtlint wire-bounds).
# Validator sets are ≤ a few hundred in practice; 2^16 signatures and
# 2^20 txs/evidence items are malformed by construction.
MAX_WIRE_COMMIT_SIGS = 1 << 16
MAX_WIRE_BLOCK_TXS = 1 << 20
MAX_WIRE_BLOCK_EVIDENCE = 1 << 16


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        return pe.varint_field(1, self.total) + pe.bytes_field(2, self.hash)

    @classmethod
    def decode(cls, data: bytes) -> "PartSetHeader":
        r = pe.Reader(data)
        total, hash_ = 0, b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                total = r.read_uvarint()
            elif f == 2:
                hash_ = r.read_bytes()
            else:
                r.skip(wt)
        return cls(total, hash_)

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative part-set total")
        if self.hash and len(self.hash) != HASH_SIZE:
            raise ValueError("bad part-set hash size")


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        return not self.hash and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == HASH_SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == HASH_SIZE
        )

    def key(self) -> bytes:
        return self.hash + self.part_set_header.encode()

    def encode(self) -> bytes:
        return pe.bytes_field(1, self.hash) + pe.message_field(
            2, self.part_set_header.encode()
        )

    @classmethod
    def decode(cls, data: bytes) -> "BlockID":
        r = pe.Reader(data)
        hash_, psh = b"", PartSetHeader()
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                hash_ = r.read_bytes()
            elif f == 2:
                psh = PartSetHeader.decode(r.read_bytes())
            else:
                r.skip(wt)
        return cls(hash_, psh)

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != HASH_SIZE:
            raise ValueError("bad block hash size")
        self.part_set_header.validate_basic()


NIL_BLOCK_ID = BlockID()


@dataclass(frozen=True)
class CommitSig:
    """One validator's precommit inside a Commit (reference types/block.go
    CommitSig). flag: absent (no vote seen), commit (voted for the block),
    nil (voted nil)."""

    flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    @classmethod
    def for_block(cls, addr: bytes, ts: int, sig: bytes) -> "CommitSig":
        return cls(BLOCK_ID_FLAG_COMMIT, addr, ts, sig)

    @classmethod
    def for_nil(cls, addr: bytes, ts: int, sig: bytes) -> "CommitSig":
        return cls(BLOCK_ID_FLAG_NIL, addr, ts, sig)

    def is_absent(self) -> bool:
        return self.flag == BLOCK_ID_FLAG_ABSENT

    def is_commit(self) -> bool:
        return self.flag == BLOCK_ID_FLAG_COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this signature attests to (reference types/block.go
        CommitSig.BlockID)."""
        return commit_block_id if self.flag == BLOCK_ID_FLAG_COMMIT else NIL_BLOCK_ID

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.flag)
        out += pe.bytes_field(2, self.validator_address)
        out += pe.message_field(3, encode_timestamp(self.timestamp_ns))
        out += pe.bytes_field(4, self.signature)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "CommitSig":
        r = pe.Reader(data)
        flag, addr, ts, sig = BLOCK_ID_FLAG_ABSENT, b"", 0, b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                flag = r.read_uvarint()
            elif f == 2:
                addr = r.read_bytes()
            elif f == 3:
                ts = _decode_timestamp(r.read_bytes())
            elif f == 4:
                sig = r.read_bytes()
            else:
                r.skip(wt)
        return cls(flag, addr, ts, sig)

    def validate_basic(self, *, aggregate: bool = False) -> None:
        """`aggregate=True` validates the entry as part of an aggregate
        commit: the per-validator signature lives in the commit-level
        aggregate, so it must be EMPTY here (flag/address/timestamp
        rules are unchanged — they identify the signer and rebuild the
        signed message)."""
        if self.flag not in (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL):
            raise ValueError(f"unknown CommitSig flag {self.flag}")
        if self.is_absent():
            if self.validator_address or self.signature or self.timestamp_ns:
                raise ValueError("absent CommitSig must be empty")
        else:
            if len(self.validator_address) != 20:
                raise ValueError("bad validator address size")
            if aggregate:
                if self.signature:
                    raise ValueError(
                        "CommitSig inside an aggregate commit must not carry "
                        "a per-validator signature"
                    )
            elif not self.signature or len(self.signature) > 96:
                raise ValueError("bad signature size")


def _decode_timestamp(data: bytes) -> int:
    r = pe.Reader(data)
    seconds = nanos = 0
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            seconds = r.read_uvarint()
        elif f == 2:
            nanos = r.read_uvarint()
        else:
            r.skip(wt)
    return seconds * 1_000_000_000 + nanos


@dataclass(frozen=True)
class Commit:
    """+2/3 precommits for a block (reference types/block.go Commit).
    signatures[i] corresponds to validator i of the signing set.

    Aggregate wire variant (the BLS commit path): `agg_sig` holds ONE
    96-byte G2 aggregate of every non-absent precommit signature, and
    the per-validator CommitSigs keep only flag/address/timestamp —
    the flags ARE the signer bitmap (absent vs commit vs nil), the
    timestamps rebuild each signer's distinct sign-bytes. A
    150-validator commit shrinks from ~150 x 96 signature bytes to one,
    at the cost of pairing-heavy verification (the arXiv:2302.00418
    trade). Conversion is pure data transformation (`aggregate_commit`
    below): BLS signatures aggregate publicly, so the proposer
    aggregates the very sigs the validators gossiped."""

    height: int
    round: int
    block_id: BlockID
    signatures: tuple[CommitSig, ...]
    agg_sig: bytes = b""

    def is_aggregate(self) -> bool:
        return bool(self.agg_sig)

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Rebuild the canonical sign-bytes of validator idx's precommit
        (reference types/block.go:816 → vote.go:93). This is host-side work
        feeding the TPU batch verifier."""
        cs = self.signatures[idx]
        return vote_sign_bytes(
            chain_id,
            SignedMsgType.PRECOMMIT,
            self.height,
            self.round,
            cs.block_id(self.block_id),
            cs.timestamp_ns,
        )

    def hash(self) -> bytes:
        leaves = [cs.encode() for cs in self.signatures]
        if self.agg_sig:
            # the aggregate is commit content: two commits differing
            # only in agg_sig must hash differently
            leaves.append(self.agg_sig)
        return merkle.hash_from_byte_slices(leaves)

    def size(self) -> int:
        return len(self.signatures)

    def encode(self) -> bytes:
        out = pe.sfixed64_field(1, self.height)
        out += pe.sfixed64_field(2, self.round)
        out += pe.message_field(3, self.block_id.encode())
        for cs in self.signatures:
            out += pe.message_field(4, cs.encode())
        if self.agg_sig:
            out += pe.bytes_field(5, self.agg_sig)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Commit":
        r = pe.Reader(data)
        height = round_ = 0
        block_id = NIL_BLOCK_ID
        sigs: list[CommitSig] = []
        agg_sig = b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                height = r.read_sfixed64()
            elif f == 2:
                round_ = r.read_sfixed64()
            elif f == 3:
                block_id = BlockID.decode(r.read_bytes())
            elif f == 4:
                sigs.append(CommitSig.decode(r.read_bytes()))
                if len(sigs) > MAX_WIRE_COMMIT_SIGS:
                    raise ValueError(
                        f"commit signatures exceed {MAX_WIRE_COMMIT_SIGS}"
                    )
            elif f == 5:
                agg_sig = r.read_bytes()
            else:
                r.skip(wt)
        return cls(height, round_, block_id, tuple(sigs), agg_sig)

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative commit height")
        if self.agg_sig and len(self.agg_sig) != 96:
            raise ValueError("bad aggregate signature size")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            aggregate = self.is_aggregate()
            participating = 0
            for cs in self.signatures:
                cs.validate_basic(aggregate=aggregate)
                if not cs.is_absent():
                    participating += 1
            if aggregate and participating == 0:
                raise ValueError("aggregate commit with no participating signers")


def aggregate_commit(commit: Commit, vals) -> Commit:
    """Convert a fully-signed commit into the aggregate wire variant:
    every non-absent precommit signature (commit AND nil votes — both
    are part of the attested history) folds into one G2 aggregate, and
    the per-validator entries keep flag/address/timestamp only.

    Pure data transformation — BLS signatures aggregate publicly, no
    re-signing. Raises ValueError when any participating signer's key
    is not BLS (mixed-scheme sets keep the per-sig wire form; the
    caller falls back) or when the commit is unsigned. Deterministic:
    the aggregate is a fixed-index-order point sum, so same votes in =>
    byte-identical aggregate commit out (the chaos bit-reproducibility
    surface)."""
    from ..crypto import bls

    if commit.is_aggregate():
        return commit
    sigs: list[bytes] = []
    stripped: list[CommitSig] = []
    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            stripped.append(cs)
            continue
        val = vals.get_by_index(idx)
        if val is None or val.pub_key.TYPE != bls.KEY_TYPE:
            raise ValueError(
                f"cannot aggregate commit: validator {idx} is not bls12381"
            )
        sigs.append(cs.signature)
        stripped.append(replace(cs, signature=b""))
    if not sigs:
        raise ValueError("cannot aggregate a commit with no signatures")
    return replace(
        commit,
        signatures=tuple(stripped),
        agg_sig=bls.aggregate_signatures(sigs),
    )


@dataclass(frozen=True)
class Header:
    """Block header (reference types/block.go Header). hash() is the merkle
    root of the deterministic encodings of the 14 fields."""

    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""
    version: int = 11  # block protocol version (reference version/version.go:27)

    def hash(self) -> bytes:
        """Merkle root over the 14 proto-encoded header fields, byte-exact
        with the reference (types/block.go headerHash region: each field
        runs through cdcEncode — a single-field proto wrapper with
        default-elision — before hashing; Version/Time/LastBlockID are
        their proto messages). Frozen against reference-produced vectors
        in tests/test_light_mbt.py and tests/test_golden_vectors.py."""
        if not self.validators_hash:
            return b""
        cached = self.__dict__.get("_hash")
        if cached is not None:
            return cached

        def cdc(b: bytes) -> bytes:  # gogotypes.BytesValue, empty -> nil
            return pe.bytes_field(1, b)

        fields = [
            pe.varint_field(1, self.version),  # Consensus{block}; app=0 elided
            pe.string_field(1, self.chain_id),
            pe.varint_field(1, self.height),
            encode_timestamp(self.time_ns),
            self.last_block_id.encode(),
            cdc(self.last_commit_hash),
            cdc(self.data_hash),
            cdc(self.validators_hash),
            cdc(self.next_validators_hash),
            cdc(self.consensus_hash),
            cdc(self.app_hash),
            cdc(self.last_results_hash),
            cdc(self.evidence_hash),
            cdc(self.proposer_address),
        ]
        # memoized on the frozen instance: consensus, gossip keying,
        # stores, and light verification all re-ask for the same header
        # hash; the fields can't change, so the root can't either
        root = merkle.hash_from_byte_slices(fields)
        self.__dict__["_hash"] = root
        return root

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.version)
        out += pe.string_field(2, self.chain_id)
        out += pe.varint_field(3, self.height)
        out += pe.message_field(4, encode_timestamp(self.time_ns))
        out += pe.message_field(5, self.last_block_id.encode())
        out += pe.bytes_field(6, self.last_commit_hash)
        out += pe.bytes_field(7, self.data_hash)
        out += pe.bytes_field(8, self.validators_hash)
        out += pe.bytes_field(9, self.next_validators_hash)
        out += pe.bytes_field(10, self.consensus_hash)
        out += pe.bytes_field(11, self.app_hash)
        out += pe.bytes_field(12, self.last_results_hash)
        out += pe.bytes_field(13, self.evidence_hash)
        out += pe.bytes_field(14, self.proposer_address)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Header":
        r = pe.Reader(data)
        kw = {}
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kw["version"] = r.read_uvarint()
            elif f == 2:
                kw["chain_id"] = r.read_bytes().decode()
            elif f == 3:
                kw["height"] = r.read_uvarint()
            elif f == 4:
                kw["time_ns"] = _decode_timestamp(r.read_bytes())
            elif f == 5:
                kw["last_block_id"] = BlockID.decode(r.read_bytes())
            elif f == 6:
                kw["last_commit_hash"] = r.read_bytes()
            elif f == 7:
                kw["data_hash"] = r.read_bytes()
            elif f == 8:
                kw["validators_hash"] = r.read_bytes()
            elif f == 9:
                kw["next_validators_hash"] = r.read_bytes()
            elif f == 10:
                kw["consensus_hash"] = r.read_bytes()
            elif f == 11:
                kw["app_hash"] = r.read_bytes()
            elif f == 12:
                kw["last_results_hash"] = r.read_bytes()
            elif f == 13:
                kw["evidence_hash"] = r.read_bytes()
            elif f == 14:
                kw["proposer_address"] = r.read_bytes()
            else:
                r.skip(wt)
        return cls(**kw)

    def validate_basic(self) -> None:
        if not self.chain_id or len(self.chain_id) > 50:
            raise ValueError("bad chain id")
        if self.height <= 0:
            raise ValueError("non-positive header height")
        if self.proposer_address and len(self.proposer_address) != 20:
            raise ValueError("bad proposer address")


def txs_hash(txs: tuple[bytes, ...]) -> bytes:
    return merkle.hash_from_byte_slices(list(txs))


@dataclass(frozen=True)
class Block:
    header: Header
    txs: tuple[bytes, ...] = ()
    evidence: tuple = ()
    last_commit: Commit | None = None

    def hash(self) -> bytes:
        return self.header.hash()

    def txs_hash(self) -> bytes:
        """Tx merkle root, memoized on the frozen block (the same
        shape as Header.hash()): the proposer computes it building the
        header and every validator recomputes it in validate_basic —
        one tree build per Block instance is enough."""
        cached = self.__dict__.get("_txs_hash")
        if cached is None:
            cached = txs_hash(self.txs)
            self.__dict__["_txs_hash"] = cached
        return cached

    def block_id(self, part_set_header: PartSetHeader) -> BlockID:
        return BlockID(self.hash(), part_set_header)

    def make_part_set(self, part_size: int | None = None):
        """Split into 64KB merkle-proved parts (reference
        types/block.go MakePartSet)."""
        from .part_set import BLOCK_PART_SIZE, PartSet

        return PartSet.from_data(self.encode(), part_size or BLOCK_PART_SIZE)

    def encode(self) -> bytes:
        out = pe.message_field(1, self.header.encode())
        for tx in self.txs:
            out += pe.message_field(2, tx)
        if self.last_commit is not None:
            out += pe.message_field(3, self.last_commit.encode())
        for ev in self.evidence:
            out += pe.message_field(4, ev.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        from .evidence import decode_evidence

        r = pe.Reader(data)
        header = Header()
        txs: list[bytes] = []
        last_commit = None
        evidence: list = []
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                header = Header.decode(r.read_bytes())
            elif f == 2:
                txs.append(r.read_bytes())
                if len(txs) > MAX_WIRE_BLOCK_TXS:
                    raise ValueError(f"block txs exceed {MAX_WIRE_BLOCK_TXS}")
            elif f == 3:
                last_commit = Commit.decode(r.read_bytes())
            elif f == 4:
                evidence.append(decode_evidence(r.read_bytes()))
                if len(evidence) > MAX_WIRE_BLOCK_EVIDENCE:
                    raise ValueError(
                        f"block evidence exceeds {MAX_WIRE_BLOCK_EVIDENCE}"
                    )
            else:
                r.skip(wt)
        return cls(header, tuple(txs), tuple(evidence), last_commit)

    def validate_basic(self) -> None:
        self.header.validate_basic()
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("block above height 1 must carry LastCommit")
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("last_commit_hash mismatch")
        if self.header.data_hash != self.txs_hash():
            raise ValueError("data_hash mismatch")
