"""Canonical sign-bytes construction.

The bytes a validator signs must be identical across every implementation
that ever validates them, so they are built here with the deterministic
encoder and never from in-memory object reprs. Height and round are encoded
as sfixed64 (fixed width) — same rationale as the reference
(types/canonical.go:56): HSM signers do cross-height comparison on raw
bytes, so variable-length encodings are ruled out.

Layout (field numbers):

  CanonicalVote / CanonicalProposal:
    1: type (varint)         2: height (sfixed64)    3: round (sfixed64)
    4: block_id (msg)        5: pol_round (sfixed64, proposal only — shifts
                                vote field numbers by one: vote timestamp=5,
                                chain_id=6; proposal timestamp=6, chain_id=7)
  CanonicalBlockID:  1: hash (bytes)  2: part_set_header (msg)
  CanonicalPartSetHeader: 1: total (varint)  2: hash (bytes)
  Timestamp: 1: seconds (varint)  2: nanos (varint)

The result is length-prefixed (the signed message is the framed encoding).

Aggregate commits (types/block.py) deliberately do NOT introduce a new
canonical form: each signer of an aggregate commit signed exactly the
per-validator `vote_sign_bytes` above (distinct timestamps => distinct
messages), and the aggregate is a public point-sum of those signatures.
Keeping the sign-bytes identical across both commit wire forms is what
makes aggregation a pure data transformation — no re-signing, no HSM
changes, and the per-sig and aggregate verification paths accept
exactly the same signer statements.
"""

from __future__ import annotations

from ..libs import protoenc as pe
from .keys import SignedMsgType

NANOS = 1_000_000_000


def encode_timestamp(ns: int) -> bytes:
    seconds, nanos = divmod(ns, NANOS)
    return pe.varint_field(1, seconds) + pe.varint_field(2, nanos)


def encode_canonical_part_set_header(total: int, hash_: bytes) -> bytes:
    return pe.varint_field(1, total) + pe.bytes_field(2, hash_)


def encode_canonical_block_id(block_id) -> bytes | None:
    """None for nil/absent block IDs (field omitted entirely)."""
    if block_id is None or block_id.is_nil():
        return None
    return pe.bytes_field(1, block_id.hash) + pe.message_field(
        2,
        encode_canonical_part_set_header(
            block_id.part_set_header.total, block_id.part_set_header.hash
        ),
    )


def vote_sign_bytes(
    chain_id: str,
    msg_type: SignedMsgType,
    height: int,
    round_: int,
    block_id,
    timestamp_ns: int,
) -> bytes:
    out = pe.varint_field(1, int(msg_type))
    out += pe.sfixed64_field(2, height)
    out += pe.sfixed64_field(3, round_)
    cbid = encode_canonical_block_id(block_id)
    if cbid is not None:
        out += pe.message_field(4, cbid)
    out += pe.message_field(5, encode_timestamp(timestamp_ns))
    out += pe.string_field(6, chain_id)
    return pe.len_prefixed(out)


def strip_timestamp(sign_bytes: bytes, field: int = 5) -> tuple[bytes, int]:
    """Canonical sign-bytes with the timestamp field removed (field 5 for
    votes, 6 for proposals); returns (stripped, timestamp_ns). Used by
    privval to allow re-signing messages that differ only in their
    timestamp (reference privval/file.go
    checkVotesOnlyDifferByTimestamp)."""
    r = pe.Reader(sign_bytes)
    inner = pe.Reader(r.read_bytes())  # drop the length prefix
    out = b""
    ts_ns = 0
    while not inner.eof():
        start = inner.pos
        f, wt = inner.read_tag()
        if f == field:
            tr = pe.Reader(inner.read_bytes())
            seconds = nanos = 0
            while not tr.eof():
                tf, twt = tr.read_tag()
                if tf == 1:
                    seconds = tr.read_uvarint()
                elif tf == 2:
                    nanos = tr.read_uvarint()
                else:
                    tr.skip(twt)
            ts_ns = seconds * NANOS + nanos
            continue
        inner.skip(wt)
        out += inner.data[start : inner.pos]
    return out, ts_ns


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id,
    timestamp_ns: int,
) -> bytes:
    out = pe.varint_field(1, int(SignedMsgType.PROPOSAL))
    out += pe.sfixed64_field(2, height)
    out += pe.sfixed64_field(3, round_)
    out += pe.sfixed64_field(4, pol_round if pol_round >= 0 else -1)
    cbid = encode_canonical_block_id(block_id)
    if cbid is not None:
        out += pe.message_field(5, cbid)
    out += pe.message_field(6, encode_timestamp(timestamp_ns))
    out += pe.string_field(7, chain_id)
    return pe.len_prefixed(out)
