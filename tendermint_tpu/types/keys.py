"""Shared enums and constants for the domain types."""

from __future__ import annotations

import enum


class SignedMsgType(enum.IntEnum):
    UNKNOWN = 0
    PREVOTE = 1
    PRECOMMIT = 2
    PROPOSAL = 32


# CommitSig block-id flags (reference types/block.go BlockIDFlag)
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3

# 64 KB block parts (reference types/params.go:21 BlockPartSizeBytes)
BLOCK_PART_SIZE = 65536

MAX_TOTAL_VOTING_POWER = 2**63 // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2
