"""Consensus parameters (reference types/params.go): per-chain limits the
application can tune via EndBlock updates, hashed into each header's
consensus_hash."""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crypto.hashes import sha256
from ..libs import protoenc as pe

# Wire-side sanity bound: params ride untrusted statesync frames — a
# corrupt repeat count must raise, never allocate (tmtlint wire-bounds).
# The key-type registry has single digits of schemes.
MAX_PUB_KEY_TYPES = 64


@dataclass(frozen=True)
class BlockParams:
    max_bytes: int = 22_020_096  # 21 MB
    max_gas: int = -1


@dataclass(frozen=True)
class EvidenceParams:
    max_age_num_blocks: int = 100_000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1_048_576


@dataclass(frozen=True)
class ValidatorParams:
    pub_key_types: tuple[str, ...] = ("ed25519",)


@dataclass(frozen=True)
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)

    def hash(self) -> bytes:
        return sha256(self.encode())  # tmtlint: allow[hash-chokepoint] -- one cold digest per params update, nothing to batch or account

    def encode(self) -> bytes:
        b = pe.varint_field(1, self.block.max_bytes) + pe.sfixed64_field(
            2, self.block.max_gas
        )
        e = (
            pe.varint_field(1, self.evidence.max_age_num_blocks)
            + pe.varint_field(2, self.evidence.max_age_duration_ns)
            + pe.varint_field(3, self.evidence.max_bytes)
        )
        v = b"".join(pe.string_field(1, t) for t in self.validator.pub_key_types)
        return (
            pe.message_field(1, b) + pe.message_field(2, e) + pe.message_field(3, v)
        )

    @classmethod
    def decode(cls, data: bytes) -> "ConsensusParams":
        r = pe.Reader(data)
        block, ev, val = BlockParams(), EvidenceParams(), ValidatorParams()
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                rr = pe.Reader(r.read_bytes())
                mb, mg = 0, 0
                while not rr.eof():
                    ff, wwt = rr.read_tag()
                    if ff == 1:
                        mb = rr.read_uvarint()
                    elif ff == 2:
                        mg = rr.read_sfixed64()
                    else:
                        rr.skip(wwt)
                block = BlockParams(mb, mg)
            elif f == 2:
                rr = pe.Reader(r.read_bytes())
                ab, ad, mb = 0, 0, 0
                while not rr.eof():
                    ff, wwt = rr.read_tag()
                    if ff == 1:
                        ab = rr.read_uvarint()
                    elif ff == 2:
                        ad = rr.read_uvarint()
                    elif ff == 3:
                        mb = rr.read_uvarint()
                    else:
                        rr.skip(wwt)
                ev = EvidenceParams(ab, ad, mb)
            elif f == 3:
                rr = pe.Reader(r.read_bytes())
                types = []
                while not rr.eof():
                    ff, wwt = rr.read_tag()
                    if ff == 1:
                        types.append(rr.read_bytes().decode())
                        if len(types) > MAX_PUB_KEY_TYPES:
                            raise ValueError(
                                f"pub_key_types exceed {MAX_PUB_KEY_TYPES}"
                            )
                    else:
                        rr.skip(wwt)
                val = ValidatorParams(tuple(types))
            else:
                r.skip(wt)
        return cls(block, ev, val)

    def validate_basic(self) -> None:
        if self.block.max_bytes <= 0:
            raise ValueError("block.max_bytes must be positive")
        if self.block.max_gas < -1:
            raise ValueError("block.max_gas must be >= -1")
        if not self.validator.pub_key_types:
            raise ValueError("no allowed pubkey types")

    def update(self, **kwargs) -> "ConsensusParams":
        return replace(self, **kwargs)
