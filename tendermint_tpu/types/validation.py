"""Commit verification — THE hot entry point of the framework (analog of
reference types/validation.go:25-265).

Three variants, all funneling every signature in a Commit into one
BatchVerifier call (one TPU kernel launch):

  verify_commit              — full validation: every non-absent signature
                               must verify (commit AND nil votes); tallied
                               power counts only votes for the block.
  verify_commit_light        — only signatures for the committed block are
                               verified; returns as soon as +2/3 is reached.
  verify_commit_light_trusting — light-client skipping verification: looks
                               validators up by address in the *trusted* set
                               and requires `trust_level` (default 1/3) of
                               its total power.

Batch verification engages when the key type supports it and there are at
least BATCH_VERIFY_THRESHOLD signatures (reference types/validation.go:12);
otherwise single verification. On batch failure the per-signature bitmap
pinpoints the offending signature for the error message.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction

from ..crypto import batch as crypto_batch
from .block import BlockID, Commit
from .validator_set import ValidatorSet

BATCH_VERIFY_THRESHOLD = 2


class InvalidCommitError(ValueError):
    pass


class _CommitVerifier:
    """Batch-verifier shim for the verify_commit* funnel: routes the
    collected signatures through the node's VerifyHub when one is
    running (cross-subsystem micro-batching + gossip-duplicate dedup),
    and otherwise through the local `create_batch_verifier` path — the
    verdicts are identical, the hub only changes where/when the batch
    launches. `lane` picks the hub scheduler lane: block-sync /
    state-sync / light-client callers submit as "backfill" so bulk
    catch-up ranges never starve live consensus."""

    def __init__(self, pub_key, lane: str = "live"):
        self._pub_key = pub_key
        self._lane = lane
        self._items: list[tuple] = []

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        from ..crypto.verify_hub import logger, running_hub

        hub = running_hub()
        if hub is not None:
            try:
                results = hub.verify_many(self._items, lane=self._lane)
                return all(results) and bool(results), results
            except Exception as e:  # noqa: BLE001 — stall/shutdown races
                # same contract as verify_one: a wedged hub costs
                # latency, never a spurious commit-verification failure
                logger.warning(
                    "hub verify_many failed (%r); verifying %d sigs locally",
                    e,
                    len(self._items),
                )
        bv = crypto_batch.create_batch_verifier(self._pub_key)
        for pk, msg, sig in self._items:
            bv.add(pk, msg, sig)
        return bv.verify()


def _basic_commit_checks(
    vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    if commit.height != height:
        raise InvalidCommitError(f"commit height {commit.height} != {height}")
    if commit.block_id != block_id:
        raise InvalidCommitError("commit is for a different block")
    if len(vals) != commit.size():
        raise InvalidCommitError(
            f"validator set size {len(vals)} != commit size {commit.size()}"
        )


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return commit.size() >= BATCH_VERIFY_THRESHOLD and all(
        crypto_batch.supports_batch_verifier(v.pub_key) for v in vals.validators
    )


def verify_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
    *,
    lane: str = "live",
) -> None:
    """Full commit verification (reference types/validation.go:25).
    Raises InvalidCommitError on failure."""
    _basic_commit_checks(vals, block_id, height, commit)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    _verify(
        chain_id,
        vals,
        commit,
        voting_power_needed,
        count_all_signatures=True,
        lookup_by_index=True,
        lane=lane,
    )


def verify_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
    *,
    lane: str = "live",
) -> None:
    """Verify only the signatures for the committed block, stopping at +2/3
    (reference types/validation.go:59) — the block-sync/light-client path."""
    _basic_commit_checks(vals, block_id, height, commit)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    _verify(
        chain_id,
        vals,
        commit,
        voting_power_needed,
        count_all_signatures=False,
        lookup_by_index=True,
        lane=lane,
    )


def verify_commit_light_trusting(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    trust_level: Fraction = Fraction(1, 3),
    *,
    lane: str = "live",
) -> None:
    """Light-client skipping verification against a *trusted* validator set
    (reference types/validation.go:94): validators are matched by address
    (the untrusted set may have rotated), and `trust_level` of the trusted
    power must have signed."""
    if trust_level.numerator * 3 < trust_level.denominator or trust_level > 1:
        raise ValueError("trust level must be in [1/3, 1]")
    total = vals.total_voting_power()
    voting_power_needed = total * trust_level.numerator // trust_level.denominator
    _verify(
        chain_id,
        vals,
        commit,
        voting_power_needed,
        count_all_signatures=False,
        lookup_by_index=False,
        lane=lane,
    )


def _verify(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    count_all_signatures: bool,
    lookup_by_index: bool,
    lane: str = "live",
) -> None:
    if commit.is_aggregate():
        _verify_aggregate(
            chain_id, vals, commit, voting_power_needed, lookup_by_index
        )
    elif _should_batch_verify(vals, commit):
        _verify_batch(
            chain_id, vals, commit, voting_power_needed, count_all_signatures,
            lookup_by_index, lane=lane,
        )
    else:
        _verify_single(
            chain_id, vals, commit, voting_power_needed, count_all_signatures,
            lookup_by_index, lane=lane,
        )


def _iter_entries(vals: ValidatorSet, commit: Commit, lookup_by_index: bool):
    """Yield (idx, commit_sig, validator) for signatures that participate.
    Absent sigs never participate; with address lookup (trusting mode),
    unknown validators are skipped and double-signing addresses rejected."""
    seen: set[bytes] = set()
    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        if lookup_by_index:
            val = vals.get_by_index(idx)
            if val is None:
                raise InvalidCommitError(f"no validator at index {idx}")
        else:
            _, val = vals.get_by_address(cs.validator_address)
            if val is None:
                continue
            if cs.validator_address in seen:
                raise InvalidCommitError("double vote from same address")
            seen.add(cs.validator_address)
        yield idx, cs, val


def _verify_aggregate(
    chain_id, vals, commit, voting_power_needed, lookup_by_index,
) -> None:
    """Aggregate-commit verification: ONE pairing-product check covers
    every non-absent signer (commit AND nil votes — the aggregate is
    indivisible, so light semantics cannot skip nil signatures; the
    tally still counts only block votes). Routed through the
    crypto/verify_hub.verify_aggregate chokepoint (verdict cache +
    device routing + breaker). Accept/reject surface matches the
    per-signature paths: a forged signer, a wrong bitmap flag, or a
    non-BLS key in an included slot all reject."""
    from ..crypto.bls import KEY_TYPE as BLS_KEY_TYPE
    from ..crypto.verify_hub import verify_aggregate

    tallied = 0
    pubs = []
    msgs = []
    seen: set[bytes] = set()
    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        if lookup_by_index:
            val = vals.get_by_index(idx)
            if val is None:
                raise InvalidCommitError(f"no validator at index {idx}")
        else:
            # trusting mode: EVERY included signer must resolve in the
            # trusted set — an aggregate cannot be verified minus the
            # signers the light client doesn't know
            _, val = vals.get_by_address(cs.validator_address)
            if val is None:
                raise InvalidCommitError(
                    f"aggregate commit signer at index {idx} unknown to the "
                    "trusted validator set (aggregate cannot be partially "
                    "verified)"
                )
            if cs.validator_address in seen:
                raise InvalidCommitError("double vote from same address")
            seen.add(cs.validator_address)
        if val.pub_key.TYPE != BLS_KEY_TYPE:
            raise InvalidCommitError(
                f"aggregate commit includes non-BLS signer at index {idx}"
            )
        if cs.signature:
            raise InvalidCommitError(
                f"aggregate commit carries a per-validator signature at "
                f"index {idx}"
            )
        pubs.append(val.pub_key)
        msgs.append(commit.vote_sign_bytes(chain_id, idx))
        if cs.is_commit():
            tallied += val.voting_power
    if tallied <= voting_power_needed:
        raise InvalidCommitError(
            f"insufficient voting power: got {tallied}, need > {voting_power_needed}"
        )
    if not pubs:
        raise InvalidCommitError("no signatures to verify")
    if not verify_aggregate(pubs, msgs, commit.agg_sig):
        raise InvalidCommitError("aggregate signature verification failed")


def _verify_batch(
    chain_id, vals, commit, voting_power_needed, count_all_signatures,
    lookup_by_index, lane="live",
) -> None:
    bv = _CommitVerifier(vals.validators[0].pub_key, lane=lane)
    tallied = 0
    added = 0
    entries = []
    for idx, cs, val in _iter_entries(vals, commit, lookup_by_index):
        if not count_all_signatures and not cs.is_commit():
            continue
        bv.add(val.pub_key, commit.vote_sign_bytes(chain_id, idx), cs.signature)
        added += 1
        entries.append((idx, cs, val))
        if cs.is_commit():
            tallied += val.voting_power
        # early cut-off: beyond +2/3 no further signatures are needed
        if not count_all_signatures and tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise InvalidCommitError(
            f"insufficient voting power: got {tallied}, need > {voting_power_needed}"
        )
    if added == 0:
        raise InvalidCommitError("no signatures to verify")
    ok, bitmap = bv.verify()
    if not ok:
        for (idx, _, _), good in zip(entries, bitmap):
            if not good:
                raise InvalidCommitError(f"invalid signature at index {idx}")
        raise InvalidCommitError("batch verification failed")


def verify_commit_range(
    chain_id: str,
    entries: list[tuple[ValidatorSet, BlockID, int, Commit]],
    *,
    lane: str = "backfill",
) -> None:
    """Cross-commit mega-batching (SURVEY.md §5 "long-context" analog):
    verify a RANGE of commits — e.g. a block-sync window — in ONE batch
    verifier call, so hundreds of heights' signatures form a single TPU
    kernel launch instead of one launch per block.

    Each entry is (validator_set, block_id, height, commit), light
    semantics per commit (+2/3 of block signatures, early cut-off). On a
    batch failure, falls back to per-commit verification to pinpoint the
    offender — so the error surface matches verify_commit_light called
    per entry. Raises InvalidCommitError carrying `failed_index` (the
    entry index) on failure."""
    if not entries:
        return
    # the verifier is created LAZILY, from the first batchable entry: a
    # mixed ed25519+secp256k1 validator set routes every commit through
    # the individual path below, and eagerly keying the verifier off
    # validators[0] crashed whenever address ordering put a secp256k1
    # key first (seen as a restarted node's block-sync dying mid-e2e)
    bv = None
    added_any = False
    for ei, (vals, block_id, height, commit) in enumerate(entries):
        try:
            _basic_commit_checks(vals, block_id, height, commit)
            if commit.is_aggregate() or not _should_batch_verify(vals, commit):
                # aggregate commits are one indivisible pairing product
                # (verdict-cached in the hub); mixed/secp256k1 sets
                # verify individually
                verify_commit_light(
                    chain_id, vals, block_id, height, commit, lane=lane
                )
                continue
            if bv is None:
                bv = _CommitVerifier(vals.validators[0].pub_key, lane=lane)
            voting_power_needed = vals.total_voting_power() * 2 // 3
            tallied = 0
            for idx, cs, val in _iter_entries(vals, commit, lookup_by_index=True):
                if not cs.is_commit():
                    continue
                bv.add(val.pub_key, commit.vote_sign_bytes(chain_id, idx), cs.signature)
                added_any = True
                tallied += val.voting_power
                if tallied > voting_power_needed:
                    break
            if tallied <= voting_power_needed:
                raise InvalidCommitError(
                    f"insufficient voting power at height {height}: "
                    f"got {tallied}, need > {voting_power_needed}"
                )
        except InvalidCommitError as e:
            e.failed_index = ei
            raise
    if not added_any:
        return
    ok, _bitmap = bv.verify()
    if ok:
        return
    # locate the offending commit: per-commit fallback
    for ei, (vals, block_id, height, commit) in enumerate(entries):
        try:
            verify_commit_light(chain_id, vals, block_id, height, commit, lane=lane)
        except InvalidCommitError as e:
            e.failed_index = ei
            raise
    raise InvalidCommitError("range batch failed but all commits verify singly")


def _verify_single(
    chain_id, vals, commit, voting_power_needed, count_all_signatures,
    lookup_by_index, lane="live",
) -> None:
    from ..crypto.verify_hub import verify_one

    tallied = 0
    for idx, cs, val in _iter_entries(vals, commit, lookup_by_index):
        if not count_all_signatures and not cs.is_commit():
            continue
        if not verify_one(
            val.pub_key, commit.vote_sign_bytes(chain_id, idx), cs.signature,
            lane=lane,
        ):
            raise InvalidCommitError(f"invalid signature at index {idx}")
        if cs.is_commit():
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            return
    if tallied <= voting_power_needed:
        raise InvalidCommitError(
            f"insufficient voting power: got {tallied}, need > {voting_power_needed}"
        )
