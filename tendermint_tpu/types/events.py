"""EventBus: typed event publication over pubsub (reference
types/event_bus.go:35, types/events.go).

Consensus and the block executor publish here; the indexer and RPC
websocket subscribers consume. Composite event keys follow the reference:
`tm.event` plus per-ABCI-event `type.attr` keys."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ..libs.pubsub import PubSub, Query, Subscription

# canonical tm.event values (reference types/events.go)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_VOTE = "Vote"
EVENT_POLKA = "Polka"
EVENT_LOCK = "Lock"
EVENT_UNLOCK = "Unlock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_BLOCK_SYNC_STATUS = "BlockSyncStatus"
EVENT_STATE_SYNC_STATUS = "StateSyncStatus"

TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


def query_for_event(event: str) -> Query:
    return Query.parse(f"{TYPE_KEY}='{event}'")


@dataclass
class EventDataNewBlock:
    block: Any
    result_begin_block: Any = None
    result_end_block: Any = None


@dataclass
class EventDataNewBlockHeader:
    header: Any
    num_txs: int = 0
    result_begin_block: Any = None
    result_end_block: Any = None


@dataclass
class EventDataTx:
    height: int
    tx: bytes
    index: int
    result: Any  # abci.ResponseDeliverTx


@dataclass
class EventDataNewEvidence:
    height: int
    evidence: Any


@dataclass
class EventDataVote:
    vote: Any


@dataclass
class EventDataValidatorSetUpdates:
    validator_updates: list = field(default_factory=list)


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass
class EventDataCompleteProposal:
    height: int
    round: int
    step: str
    block_id: Any = None


def abci_events_to_map(abci_events) -> dict[str, list[str]]:
    """Flatten ABCI events into composite-key map entries (reference
    types/events.go TryUnwrapXXX / indexer key scheme)."""
    out: dict[str, list[str]] = {}
    for ev in abci_events or ():
        for attr in ev.attributes:
            key = f"{ev.type}.{attr.key}"
            out.setdefault(key, []).append(attr.value)
    return out


class EventBus:
    def __init__(self):
        self.pubsub = PubSub()

    def subscribe(
        self, subscriber: str, query: Query, buffer: int = 100,
        drop_on_full: bool = False,
    ) -> Subscription:
        return self.pubsub.subscribe(subscriber, query, buffer, drop_on_full)

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self.pubsub.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self.pubsub.unsubscribe_all(subscriber)

    def _publish(self, event: str, data: Any, extra: dict[str, list[str]] | None = None):
        events = {TYPE_KEY: [event]}
        if extra:
            for k, v in extra.items():
                events.setdefault(k, []).extend(v)
        self.pubsub.publish(data, events)

    def publish_new_block(self, data: EventDataNewBlock) -> None:
        extra = abci_events_to_map(
            tuple(getattr(data.result_begin_block, "events", ()) or ())
            + tuple(getattr(data.result_end_block, "events", ()) or ())
        )
        extra.setdefault(BLOCK_HEIGHT_KEY, []).append(str(data.block.header.height))
        self._publish(EVENT_NEW_BLOCK, data, extra)

    def publish_new_block_header(self, data: EventDataNewBlockHeader) -> None:
        self._publish(
            EVENT_NEW_BLOCK_HEADER,
            data,
            {BLOCK_HEIGHT_KEY: [str(data.header.height)]},
        )

    def publish_tx(self, data: EventDataTx) -> None:
        from ..crypto.hash_hub import sha256_one

        extra = abci_events_to_map(getattr(data.result, "events", ()))
        extra.setdefault(TX_HASH_KEY, []).append(sha256_one(data.tx).hex().upper())
        extra.setdefault(TX_HEIGHT_KEY, []).append(str(data.height))
        self._publish(EVENT_TX, data, extra)

    def publish_new_evidence(self, data: EventDataNewEvidence) -> None:
        self._publish(EVENT_NEW_EVIDENCE, data)

    def publish_vote(self, data: EventDataVote) -> None:
        self._publish(EVENT_VOTE, data)

    def publish_validator_set_updates(self, data: EventDataValidatorSetUpdates) -> None:
        self._publish(EVENT_VALIDATOR_SET_UPDATES, data)

    def publish_new_round(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_NEW_ROUND, data)

    def publish_new_round_step(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_NEW_ROUND_STEP, data)

    def publish_complete_proposal(self, data: EventDataCompleteProposal) -> None:
        self._publish(EVENT_COMPLETE_PROPOSAL, data)

    def publish_polka(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_POLKA, data)

    def publish_lock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_LOCK, data)

    def publish_unlock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_UNLOCK, data)

    def publish_timeout_propose(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_TIMEOUT_PROPOSE, data)

    def publish_timeout_wait(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_TIMEOUT_WAIT, data)
