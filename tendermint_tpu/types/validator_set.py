"""Validator and ValidatorSet with proposer-priority rotation.

Behavioral analog of reference types/validator_set.go (933 LoC): weighted
round-robin proposer selection via accumulating priorities, rescaling to a
2·totalPower window, centering around zero, and the -1.125·totalPower
penalty for newly joining validators. Integer division follows truncation
toward zero (the reference's Go semantics) — Python's floor division would
diverge on negative priorities, so `_div_trunc` is used throughout.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..crypto import PubKey
from ..crypto.merkle import hash_from_byte_slices
from ..libs import protoenc as pe
from .keys import MAX_TOTAL_VOTING_POWER, PRIORITY_WINDOW_SIZE_FACTOR

# Wire-side sanity bound: validator sets ride untrusted frames (light
# blocks, statesync params, evidence) — a corrupt repeat count must
# raise at decode, never allocate (tmtlint wire-bounds). Real
# committees are ≤ a few hundred validators.
MAX_WIRE_VALIDATORS = 1 << 16


def _div_trunc(a: int, b: int) -> int:
    q = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        q = -q
    return q


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0

    @property
    def address(self) -> bytes:
        return self.pub_key.address()

    def copy(self) -> "Validator":
        return Validator(self.pub_key, self.voting_power, self.proposer_priority)

    def simple_encode(self) -> bytes:
        """SimpleValidator proto encoding used for the validator-set hash
        (reference types/validator.go Bytes(): SimpleValidator{PubKey,
        VotingPower}) — byte-exact with the reference; frozen against its
        MBT vectors in tests/test_light_mbt.py."""
        from ..crypto import pubkey_to_proto

        out = pe.message_field(1, pubkey_to_proto(self.pub_key))
        out += pe.varint_field(2, self.voting_power)
        return out

    def encode(self) -> bytes:
        out = self.simple_encode()
        out += pe.sfixed64_field(3, self.proposer_priority)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Validator":
        from .. import crypto

        r = pe.Reader(data)
        pub, power, prio = None, 0, 0
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                pub = crypto.pubkey_from_proto(r.read_bytes())
            elif f == 2:
                power = r.read_uvarint()
            elif f == 3:
                prio = r.read_sfixed64()
            else:
                r.skip(wt)
        if pub is None:
            # fail HERE so the router's decode guard converts it into a
            # peer error, instead of a None pub key detonating later
            # inside reactor logic
            raise ValueError("validator encoding missing public key")
        return cls(pub, power, prio)


class ValidatorSet:
    """Ordered validator set. Order: voting power descending, then address
    ascending — fixed at construction and preserved across priority updates
    (the hash depends on it)."""

    def __init__(self, validators: list[Validator]):
        vals = [v.copy() for v in validators]
        vals.sort(key=lambda v: (-v.voting_power, v.address))
        self.validators = vals
        self._proposer: Validator | None = None
        self._hash: bytes | None = None  # memo; priorities don't affect it
        if self.total_voting_power() > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power exceeds maximum")
        if vals:
            self.increment_proposer_priority(1)

    # -- lookups ---------------------------------------------------------

    def __len__(self) -> int:
        return len(self.validators)

    def get_by_index(self, idx: int) -> Validator | None:
        if 0 <= idx < len(self.validators):
            return self.validators[idx]
        return None

    def get_by_address(self, addr: bytes) -> tuple[int, Validator | None]:
        for i, v in enumerate(self.validators):
            if v.address == addr:
                return i, v
        return -1, None

    def has_address(self, addr: bytes) -> bool:
        return self.get_by_address(addr)[1] is not None

    def total_voting_power(self) -> int:
        return sum(v.voting_power for v in self.validators)

    # -- proposer rotation ----------------------------------------------

    def get_proposer(self) -> Validator:
        if self._proposer is None:
            self._proposer = self._find_proposer()
        return self._proposer

    def _find_proposer(self) -> Validator:
        best = self.validators[0]
        for v in self.validators[1:]:
            if v.proposer_priority > best.proposer_priority or (
                v.proposer_priority == best.proposer_priority and v.address < best.address
            ):
                best = v
        return best

    def rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0 or not self.validators:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        if diff > diff_max:
            ratio = (diff + diff_max - 1) // diff_max
            for v in self.validators:
                v.proposer_priority = _div_trunc(v.proposer_priority, ratio)

    def _shift_by_avg_proposer_priority(self) -> None:
        n = len(self.validators)
        avg = _div_trunc(sum(v.proposer_priority for v in self.validators), n)
        for v in self.validators:
            v.proposer_priority -= avg

    def increment_proposer_priority(self, times: int) -> None:
        """Advance the weighted round-robin `times` steps (reference
        types/validator_set.go:77-109)."""
        if not self.validators:
            return
        total = self.total_voting_power()
        self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * total)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            for v in self.validators:
                v.proposer_priority += v.voting_power
            proposer = self._find_proposer()
            proposer.proposer_priority -= total
        self._proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    def copy(self) -> "ValidatorSet":
        new = object.__new__(ValidatorSet)
        new.validators = [v.copy() for v in self.validators]
        new._proposer = None
        new._hash = self._hash  # same keys/powers -> same hash
        if self._proposer is not None:
            idx, _ = new.get_by_address(self._proposer.address)
            new._proposer = new.validators[idx] if idx >= 0 else None
        return new

    # -- updates ---------------------------------------------------------

    def update_with_change_set(self, changes: list[Validator]) -> None:
        """Apply validator updates from the application: power 0 removes,
        otherwise add/update. New validators join with priority
        -(totalPower + totalPower/8), keeping them from proposing
        immediately (reference types/validator_set.go update path)."""
        by_addr = {v.address: v for v in self.validators}
        seen: set[bytes] = set()
        for c in changes:
            addr = c.address
            if addr in seen:
                raise ValueError("duplicate address in change set")
            seen.add(addr)
            if c.voting_power < 0:
                raise ValueError("negative voting power")
            if c.voting_power == 0:
                if addr not in by_addr:
                    raise ValueError("removing unknown validator")
                del by_addr[addr]
            elif addr in by_addr:
                by_addr[addr].voting_power = c.voting_power
            else:
                by_addr[addr] = Validator(c.pub_key, c.voting_power)
        if not by_addr:
            raise ValueError("validator set cannot become empty")
        new_vals = list(by_addr.values())
        total = sum(v.voting_power for v in new_vals)
        if total > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power exceeds maximum")
        penalty = -(total + _div_trunc(total, 8))
        existing = {v.address for v in self.validators}
        for v in new_vals:
            if v.address not in existing:
                v.proposer_priority = penalty
        new_vals.sort(key=lambda v: (-v.voting_power, v.address))
        self.validators = new_vals
        self._proposer = None
        self._hash = None
        self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * total)
        self._shift_by_avg_proposer_priority()

    # -- hashing / serialization ----------------------------------------

    def hash(self) -> bytes:
        """Merkle root of the simple-encoded validators (reference
        types/validator_set.go:77-109 region). Memoized: the hash covers
        pubkeys + powers only, which change solely through
        update_with_change_set (proposer-priority churn doesn't touch it),
        and hot paths (block-sync rotation guards) call this per block."""
        if self._hash is None:
            self._hash = hash_from_byte_slices(
                [v.simple_encode() for v in self.validators]
            )
        return self._hash

    def encode(self) -> bytes:
        out = b""
        for v in self.validators:
            out += pe.message_field(1, v.encode())
        if self._proposer is not None:
            out += pe.bytes_field(2, self._proposer.address)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorSet":
        r = pe.Reader(data)
        vals: list[Validator] = []
        proposer_addr = b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                vals.append(Validator.decode(r.read_bytes()))
                if len(vals) > MAX_WIRE_VALIDATORS:
                    raise ValueError(
                        f"validator set exceeds {MAX_WIRE_VALIDATORS} entries"
                    )
            elif f == 2:
                proposer_addr = r.read_bytes()
            else:
                r.skip(wt)
        new = object.__new__(cls)
        new.validators = vals
        new._proposer = None
        new._hash = None
        if proposer_addr:
            idx, v = new.get_by_address(proposer_addr)
            new._proposer = v
        return new

    def validate_basic(self) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        seen = set()
        for v in self.validators:
            if v.voting_power <= 0:
                raise ValueError("validator with non-positive power")
            if v.address in seen:
                raise ValueError("duplicate validator address")
            seen.add(v.address)
