"""Evidence of Byzantine behavior (reference types/evidence.go).

DuplicateVoteEvidence: equivocation — two different votes for the same
height/round/type from one validator.

LightClientAttackEvidence (reference types/evidence.go:214): a provider
served a light client a conflicting, properly-signed header. The evidence
carries the whole conflicting light block, the last height at which the
attacked client and the attacker agreed (common height), and the
validators the attack can be attributed to."""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.hashes import sha256
from ..libs import protoenc as pe
from .validator_set import MAX_WIRE_VALIDATORS, Validator, ValidatorSet
from .vote import Vote

EVIDENCE_DUPLICATE_VOTE = 1
EVIDENCE_LIGHT_CLIENT_ATTACK = 2


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int
    validator_power: int
    timestamp_ns: int

    TYPE = EVIDENCE_DUPLICATE_VOTE

    @classmethod
    def from_votes(
        cls, vote_a: Vote, vote_b: Vote, block_time_ns: int, val_set: ValidatorSet
    ) -> "DuplicateVoteEvidence":
        _, val = val_set.get_by_address(vote_a.validator_address)
        if val is None:
            raise ValueError("evidence from validator not in set")
        # deterministic order: lexicographically smaller block key first
        a, b = vote_a, vote_b
        if a.block_id.key() > b.block_id.key():
            a, b = b, a
        return cls(a, b, val_set.total_voting_power(), val.voting_power, block_time_ns)

    @property
    def height(self) -> int:
        return self.vote_a.height

    def hash(self) -> bytes:
        # memoized: the evidence gossip reactor hashes every pending
        # item per peer per broadcast tick (4 Hz) — recomputing
        # encode+sha256 each time is measurable at committee scale.
        # Safe on a frozen dataclass: the fields can never change.
        h = self.__dict__.get("_hash")
        if h is None:
            h = sha256(self.encode())  # tmtlint: allow[hash-chokepoint] -- memoized single digest (one per evidence lifetime), nothing to batch
            object.__setattr__(self, "_hash", h)
        return h

    def encode(self) -> bytes:
        enc = self.__dict__.get("_enc")
        if enc is None:
            enc = (
                pe.varint_field(1, self.TYPE)
                + pe.message_field(2, self.vote_a.encode())
                + pe.message_field(3, self.vote_b.encode())
                + pe.varint_field(4, self.total_voting_power)
                + pe.varint_field(5, self.validator_power)
                + pe.message_field(6, pe.varint_field(1, self.timestamp_ns))
            )
            object.__setattr__(self, "_enc", enc)
        return enc

    @classmethod
    def decode_fields(cls, r: pe.Reader) -> "DuplicateVoteEvidence":
        va = vb = None
        tvp = vp = ts = 0
        while not r.eof():
            f, wt = r.read_tag()
            if f == 2:
                va = Vote.decode(r.read_bytes())
            elif f == 3:
                vb = Vote.decode(r.read_bytes())
            elif f == 4:
                tvp = r.read_uvarint()
            elif f == 5:
                vp = r.read_uvarint()
            elif f == 6:
                rr = pe.Reader(r.read_bytes())
                while not rr.eof():
                    ff, wwt = rr.read_tag()
                    if ff == 1:
                        ts = rr.read_uvarint()
                    else:
                        rr.skip(wwt)
            else:
                r.skip(wt)
        return cls(va, vb, tvp, vp, ts)

    def validate_basic(self) -> None:
        a, b = self.vote_a, self.vote_b
        if a is None or b is None:
            raise ValueError("missing votes")
        a.validate_basic()
        b.validate_basic()
        if (a.height, a.round, a.type) != (b.height, b.round, b.type):
            raise ValueError("votes are not for the same height/round/type")
        if a.validator_address != b.validator_address:
            raise ValueError("votes from different validators")
        if a.block_id == b.block_id:
            raise ValueError("votes are identical — no equivocation")
        if a.block_id.key() > b.block_id.key():
            raise ValueError("votes not in deterministic order")


@dataclass(frozen=True)
class LightClientAttackEvidence:
    """Reference types/evidence.go:214. `conflicting_block` is the forged
    (but properly signed) light block; `common_height` the last height the
    divergent chains agreed at; `byzantine_validators` the validators the
    attack is attributable to (empty for amnesia attacks)."""

    conflicting_block: object  # light.types.LightBlock (lazy to avoid cycle)
    common_height: int
    byzantine_validators: tuple  # tuple[Validator, ...]
    total_voting_power: int
    timestamp_ns: int

    TYPE = EVIDENCE_LIGHT_CLIENT_ATTACK

    @property
    def height(self) -> int:
        # expiry is measured from the common height (evidence.go Height())
        return self.common_height

    @property
    def conflicting_height(self) -> int:
        return self.conflicting_block.height

    def hash(self) -> bytes:
        # header hash + common height: the same attack reported with
        # different byzantine attributions dedupes to one entry.
        # Memoized (the DuplicateVoteEvidence pattern): the gossip
        # reactor hashes every pending item per peer per 4 Hz tick, and
        # an LCA hash covers a whole committee-scale header — safe on a
        # frozen dataclass.
        h = self.__dict__.get("_hash")
        if h is None:
            # tmtlint: allow[hash-chokepoint] -- memoized single digest over two small fields, nothing to batch
            h = sha256(
                self.conflicting_block.header.hash()
                + self.common_height.to_bytes(8, "big")
            )
            object.__setattr__(self, "_hash", h)
        return h

    def conflicting_header_is_invalid(self, trusted_header) -> bool:
        """Lunatic attack: the conflicting header fabricates one of the
        fields that are deterministically derived from state (reference
        evidence.go ConflictingHeaderIsInvalid)."""
        h, t = self.conflicting_block.header, trusted_header
        return not (
            h.validators_hash == t.validators_hash
            and h.next_validators_hash == t.next_validators_hash
            and h.consensus_hash == t.consensus_hash
            and h.app_hash == t.app_hash
            and h.last_results_hash == t.last_results_hash
        )

    def get_byzantine_validators(
        self, common_vals: ValidatorSet, trusted_signed_header
    ) -> list[Validator]:
        """Who to punish (reference evidence.go GetByzantineValidators):
        lunatic → common-set validators who signed the conflicting block;
        equivocation (same round) → validators who signed both blocks;
        amnesia (different rounds) → unattributable, empty."""
        conflicting_commit = self.conflicting_block.signed_header.commit
        out: list[Validator] = []
        if self.conflicting_header_is_invalid(trusted_signed_header.header):
            for sig in conflicting_commit.signatures:
                if not sig.is_commit():
                    continue
                _, val = common_vals.get_by_address(sig.validator_address)
                if val is not None:
                    out.append(val)
        elif trusted_signed_header.commit.round == conflicting_commit.round:
            trusted_signers = {
                s.validator_address
                for s in trusted_signed_header.commit.signatures
                if s.is_commit()
            }
            for sig in conflicting_commit.signatures:
                if not sig.is_commit() or sig.validator_address not in trusted_signers:
                    continue
                _, val = self.conflicting_block.validators.get_by_address(
                    sig.validator_address
                )
                if val is not None:
                    out.append(val)
        out.sort(key=lambda v: (-v.voting_power, v.address))
        return out

    def encode(self) -> bytes:
        # memoized like hash(): an LCA encoding carries the entire
        # conflicting light block (validator set + commit), re-encoded
        # otherwise on every broadcast poll and pool size pass
        enc = self.__dict__.get("_enc")
        if enc is None:
            enc = pe.varint_field(1, self.TYPE)
            enc += pe.message_field(2, self.conflicting_block.encode())
            enc += pe.varint_field(3, self.common_height)
            for val in self.byzantine_validators:
                enc += pe.message_field(4, val.encode())
            enc += pe.varint_field(5, self.total_voting_power)
            enc += pe.message_field(6, pe.varint_field(1, self.timestamp_ns))
            object.__setattr__(self, "_enc", enc)
        return enc

    @classmethod
    def decode_fields(cls, r: pe.Reader) -> "LightClientAttackEvidence":
        from ..light.types import LightBlock

        cb = None
        ch = tvp = ts = 0
        byz: list[Validator] = []
        while not r.eof():
            f, wt = r.read_tag()
            if f == 2:
                cb = LightBlock.decode(r.read_bytes())
            elif f == 3:
                ch = r.read_uvarint()
            elif f == 4:
                byz.append(Validator.decode(r.read_bytes()))
                if len(byz) > MAX_WIRE_VALIDATORS:
                    raise ValueError(
                        f"LCA byzantine validators exceed {MAX_WIRE_VALIDATORS}"
                    )
            elif f == 5:
                tvp = r.read_uvarint()
            elif f == 6:
                rr = pe.Reader(r.read_bytes())
                while not rr.eof():
                    ff, wwt = rr.read_tag()
                    if ff == 1:
                        ts = rr.read_uvarint()
                    else:
                        rr.skip(wwt)
            else:
                r.skip(wt)
        return cls(cb, ch, tuple(byz), tvp, ts)

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise ValueError("missing conflicting block")
        if self.conflicting_block.signed_header is None:
            raise ValueError("conflicting block missing signed header")
        if self.conflicting_block.validators is None:
            raise ValueError("conflicting block missing validator set")
        if self.common_height <= 0:
            raise ValueError("non-positive common height")
        if self.common_height > self.conflicting_block.height:
            raise ValueError("common height beyond conflicting block height")
        if self.total_voting_power <= 0:
            raise ValueError("non-positive total voting power")


def decode_evidence(data: bytes):
    r = pe.Reader(data)
    f, wt = r.read_tag()
    if f != 1 or wt != pe.WIRE_VARINT:
        raise ValueError("evidence missing type tag")
    type_ = r.read_uvarint()
    if type_ == EVIDENCE_DUPLICATE_VOTE:
        return DuplicateVoteEvidence.decode_fields(r)
    if type_ == EVIDENCE_LIGHT_CLIENT_ATTACK:
        return LightClientAttackEvidence.decode_fields(r)
    raise ValueError(f"unknown evidence type {type_}")


def evidence_hash(evidence: tuple) -> bytes:
    from ..crypto import merkle

    return merkle.hash_from_byte_slices([ev.encode() for ev in evidence])
