"""Evidence of Byzantine behavior (reference types/evidence.go).

Round 1 implements DuplicateVoteEvidence (equivocation — two different
votes for the same height/round/type from one validator). Light-client
attack evidence lands with the light-client detector."""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.hashes import sha256
from ..libs import protoenc as pe
from .validator_set import ValidatorSet
from .vote import Vote

EVIDENCE_DUPLICATE_VOTE = 1
EVIDENCE_LIGHT_CLIENT_ATTACK = 2


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int
    validator_power: int
    timestamp_ns: int

    TYPE = EVIDENCE_DUPLICATE_VOTE

    @classmethod
    def from_votes(
        cls, vote_a: Vote, vote_b: Vote, block_time_ns: int, val_set: ValidatorSet
    ) -> "DuplicateVoteEvidence":
        _, val = val_set.get_by_address(vote_a.validator_address)
        if val is None:
            raise ValueError("evidence from validator not in set")
        # deterministic order: lexicographically smaller block key first
        a, b = vote_a, vote_b
        if a.block_id.key() > b.block_id.key():
            a, b = b, a
        return cls(a, b, val_set.total_voting_power(), val.voting_power, block_time_ns)

    @property
    def height(self) -> int:
        return self.vote_a.height

    def hash(self) -> bytes:
        return sha256(self.encode())

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.TYPE)
        out += pe.message_field(2, self.vote_a.encode())
        out += pe.message_field(3, self.vote_b.encode())
        out += pe.varint_field(4, self.total_voting_power)
        out += pe.varint_field(5, self.validator_power)
        out += pe.message_field(6, pe.varint_field(1, self.timestamp_ns))
        return out

    @classmethod
    def decode_fields(cls, r: pe.Reader) -> "DuplicateVoteEvidence":
        va = vb = None
        tvp = vp = ts = 0
        while not r.eof():
            f, wt = r.read_tag()
            if f == 2:
                va = Vote.decode(r.read_bytes())
            elif f == 3:
                vb = Vote.decode(r.read_bytes())
            elif f == 4:
                tvp = r.read_uvarint()
            elif f == 5:
                vp = r.read_uvarint()
            elif f == 6:
                rr = pe.Reader(r.read_bytes())
                while not rr.eof():
                    ff, wwt = rr.read_tag()
                    if ff == 1:
                        ts = rr.read_uvarint()
                    else:
                        rr.skip(wwt)
            else:
                r.skip(wt)
        return cls(va, vb, tvp, vp, ts)

    def validate_basic(self) -> None:
        a, b = self.vote_a, self.vote_b
        if a is None or b is None:
            raise ValueError("missing votes")
        a.validate_basic()
        b.validate_basic()
        if (a.height, a.round, a.type) != (b.height, b.round, b.type):
            raise ValueError("votes are not for the same height/round/type")
        if a.validator_address != b.validator_address:
            raise ValueError("votes from different validators")
        if a.block_id == b.block_id:
            raise ValueError("votes are identical — no equivocation")
        if a.block_id.key() > b.block_id.key():
            raise ValueError("votes not in deterministic order")


def decode_evidence(data: bytes):
    r = pe.Reader(data)
    f, wt = r.read_tag()
    if f != 1 or wt != pe.WIRE_VARINT:
        raise ValueError("evidence missing type tag")
    type_ = r.read_uvarint()
    if type_ == EVIDENCE_DUPLICATE_VOTE:
        return DuplicateVoteEvidence.decode_fields(r)
    raise ValueError(f"unknown evidence type {type_}")


def evidence_hash(evidence: tuple) -> bytes:
    from ..crypto import merkle

    return merkle.hash_from_byte_slices([ev.encode() for ev in evidence])
