"""Genesis document (reference types/genesis.go) — JSON, human-editable."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from .. import crypto
from ..crypto.hashes import sha256
from .params import ConsensusParams, BlockParams, EvidenceParams, ValidatorParams
from .validator_set import Validator


@dataclass
class GenesisValidator:
    pub_key: crypto.PubKey
    power: int
    name: str = ""
    # bls12381 validators MUST carry a proof of possession (a signature
    # over the pubkey bytes under the POP domain tag): aggregate-commit
    # positions are only sound against rogue-key attacks when every
    # aggregated key proved knowledge of its secret. Checked at
    # validator-set construction, not per verification.
    pop: bytes = b""


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b"{}"

    def validator_set(self):
        from .validator_set import ValidatorSet

        self._check_pops()
        return ValidatorSet(
            [Validator(gv.pub_key, gv.power) for gv in self.validators]
        )

    def _check_pops(self) -> None:
        """Rogue-key defense: every bls12381 genesis validator must
        prove possession of its secret key before the set is
        constructed — an unproven key in an aggregate position could be
        a rogue-key combination of honest keys. (Per-validator verify
        results are memoized in crypto/bls, so multi-node in-process
        tests pay the pairing once per key.)"""
        for gv in self.validators:
            if gv.pub_key.TYPE != "bls12381":
                continue
            if not gv.pop:
                raise ValueError(
                    f"bls12381 genesis validator {gv.name or gv.pub_key!r} "
                    "missing proof of possession"
                )
            if not gv.pub_key.pop_verify(gv.pop):
                raise ValueError(
                    f"bls12381 genesis validator {gv.name or gv.pub_key!r} "
                    "has an invalid proof of possession"
                )

    def validate_basic(self) -> None:
        if not self.chain_id or len(self.chain_id) > 50:
            raise ValueError("bad chain id")
        if self.initial_height < 1:
            raise ValueError("initial height must be >= 1")
        self.consensus_params.validate_basic()
        for gv in self.validators:
            if gv.power <= 0:
                raise ValueError("genesis validator with non-positive power")
        self._check_pops()

    def to_json(self) -> str:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "genesis_time_ns": self.genesis_time_ns,
                "initial_height": self.initial_height,
                "consensus_params": {
                    "block": {
                        "max_bytes": self.consensus_params.block.max_bytes,
                        "max_gas": self.consensus_params.block.max_gas,
                    },
                    "evidence": {
                        "max_age_num_blocks": self.consensus_params.evidence.max_age_num_blocks,
                        "max_age_duration_ns": self.consensus_params.evidence.max_age_duration_ns,
                        "max_bytes": self.consensus_params.evidence.max_bytes,
                    },
                    "validator": {
                        "pub_key_types": list(
                            self.consensus_params.validator.pub_key_types
                        )
                    },
                },
                "validators": [
                    {
                        "pub_key_type": gv.pub_key.TYPE,
                        "pub_key": gv.pub_key.bytes().hex(),
                        "power": gv.power,
                        "name": gv.name,
                        **({"pop": gv.pop.hex()} if gv.pop else {}),
                    }
                    for gv in self.validators
                ],
                "app_hash": self.app_hash.hex(),
                "app_state": self.app_state.decode(),
            },
            indent=2,
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, text: str) -> "GenesisDoc":
        d = json.loads(text)
        cp = d.get("consensus_params", {})
        params = ConsensusParams(
            block=BlockParams(**cp.get("block", {})),
            evidence=EvidenceParams(**cp.get("evidence", {})),
            validator=ValidatorParams(
                pub_key_types=tuple(
                    cp.get("validator", {}).get("pub_key_types", ("ed25519",))
                )
            ),
        )
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time_ns=d.get("genesis_time_ns", 0),
            initial_height=d.get("initial_height", 1),
            consensus_params=params,
            validators=[
                GenesisValidator(
                    crypto.pubkey_from_type_and_bytes(
                        v.get("pub_key_type", "ed25519"), bytes.fromhex(v["pub_key"])
                    ),
                    v["power"],
                    v.get("name", ""),
                    bytes.fromhex(v.get("pop", "")),
                )
                for v in d.get("validators", [])
            ],
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=d.get("app_state", "{}").encode(),
        )
        doc.validate_basic()
        return doc

    def hash(self) -> bytes:
        return sha256(self.to_json().encode())  # tmtlint: allow[hash-chokepoint] -- genesis doc hashes once at startup, cold by definition
