"""Vote — a prevote or precommit from a single validator (reference
types/vote.go). Also Proposal, which shares the canonical sign-bytes
machinery."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs import protoenc as pe
from .block import BlockID, NIL_BLOCK_ID, _decode_timestamp
from .canonical import proposal_sign_bytes, vote_sign_bytes, encode_timestamp
from .keys import SignedMsgType


@dataclass(frozen=True)
class Vote:
    type: SignedMsgType
    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, self.timestamp_ns
        )

    def verify(self, chain_id: str, pub_key) -> bool:
        """Single-vote verification (reference types/vote.go:147).
        Routes through the VerifyHub's SYNC facade when one is running.

        Since the pipelined ingest landed this is the *fallback* path:
        peer votes normally arrive at `VoteSet.add_vote` already proven
        by stage 1 of consensus/ingest.py (the async `hub.verify` API,
        many in flight per node) and skip this call entirely. What still
        funnels through here: our own freshly signed votes, the evidence
        pool's checks, replay, and any vote the pipeline could not
        attribute to a validator set. The hub's verdict cache then makes
        a repeat check (the same vote from many peers) free."""
        if pub_key.address() != self.validator_address:
            return False
        from ..crypto.verify_hub import verify_one

        return verify_one(pub_key, self.sign_bytes(chain_id), self.signature)

    def is_nil(self) -> bool:
        return self.block_id.is_nil()

    def encode(self) -> bytes:
        out = pe.varint_field(1, int(self.type))
        out += pe.sfixed64_field(2, self.height)
        out += pe.sfixed64_field(3, self.round)
        out += pe.message_field(4, self.block_id.encode())
        out += pe.message_field(5, encode_timestamp(self.timestamp_ns))
        out += pe.bytes_field(6, self.validator_address)
        out += pe.varint_field(7, self.validator_index + 1)  # +1: index 0 must round-trip
        out += pe.bytes_field(8, self.signature)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        r = pe.Reader(data)
        kw = dict(
            type=SignedMsgType.UNKNOWN,
            height=0,
            round=0,
            block_id=NIL_BLOCK_ID,
            timestamp_ns=0,
            validator_address=b"",
            validator_index=-1,
            signature=b"",
        )
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kw["type"] = SignedMsgType(r.read_uvarint())
            elif f == 2:
                kw["height"] = r.read_sfixed64()
            elif f == 3:
                kw["round"] = r.read_sfixed64()
            elif f == 4:
                kw["block_id"] = BlockID.decode(r.read_bytes())
            elif f == 5:
                kw["timestamp_ns"] = _decode_timestamp(r.read_bytes())
            elif f == 6:
                kw["validator_address"] = r.read_bytes()
            elif f == 7:
                kw["validator_index"] = r.read_uvarint() - 1
            elif f == 8:
                kw["signature"] = r.read_bytes()
            else:
                r.skip(wt)
        return cls(**kw)

    def validate_basic(self) -> None:
        if self.type not in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
            raise ValueError("invalid vote type")
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        self.block_id.validate_basic()
        if len(self.validator_address) != 20:
            raise ValueError("bad validator address")
        if self.validator_index < 0:
            raise ValueError("negative validator index")
        if not self.signature or len(self.signature) > 96:
            raise ValueError("bad signature")


@dataclass(frozen=True)
class Proposal:
    """Block proposal for (height, round) (reference types/proposal.go).
    pol_round is the proof-of-lock round, -1 when unlocked."""

    height: int
    round: int
    pol_round: int
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return proposal_sign_bytes(
            chain_id, self.height, self.round, self.pol_round, self.block_id, self.timestamp_ns
        )

    def encode(self) -> bytes:
        out = pe.sfixed64_field(1, self.height)
        out += pe.sfixed64_field(2, self.round)
        out += pe.sfixed64_field(3, self.pol_round if self.pol_round >= 0 else -1)
        out += pe.message_field(4, self.block_id.encode())
        out += pe.message_field(5, encode_timestamp(self.timestamp_ns))
        out += pe.bytes_field(6, self.signature)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        r = pe.Reader(data)
        kw = dict(height=0, round=0, pol_round=-1, block_id=NIL_BLOCK_ID, timestamp_ns=0, signature=b"")
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kw["height"] = r.read_sfixed64()
            elif f == 2:
                kw["round"] = r.read_sfixed64()
            elif f == 3:
                kw["pol_round"] = r.read_sfixed64()
            elif f == 4:
                kw["block_id"] = BlockID.decode(r.read_bytes())
            elif f == 5:
                kw["timestamp_ns"] = _decode_timestamp(r.read_bytes())
            elif f == 6:
                kw["signature"] = r.read_bytes()
            else:
                r.skip(wt)
        return cls(**kw)

    def validate_basic(self) -> None:
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        if self.pol_round < -1 or self.pol_round >= self.round:
            raise ValueError("invalid pol_round")
        if not self.block_id.is_complete():
            raise ValueError("proposal must carry a complete block id")
        if not self.signature or len(self.signature) > 96:
            raise ValueError("bad signature")
