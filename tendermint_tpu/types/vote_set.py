"""VoteSet — collects votes of one (height, round, type), tracks the 2/3
tally, detects conflicting votes (reference types/vote_set.go).

A vote set accepts at most one vote per validator; a second, different vote
from the same validator is rejected and surfaced as a conflict pair for the
evidence pool. `two_thirds_majority()` returns the BlockID once >2/3 of the
voting power has voted for it.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..libs.bits import BitArray
from .block import BlockID
from .keys import SignedMsgType
from .validator_set import ValidatorSet
from .vote import Vote


class VoteSetError(ValueError):
    pass


@dataclass
class ConflictingVoteError(Exception):
    existing: Vote
    new: Vote


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        type_: SignedMsgType,
        val_set: ValidatorSet,
    ):
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = type_
        self.val_set = val_set
        self.votes: list[Vote | None] = [None] * len(val_set)
        self.votes_bit_array = BitArray(len(val_set))
        self.sum = 0
        self._by_block: dict[bytes, int] = {}  # block key -> tallied power
        self._block_votes: dict[bytes, BitArray] = {}
        self.maj23: BlockID | None = None
        # peer-claimed +2/3 blocks (reference SetPeerMaj23): conflicting
        # votes for a CLAIMED block stay admissible, so a catching-up
        # node can still assemble the committed majority when an
        # equivocator's twin got tallied first and occupies the slot —
        # without this, one reordered twin wedges the laggard forever
        # (it re-rejects the committed majority's real vote as a
        # conflict on every catch-up re-serve).
        self._peer_maj23_blocks: dict[bytes, BlockID] = {}
        self._maj23_claims_by_peer: dict[str, set[bytes]] = {}
        self._maj23_votes: dict[bytes, dict[int, Vote]] = {}

    def size(self) -> int:
        return len(self.val_set)

    def add_vote(self, vote: Vote, *, verified: bool = False) -> bool:
        """Validate + add a vote. Returns True if added; raises on invalid
        votes; raises ConflictingVoteError on an equivocation (the caller
        turns it into DuplicateVoteEvidence).

        `verified=True` is the pre-verified-vote path: the pipelined
        ingest (consensus/ingest.py) already proved this exact vote's
        signature against the pubkey this set resolves for its index,
        so the apply-time re-check is skipped. Index/address identity
        and conflict detection still run unconditionally."""
        if vote is None:
            raise VoteSetError("nil vote")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.type
        ):
            raise VoteSetError(
                f"vote {vote.height}/{vote.round}/{vote.type} does not match "
                f"set {self.height}/{self.round}/{self.type}"
            )
        idx = vote.validator_index
        val = self.val_set.get_by_index(idx)
        if val is None:
            raise VoteSetError(f"no validator at index {idx}")
        if val.address != vote.validator_address:
            raise VoteSetError("validator address does not match index")

        existing = self.votes[idx]
        if existing is not None:
            if existing.block_id == vote.block_id:
                return False  # duplicate, not an error
            key = vote.block_id.key()
            if key in self._peer_maj23_blocks:
                return self._add_conflicting_maj23_vote(
                    vote, idx, val, key, verified
                )
            raise ConflictingVoteError(existing, vote)

        if not verified and not vote.verify(self.chain_id, val.pub_key):
            raise VoteSetError(f"invalid signature from validator {idx}")

        self.votes[idx] = vote
        self.votes_bit_array.set(idx, True)
        self.sum += val.voting_power
        key = vote.block_id.key()
        self._by_block[key] = self._by_block.get(key, 0) + val.voting_power
        ba = self._block_votes.setdefault(key, BitArray(len(self.val_set)))
        ba.set(idx, True)
        self._maybe_cross_maj23(key, vote.block_id)
        return True

    def _maybe_cross_maj23(self, key: bytes, block_id: BlockID) -> None:
        """Single place +2/3 crossing is decided — BOTH add paths call
        it, so conflict-admitted bucket votes are adopted into the
        canonical slots no matter which vote tipped the tally over.
        (Adopting only inside the conflict path left make_commit
        holding twins — an under-quorum commit — whenever the crossing
        vote arrived through the normal path.)"""
        if self.maj23 is not None:
            return
        total = self.val_set.total_voting_power()
        if self._by_block.get(key, 0) * 3 <= total * 2:
            return
        self.maj23 = block_id
        for i, v in self._maj23_votes.get(key, {}).items():
            cur = self.votes[i]
            if cur is not None and cur.block_id != block_id:
                self.votes[i] = v

    def set_peer_maj23_block(
        self, block_id: BlockID | None, peer_id: str = ""
    ) -> None:
        """A peer claims +2/3 voted `block_id` (reference vote_set.go
        SetPeerMaj23): record the block so conflicting votes for it
        become admissible (see `_add_conflicting_maj23_vote`). Bounded
        PER PEER (reference keys claims by peer): a lying peer can burn
        only its own two slots — it cannot exhaust a shared table and
        crowd out an honest donor's claim for the real committed block.
        A claim changes nothing until +2/3 of real signatures arrive."""
        if block_id is None or block_id.is_nil():
            return
        key = block_id.key()
        if key in self._peer_maj23_blocks:
            return
        claims = self._maj23_claims_by_peer.setdefault(peer_id, set())
        if len(claims) >= 2:
            return
        claims.add(key)
        self._peer_maj23_blocks[key] = block_id

    def _add_conflicting_maj23_vote(
        self, vote: Vote, idx: int, val, key: bytes, verified: bool
    ) -> bool:
        """Admit a conflicting vote for a peer-claimed +2/3 block
        (reference vote_set.go votesByBlock): the vote counts toward
        THAT block's tally only — the canonical slot keeps its first
        vote — and when the claimed block actually crosses +2/3 the
        canonical slots adopt its votes, so `make_commit` materializes
        the real committed majority, not the equivocator's twins.

        The (existing, vote) pair is NOT re-raised here: the node is
        rescuing itself with already-gossiped votes, and every node
        that tallied the pair in the other order produced the
        DuplicateVoteEvidence through the normal conflict path."""
        bucket = self._maj23_votes.setdefault(key, {})
        if idx in bucket:
            return False  # same conflicting vote again: plain duplicate
        if not verified and not vote.verify(self.chain_id, val.pub_key):
            raise VoteSetError(f"invalid signature from validator {idx}")
        bucket[idx] = vote
        ba = self._block_votes.setdefault(key, BitArray(len(self.val_set)))
        if not ba.get(idx):
            ba.set(idx, True)
            self._by_block[key] = self._by_block.get(key, 0) + val.voting_power
        self._maybe_cross_maj23(key, self._peer_maj23_blocks[key])
        return True

    def get_vote(self, idx: int) -> Vote | None:
        if 0 <= idx < len(self.votes):
            return self.votes[idx]
        return None

    def two_thirds_majority(self) -> BlockID | None:
        return self.maj23

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def has_two_thirds_any(self) -> bool:
        return self.sum * 3 > self.val_set.total_voting_power() * 2

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        ba = self._block_votes.get(block_id.key())
        return ba.copy() if ba is not None else None

    def make_commit(self) -> "Commit":
        """Materialize a Commit once a block has +2/3 precommits
        (reference types/vote_set.go MakeCommit)."""
        from .block import Commit, CommitSig

        if self.type != SignedMsgType.PRECOMMIT:
            raise VoteSetError("commit requires precommits")
        if self.maj23 is None or self.maj23.is_nil():
            raise VoteSetError("no +2/3 majority for a block")
        sigs = []
        for i, vote in enumerate(self.votes):
            if vote is None:
                sigs.append(CommitSig.absent())
            elif vote.block_id == self.maj23:
                sigs.append(
                    CommitSig.for_block(
                        vote.validator_address, vote.timestamp_ns, vote.signature
                    )
                )
            elif vote.is_nil():
                sigs.append(
                    CommitSig.for_nil(
                        vote.validator_address, vote.timestamp_ns, vote.signature
                    )
                )
            else:
                # vote for a different block: recorded as absent in the commit
                sigs.append(CommitSig.absent())
        return Commit(self.height, self.round, self.maj23, tuple(sigs))
