"""Application interface (reference abci/types/application.go:11-31).

Applications are synchronous objects; the client layer serializes access
and presents an async interface to the node. BaseApplication provides
no-op defaults so apps override only what they need."""

from __future__ import annotations

from . import types as abci


class Application:
    """The state-transition machine replicated by consensus."""

    # info/query connection
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    # mempool connection
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    # consensus connection
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    # snapshot connection (state sync)
    def list_snapshots(self) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError


class BaseApplication(Application):
    """No-op defaults (reference abci/types/application.go BaseApplication)."""

    def info(self, req):
        return abci.ResponseInfo()

    def query(self, req):
        return abci.ResponseQuery()

    def check_tx(self, req):
        return abci.ResponseCheckTx()

    def init_chain(self, req):
        return abci.ResponseInitChain()

    def begin_block(self, req):
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req):
        return abci.ResponseDeliverTx()

    def end_block(self, req):
        return abci.ResponseEndBlock()

    def commit(self):
        return abci.ResponseCommit()

    def list_snapshots(self):
        return abci.ResponseListSnapshots()

    def offer_snapshot(self, req):
        return abci.ResponseOfferSnapshot(abci.OfferSnapshotResult.ABORT)

    def load_snapshot_chunk(self, req):
        return abci.ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req):
        return abci.ResponseApplySnapshotChunk(abci.ApplySnapshotChunkResult.ABORT)
