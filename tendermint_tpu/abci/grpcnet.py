"""gRPC ABCI transport (reference abci/client/grpc_client.go:1 and
abci/server/grpc_server.go:1) — the second first-class way to attach an
out-of-process app.

Uses grpc.aio with GENERIC method handlers: the method table and the
dataclass codec are shared with the socket transport (socket.py), so the
two attachment modes cannot drift apart. No protoc codegen — the payload
codec is the framework's own deterministic dataclass JSON (the reference
generates stubs from abci/types.proto; here the registry in socket.py is
the schema).

Unlike the socket transport (strict pipelining on one connection), gRPC
multiplexes; app access is serialized server-side with one lock, which is
the same guarantee the reference's grpc server gives via the app mutex.
"""

from __future__ import annotations

import asyncio
import json
import logging

import grpc

from .application import Application
from .client import Client
from .socket import _METHODS, _from_jsonable, _to_jsonable

SERVICE = "tendermint.abci.ABCI"


def _dumps(obj) -> bytes:
    # envelope dict: grpc.aio silently coerces bare-str messages to bytes
    # BEFORE the serializer runs, so payloads must never be naked strings
    return json.dumps({"v": _to_jsonable(obj)}).encode()


def _loads(data: bytes):
    return _from_jsonable(json.loads(data)["v"]) if data else None


class GrpcABCIServer:
    """Serves a local Application over gRPC (reference
    abci/server/grpc_server.go)."""

    def __init__(self, app: Application, *, logger: logging.Logger | None = None):
        self.app = app
        self.logger = logger or logging.getLogger("abci.grpc")
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None
        self._lock = asyncio.Lock()

    def _handler(self, method: str, has_req: bool):
        async def handle(request, context):
            if method == "echo":
                # grpc.aio coerces bare-str RESPONSES to bytes before the
                # serializer — wrap in a message dict (reference
                # ResponseEcho{message}); the client unwraps
                return {"message": request}
            fn = getattr(self.app, method)
            async with self._lock:
                return fn(request) if has_req else fn()

        return handle

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._server = grpc.aio.server()
        handlers = {
            method: grpc.unary_unary_rpc_method_handler(
                self._handler(method, has_req),
                request_deserializer=_loads,
                response_serializer=_dumps,
            )
            for method, has_req in _METHODS.items()
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        await self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)


class GrpcClient(Client):
    """ABCI client over gRPC (reference abci/client/grpc_client.go).
    Concurrency is the channel's — no client-side pipelining needed."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._channel: grpc.aio.Channel | None = None
        self._stubs: dict[str, object] = {}

    async def start(self) -> None:
        self._channel = grpc.aio.insecure_channel(f"{self.host}:{self.port}")
        for method in _METHODS:
            self._stubs[method] = self._channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=_dumps,
                response_deserializer=_loads,
            )

    async def stop(self) -> None:
        if self._channel is not None:
            await self._channel.close()

    async def _call(self, method: str, req=None):
        return await self._stubs[method](req)

    async def echo(self, msg: str) -> str:
        return (await self._call("echo", msg))["message"]

    async def info(self, req):
        return await self._call("info", req)

    async def query(self, req):
        return await self._call("query", req)

    async def check_tx(self, req):
        return await self._call("check_tx", req)

    async def init_chain(self, req):
        return await self._call("init_chain", req)

    async def begin_block(self, req):
        return await self._call("begin_block", req)

    async def deliver_tx(self, req):
        return await self._call("deliver_tx", req)

    async def end_block(self, req):
        return await self._call("end_block", req)

    async def commit(self):
        return await self._call("commit")

    async def list_snapshots(self):
        return await self._call("list_snapshots")

    async def offer_snapshot(self, req):
        return await self._call("offer_snapshot", req)

    async def load_snapshot_chunk(self, req):
        return await self._call("load_snapshot_chunk", req)

    async def apply_snapshot_chunk(self, req):
        return await self._call("apply_snapshot_chunk", req)
