"""ABCI request/response types (reference abci/types, proto/tendermint/abci).

The reference's 0.34-line ABCI surface: Info/InitChain/Query/CheckTx +
BeginBlock/DeliverTx/EndBlock/Commit + the four snapshot RPCs
(reference abci/types/application.go:11-31). Dataclasses instead of
generated protobuf; encode()/decode() (libs/protoenc) is the socket wire
format for out-of-process apps.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..libs import protoenc as pe


class CheckTxType(enum.IntEnum):
    NEW = 0
    RECHECK = 1


CODE_TYPE_OK = 0

# Wire-side sanity bounds. ABCI frames usually come from the node's own
# app, but the same decoders run over the remote-socket client AND over
# the state store's durable bytes (where chaos bit-rot applies): a
# corrupt repeat-count must raise ValueError, never allocate
# (tmtlint wire-bounds; the RouterNet corrupt-frame class).
MAX_WIRE_EVENTS = 1 << 16
MAX_WIRE_EVENT_ATTRS = 1 << 16


# --------------------------------------------------------------------------
# events (reference abci/types/types.pb.go Event/EventAttribute)


@dataclass(frozen=True)
class EventAttribute:
    key: str
    value: str
    index: bool = False

    def encode(self) -> bytes:
        return (
            pe.string_field(1, self.key)
            + pe.string_field(2, self.value)
            + pe.bool_field(3, self.index)
        )

    @classmethod
    def decode(cls, data: bytes) -> "EventAttribute":
        r = pe.Reader(data)
        key = value = ""
        index = False
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                key = r.read_bytes().decode()
            elif f == 2:
                value = r.read_bytes().decode()
            elif f == 3:
                index = bool(r.read_uvarint())
            else:
                r.skip(wt)
        return cls(key, value, index)


@dataclass(frozen=True)
class Event:
    type: str
    attributes: tuple[EventAttribute, ...] = ()

    def encode(self) -> bytes:
        out = pe.string_field(1, self.type)
        for a in self.attributes:
            out += pe.message_field(2, a.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Event":
        r = pe.Reader(data)
        type_ = ""
        attrs: list[EventAttribute] = []
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                type_ = r.read_bytes().decode()
            elif f == 2:
                attrs.append(EventAttribute.decode(r.read_bytes()))
                if len(attrs) > MAX_WIRE_EVENT_ATTRS:
                    raise ValueError(
                        f"event attributes exceed {MAX_WIRE_EVENT_ATTRS}"
                    )
            else:
                r.skip(wt)
        return cls(type_, tuple(attrs))


def _encode_events(first_field: int, events: tuple[Event, ...]) -> bytes:
    return b"".join(pe.message_field(first_field, e.encode()) for e in events)


# --------------------------------------------------------------------------
# validator types crossing the ABCI boundary


@dataclass(frozen=True)
class ValidatorUpdate:
    """App-requested validator-set change (reference abci ValidatorUpdate):
    power 0 removes the validator. bls12381 additions must carry `pop`
    (proof of possession) — aggregate-commit soundness requires every
    key in the set to have proven its secret, and validator updates are
    the only post-genesis entry point (state/execution enforces it)."""

    pub_key_type: str
    pub_key: bytes
    power: int
    pop: bytes = b""

    def encode(self) -> bytes:
        out = (
            pe.string_field(1, self.pub_key_type)
            + pe.bytes_field(2, self.pub_key)
            + pe.varint_field(3, self.power)
        )
        if self.pop:
            out += pe.bytes_field(4, self.pop)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorUpdate":
        r = pe.Reader(data)
        t, pk, power, pop = "ed25519", b"", 0, b""
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                t = r.read_bytes().decode()
            elif f == 2:
                pk = r.read_bytes()
            elif f == 3:
                power = r.read_uvarint()
            elif f == 4:
                pop = r.read_bytes()
            else:
                r.skip(wt)
        return cls(t, pk, power, pop)


@dataclass(frozen=True)
class VoteInfo:
    """Who signed the last commit (reference abci VoteInfo), fed to
    BeginBlock for fee distribution / liveness tracking."""

    validator_address: bytes
    power: int
    signed_last_block: bool

    def encode(self) -> bytes:
        return (
            pe.bytes_field(1, self.validator_address)
            + pe.varint_field(2, self.power)
            + pe.bool_field(3, self.signed_last_block)
        )

    @classmethod
    def decode(cls, data: bytes) -> "VoteInfo":
        r = pe.Reader(data)
        addr, power, signed = b"", 0, False
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                addr = r.read_bytes()
            elif f == 2:
                power = r.read_uvarint()
            elif f == 3:
                signed = bool(r.read_uvarint())
            else:
                r.skip(wt)
        return cls(addr, power, signed)


@dataclass(frozen=True)
class Misbehavior:
    """Byzantine-validator report to BeginBlock (reference abci Evidence)."""

    type: str  # "duplicate_vote" | "light_client_attack"
    validator_address: bytes
    power: int
    height: int
    time_ns: int
    total_voting_power: int

    def encode(self) -> bytes:
        return (
            pe.string_field(1, self.type)
            + pe.bytes_field(2, self.validator_address)
            + pe.varint_field(3, self.power)
            + pe.varint_field(4, self.height)
            + pe.varint_field(5, self.time_ns)
            + pe.varint_field(6, self.total_voting_power)
        )

    @classmethod
    def decode(cls, data: bytes) -> "Misbehavior":
        r = pe.Reader(data)
        kw = dict(
            type="", validator_address=b"", power=0, height=0, time_ns=0,
            total_voting_power=0,
        )
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kw["type"] = r.read_bytes().decode()
            elif f == 2:
                kw["validator_address"] = r.read_bytes()
            elif f == 3:
                kw["power"] = r.read_uvarint()
            elif f == 4:
                kw["height"] = r.read_uvarint()
            elif f == 5:
                kw["time_ns"] = r.read_uvarint()
            elif f == 6:
                kw["total_voting_power"] = r.read_uvarint()
            else:
                r.skip(wt)
        return cls(**kw)


# --------------------------------------------------------------------------
# requests


@dataclass(frozen=True)
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass(frozen=True)
class RequestInitChain:
    time_ns: int
    chain_id: str
    consensus_params: object | None  # types.ConsensusParams
    validators: tuple[ValidatorUpdate, ...]
    app_state_bytes: bytes
    initial_height: int


@dataclass(frozen=True)
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass(frozen=True)
class RequestCheckTx:
    tx: bytes
    type: CheckTxType = CheckTxType.NEW


@dataclass(frozen=True)
class LastCommitInfo:
    round: int
    votes: tuple[VoteInfo, ...] = ()


@dataclass(frozen=True)
class RequestBeginBlock:
    hash: bytes
    header: object  # types.Header
    last_commit_info: LastCommitInfo
    byzantine_validators: tuple[Misbehavior, ...] = ()


@dataclass(frozen=True)
class RequestDeliverTx:
    tx: bytes


@dataclass(frozen=True)
class RequestEndBlock:
    height: int


@dataclass(frozen=True)
class Snapshot:
    """App snapshot advertisement (reference abci Snapshot)."""

    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""

    def encode(self) -> bytes:
        return (
            pe.varint_field(1, self.height)
            + pe.varint_field(2, self.format)
            + pe.varint_field(3, self.chunks)
            + pe.bytes_field(4, self.hash)
            + pe.bytes_field(5, self.metadata)
        )

    @classmethod
    def decode(cls, data: bytes) -> "Snapshot":
        r = pe.Reader(data)
        kw = dict(height=0, format=0, chunks=0, hash=b"", metadata=b"")
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kw["height"] = r.read_uvarint()
            elif f == 2:
                kw["format"] = r.read_uvarint()
            elif f == 3:
                kw["chunks"] = r.read_uvarint()
            elif f == 4:
                kw["hash"] = r.read_bytes()
            elif f == 5:
                kw["metadata"] = r.read_bytes()
            else:
                r.skip(wt)
        return cls(**kw)


@dataclass(frozen=True)
class RequestOfferSnapshot:
    snapshot: Snapshot
    app_hash: bytes


@dataclass(frozen=True)
class RequestLoadSnapshotChunk:
    height: int
    format: int
    chunk: int


@dataclass(frozen=True)
class RequestApplySnapshotChunk:
    index: int
    chunk: bytes
    sender: str = ""


# --------------------------------------------------------------------------
# responses


@dataclass(frozen=True)
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass(frozen=True)
class ResponseInitChain:
    consensus_params: object | None = None
    validators: tuple[ValidatorUpdate, ...] = ()
    app_hash: bytes = b""


@dataclass(frozen=True)
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: tuple = ()
    height: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass(frozen=True)
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: tuple[Event, ...] = ()
    codespace: str = ""
    sender: str = ""
    priority: int = 0

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass(frozen=True)
class ResponseBeginBlock:
    events: tuple[Event, ...] = ()


@dataclass(frozen=True)
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: tuple[Event, ...] = ()
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        out = pe.varint_field(1, self.code)
        out += pe.bytes_field(2, self.data)
        out += pe.string_field(3, self.log)
        out += pe.varint_field(4, self.gas_wanted)
        out += pe.varint_field(5, self.gas_used)
        out += _encode_events(6, self.events)
        out += pe.string_field(7, self.codespace)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseDeliverTx":
        r = pe.Reader(data)
        kw: dict = {}
        events: list[Event] = []
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                kw["code"] = r.read_uvarint()
            elif f == 2:
                kw["data"] = r.read_bytes()
            elif f == 3:
                kw["log"] = r.read_bytes().decode()
            elif f == 4:
                kw["gas_wanted"] = r.read_uvarint()
            elif f == 5:
                kw["gas_used"] = r.read_uvarint()
            elif f == 6:
                events.append(Event.decode(r.read_bytes()))
                if len(events) > MAX_WIRE_EVENTS:
                    raise ValueError(
                        f"deliver-tx events exceed {MAX_WIRE_EVENTS}"
                    )
            elif f == 7:
                kw["codespace"] = r.read_bytes().decode()
            else:
                r.skip(wt)
        return cls(events=tuple(events), **kw)


@dataclass(frozen=True)
class ResponseEndBlock:
    validator_updates: tuple[ValidatorUpdate, ...] = ()
    consensus_param_updates: object | None = None
    events: tuple[Event, ...] = ()


@dataclass(frozen=True)
class ResponseCommit:
    data: bytes = b""  # the app hash
    retain_height: int = 0


@dataclass(frozen=True)
class ResponseListSnapshots:
    snapshots: tuple[Snapshot, ...] = ()


class OfferSnapshotResult(enum.IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    REJECT = 3
    REJECT_FORMAT = 4
    REJECT_SENDER = 5


@dataclass(frozen=True)
class ResponseOfferSnapshot:
    result: OfferSnapshotResult = OfferSnapshotResult.UNKNOWN


@dataclass(frozen=True)
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


class ApplySnapshotChunkResult(enum.IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    RETRY = 3
    RETRY_SNAPSHOT = 4
    REJECT_SNAPSHOT = 5


@dataclass(frozen=True)
class ResponseApplySnapshotChunk:
    result: ApplySnapshotChunkResult = ApplySnapshotChunkResult.UNKNOWN
    refetch_chunks: tuple[int, ...] = ()
    reject_senders: tuple[str, ...] = ()
